"""Whole-fit fused coordinate descent: ONE XLA program per GAME fit.

The unfused ``CoordinateDescent`` dispatches one device program per bucket
solve, per scorer, and per residual update — ~24 dispatches per fit on the
bench workload. On a remote-attached TPU every *distinct program* pays a
compile + first-execution round trip (seconds each, noisy under shared
compiler load), and every *dispatch* pays RPC latency. This module traces
the entire block-coordinate-descent fit — fixed-effect L-BFGS solves,
batched per-entity Newton/Cholesky solves, scoring, and the
``summed - old + previous`` residual algebra (CoordinateDescent.scala
:442,583) — into one jitted program with a ``lax.fori_loop`` over CD
iterations, so a fit is ONE compile and ONE dispatch.

Semantics match the unfused loop exactly (pinned by
tests/test_fused_fit.py): the same ``_solve_block`` / ``_run_impl``
primitives are inlined by jit-in-jit tracing, warm starts enter as traced
table operands, and regularization weights stay traced so a config-grid
sweep (GameEstimator.scala:452-468 warm-start ladder) re-enters the SAME
executable with new lambdas.

Eligibility (``fuse_eligible``): single device (collectives stay on the
serialized unfused path), no validation-driven best-model tracking, lazy
random-effect datasets, no down-sampling (its per-iteration reseeding is
host-driven). Everything else falls back to ``CoordinateDescent``.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_tpu.algorithm.coordinate import (
    FixedEffectCoordinate,
    ModelCoordinate,
)
from photon_tpu.algorithm.coordinate_descent import (
    CoordinateDescentResult,
    CoordinateUpdateRecord,
)
from photon_tpu.algorithm.problems import (
    VarianceComputationType,
    _run_impl,
)
from photon_tpu.algorithm.random_effect import (
    RandomEffectCoordinate,
    RandomEffectTrainingStats,
    _solve_block,
)
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    _bucket_score_add,
    _passive_score_set_dense,
    _passive_score_set_sparse,
    bucket_score_parts,
    passive_raw_scores,
    score_raw_features,
)
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel

Array = jax.Array
logger = logging.getLogger(__name__)

# Program contract (audited by `python -m photon_tpu.analysis --semantic`,
# machinery in analysis/program.py): one fused-fit generation is at most
# THREE distinct compiled programs — the slab materialization, the cold
# whole-fit program, and its warm-start twin (has_init is static). A λ-grid
# config sweep must re-enter those executables; an optimizer swap, an
# iteration-count change, or a precision switch (ops/precision.py mixed
# bf16 vs the default f32) is a declared recompile (new statics/dtypes
# by design).
PROGRAM_AUDIT = dict(
    name="fused-fit",
    entry="algorithm.fused_fit.FusedFit (_mat_fn + _fit_fn)",
    builder="build_fused_fit",
    max_programs=3,
    stable_under=("lambda_grid",),
    recompiles_on=("optimizer_swap", "iteration_count", "precision"),
    hot_loop=True,
)

# Memory contract (audited by `python -m photon_tpu.analysis --memory`,
# machinery in analysis/memory.py): the expected peak-HBM of each fused
# program as a formula over the audit fixture's dims, priced against the
# static live-range walk of the traced jaxpr. Materialize is dominated
# by the packed ingest buffer's fixed 4 MiB transfer granule
# (data/pipeline._TRANSFER_GRANULE_ELEMS) plus a handful of [n] row
# vectors; the fit's live set is ~32 [n]-row working vectors per
# coordinate per sweep (the Newton/CG scan-body residency) on top of the
# design matrices. A new slab-sized buffer that none of these terms
# price fails the audit as memory-undeclared-growth.
MEMORY_AUDIT = dict(
    name="fused-fit-memory",
    entry="algorithm.fused_fit.FusedFit (_mat_fn + _fit_fn)",
    covers=("fused-fit",),
    builder="build_fused_fit_memory",
    budgets={
        "materialize": "4 * 2 ** 20 + 24 * n * wbytes",
        "fit": "iters * coords * 32 * n * wbytes + (d + du) * n * wbytes",
        "fit_warm": (
            "iters * coords * 32 * n * wbytes + (d + du) * n * wbytes"
        ),
    },
    # Declared donations the compiled HLO must actually alias. The CD
    # sweep's carry twin is probed against its lowered module; the
    # random-effect _solve_block's slab donation (positions 9/10) needs
    # a full coordinate build to lower, so it is declared here and
    # enforced at source level by the tier-1 use-after-donate rule.
    donations={
        "algorithm.coordinate_descent._sub_add_donating": (0,),
        "algorithm.random_effect._solve_block": (9, 10),
    },
    tolerance=1.5,
)

# Tier-5 numerics contract (`--numerics`, ANALYSIS.md): the fused
# materialize/fit programs are dtype-flow walked at BOTH precisions —
# the f32 variant is the control (zero bf16 lineage, zero roundings,
# budget 0) and the bf16 variant is the audited policy. The two
# suppressed cast-census rules are the policy itself, not accidents;
# each reason below names the test that pins the behavior.
NUMERICS_AUDIT = dict(
    name="fused-fit-numerics",
    entry="algorithm.fused_fit.FusedFit (_mat_fn + _fit_fn)",
    covers=("fused-fit",),
    builder="build_fused_fit_numerics",
    budgets={
        # the default path traces byte-identical pre-policy programs:
        # no narrowing casts may exist at all
        "*_f32": "0",
        # one slab storage rounding at materialization
        "materialize_bf16": "u16",
        # worst-case compounding over the sweep: each row's score cell
        # passes through at most 4 chained bf16 re-roundings per
        # coordinate per iteration (store_score + quantize + the two
        # storage-dtype casts around the bucket scorer) — the auditor's
        # chain model; measured parity (PERFORMANCE.md) sits ~100x
        # below because the roundings land on independently-stored
        # lanes, not one chained value
        "fit_bf16": "u16 * 4 * n * iters * coords",
    },
    deterministic={
        # convergence diagnostics and per-bucket results scatter with
        # .at[].set into unique destinations (entity codes within a
        # bucket are unique by construction, iteration slots are
        # distinct) — no colliding writes exist to order
        "fit_*:scatter": (
            "set-scatters write unique rows: per-bucket entity codes "
            "are unique and sorted (bucket-slab construction), "
            "diagnostic slots are distinct iteration indices"
        ),
    },
    suppress={
        "numerics-scan-recast": (
            "the bf16 score carries ARE the policy: per-coordinate "
            "score vectors are stored bf16 in the sweep carry and "
            "upcast on read (PERFORMANCE.md policy table); parity is "
            "gated per family by tests/test_precision.py"
        ),
        "numerics-cast-roundtrip": (
            "_quantize_score's f32->bf16->f32 round-trip is "
            "INTENTIONAL and idempotent: convergence checks must see "
            "exactly the value a bf16 carry will store, pinned by "
            "test_score_quantization_is_idempotent_against_storage"
        ),
    },
    tolerance=1.5,
)


class _PackedDiags:
    """All per-update diagnostic arrays of one fused fit, packed into ONE
    int32 device buffer — a host pull costs a ~100ms round trip on the
    tunneled backend, so six per-coordinate arrays would cost more than
    the fit's dispatch. Pulled lazily, once, on first diagnostic access."""

    def __init__(self, flat: Array, shapes: list[tuple]):
        self._flat = flat
        self._shapes = shapes
        self._arrays: list[np.ndarray] | None = None

    def get(self, index: int) -> np.ndarray:
        if self._arrays is None:
            flat = np.asarray(self._flat)
            self._arrays = []
            o = 0
            for shape in self._shapes:
                size = int(np.prod(shape))
                self._arrays.append(flat[o:o + size].reshape(shape))
                o += size
            self._flat = None
        return self._arrays[index]


class FusedFixedEffectStats:
    """Per-update fixed-effect diagnostics from the fused program.

    Mirrors the OptimizationResult attributes the reporting/bench layer
    reads (iterations, convergence_reason); values pull lazily through the
    packed diagnostics buffer."""

    def __init__(self, packed: _PackedDiags, it_index: int, rs_index: int,
                 iteration: int):
        self._packed = packed
        self._it_index = it_index
        self._rs_index = rs_index
        self._iteration = iteration

    @property
    def iterations(self) -> int:
        return int(self._packed.get(self._it_index)[self._iteration])

    @property
    def convergence_reason(self) -> int:
        return int(self._packed.get(self._rs_index)[self._iteration])


def fuse_ineligibility_reasons(
    coords: dict[str, object],
    *,
    mesh=None,
    emitter=None,
) -> list[str]:
    """Every reason this coordinate structure cannot ride the fused fit.

    Empty list == eligible. ``fuse_eligible`` is this predicate on the
    per-coordinate reasons alone; the estimator's program cache
    (``GameEstimator._fused_for``) passes its mesh/listener state in too,
    and the semantic auditor's sharding report uses the same call to
    state *why* the mesh path is unfused today, not just that it is
    (analysis/program.py build_mesh_sharding).
    """
    reasons: list[str] = []
    if mesh is not None:
        reasons.append(
            "mesh execution: fusing would fold every coordinate's "
            "collectives into one program with no host serialization "
            "point between them — the unfused path serializes "
            "collective-bearing dispatches on CPU meshes "
            "(coordinate_descent._serialize_on_cpu_mesh) and keeps "
            "per-bucket programs independently shardable")
    if emitter is not None:
        reasons.append(
            "listeners: per-update events need a host boundary after "
            "each coordinate update; the fused program has none until "
            "the whole fit completes")
    for cid, coord in coords.items():
        if isinstance(coord, ModelCoordinate):
            continue
        inner = getattr(coord, "inner", coord)
        if isinstance(inner, FixedEffectCoordinate):
            rate = inner.config.down_sampling_rate
            if 0.0 < rate < 1.0:
                reasons.append(
                    f"coordinate {cid!r}: down-sampling reseeds per "
                    "iteration on host")
            if inner.config.optimizer.box_constraints is not None:
                reasons.append(
                    f"coordinate {cid!r}: box constraints run the "
                    "untraced solver path (constraint arrays would bake "
                    "in as trace constants)")
            if (inner.logical_rows is not None
                    and inner.batch.num_samples != inner.logical_rows):
                reasons.append(
                    f"coordinate {cid!r}: padded mesh batch "
                    "(num_samples != logical_rows) stays unfused")
            if getattr(inner.batch.features, "logical_d", None) is not None:
                reasons.append(
                    f"coordinate {cid!r}: column-sharded features solve "
                    "on the mesh path")
        elif isinstance(inner, RandomEffectCoordinate):
            if not inner.dataset.is_lazy:
                reasons.append(
                    f"coordinate {cid!r}: materialized score tables ride "
                    "the legacy scoring path")
        else:
            reasons.append(
                f"coordinate {cid!r}: unknown coordinate type "
                f"{type(inner).__name__}")
    return reasons


def fuse_eligible(coords: dict[str, object]) -> bool:
    """True when every coordinate can ride the single-program fit."""
    return not fuse_ineligibility_reasons(coords)


def _re_statics(coord: RandomEffectCoordinate) -> dict:
    """Static solver routing for one RE coordinate (mirrors
    RandomEffectCoordinate._dispatch_block's well-posedness analysis)."""
    from photon_tpu.types import TaskType

    cfg = coord.config
    well_posed = (
        cfg.l1_weight == 0.0
        and cfg.l2_weight > 0.0
        and cfg.optimizer.box_constraints is None
        and (coord.prior is None or cfg.incremental_weight > 0.0)
    )
    direct = well_posed and coord.task == TaskType.LINEAR_REGRESSION
    newton = well_posed and coord.task in (
        TaskType.LOGISTIC_REGRESSION, TaskType.POISSON_REGRESSION
    )
    return dict(
        task=coord.task,
        opt_config=cfg.optimizer,
        use_owlqn=cfg.l1_weight != 0.0,
        variance_computation=cfg.variance_computation,
        direct=direct,
        newton=newton,
    )


def fused_static_key(coords: dict, seq: list[str], num_iterations: int,
                     locked: set[str],
                     precision: str = "float32") -> tuple:
    """Hashable descriptor of everything baked into the fused trace.

    Initial models are NOT part of the key: warm-start tables are always
    operands (zeros when absent), so their presence never changes the
    traced structure. ``precision`` IS part of the key — the declared
    mixed-precision recompile trigger (slab/score dtypes change)."""
    from photon_tpu.ops import precision as precision_mod

    parts: list = [
        tuple(seq), num_iterations, tuple(sorted(locked)),
        precision_mod.resolve(precision),
    ]
    for cid in seq:
        coord = coords[cid]
        if isinstance(coord, ModelCoordinate):
            parts.append((cid, "locked"))
            continue
        inner = getattr(coord, "inner", coord)
        if isinstance(inner, FixedEffectCoordinate):
            cfg = inner.config
            parts.append((
                cid, "fixed", inner.problem.task, cfg.optimizer,
                cfg.l1_weight != 0.0, cfg.variance_computation,
                inner.problem.intercept_index,
                inner.problem.prior is not None,
                inner.problem.normalization.factors is not None,
                inner.problem.normalization.shifts is not None,
                inner.batch.num_samples, inner.batch.num_features,
            ))
        else:
            ds = inner.dataset
            st = _re_statics(inner)
            parts.append((
                cid, "random", st["task"], st["opt_config"],
                st["use_owlqn"], st["variance_computation"], st["direct"],
                st["newton"], inner.prior is not None,
                inner.normalization.factors is not None,
                inner.normalization.shifts is not None,
                ds.num_entities, ds.max_sub_dim,
                tuple(
                    (b.row_ids.shape, b.proj.shape) for b in ds.blocks
                ),
            ))
    return tuple(parts)


class FusedFit:
    """One estimator-generation's compiled whole-fit program.

    Built from a coords dict (the first config's); ``run`` re-assembles
    traced operands from the CURRENT coords, so later configs in a grid
    (same structure, new lambdas) reuse the compiled executable.
    """

    def __init__(
        self,
        coords: dict[str, object],
        update_sequence: list[str],
        num_iterations: int,
        locked_coordinates: set[str] | None = None,
        mat_share: dict | None = None,
        precision: str = "float32",
    ):
        from photon_tpu.ops import precision as precision_mod

        self.seq = list(update_sequence)
        self.num_iterations = num_iterations
        self.locked = set(locked_coordinates or ())
        # Mixed-precision policy (ops/precision.py): "bfloat16" stores
        # the materialized slabs AND the per-coordinate score carries in
        # bf16 (the two dominant per-sweep HBM reads), with f32
        # accumulators for every row-crossing sum; "float32" (default)
        # traces the historical program. Part of fused_static_key — the
        # declared `precision` recompile family.
        self.precision = precision_mod.resolve(precision)
        self.kinds: dict[str, str] = {}
        self._re_meta: dict[str, dict] = {}
        for cid in self.seq:
            coord = coords[cid]
            if isinstance(coord, ModelCoordinate) or cid in self.locked:
                self.kinds[cid] = "locked"
                continue
            inner = getattr(coord, "inner", coord)
            if isinstance(inner, FixedEffectCoordinate):
                self.kinds[cid] = "fixed"
            else:
                self.kinds[cid] = "random"
                ds = inner.dataset
                keep = np.zeros(ds.num_entities, bool)
                for codes in ds.block_codes_np:
                    real = codes[codes < ds.num_entities]
                    keep[real] = True
                _, passive = ds.covered_row_partition()
                # Packed-plan layout: (element offset, shape) per plan
                # array inside the ingest's single packed device buffer,
                # so the materialization program can slice them IN-TRACE
                # (no split program, no per-shape transfers). The layout
                # contract is the view's static_slices() — None for the
                # non-packed fallback.
                pv = ds.packed_view
                slices = buf = None
                if pv is not None:
                    slices = pv.static_slices()
                    buf = pv.buffer if slices is not None else None
                self._re_meta[cid] = {
                    "keep": keep,
                    "passive": passive if passive.size else None,
                    "slices": slices,
                    "buf": buf,
                    "n_blocks": len(ds.blocks),
                }
        # FE normalization contexts ride as trace-time constants: the
        # factor/shift arrays are tiny [d] vectors fixed per estimator
        # generation, and embedding them keeps _run_impl's static
        # specialization (None factors -> raw fast path) intact.
        self._norms = []
        for cid in self.seq:
            inner = getattr(coords[cid], "inner", coords[cid])
            self._norms.append(
                inner.problem.normalization
                if isinstance(inner, FixedEffectCoordinate) else None
            )
        self._jit = jax.jit(self._fit_fn, static_argnames=("statics",))
        # Slab materialization runs ONCE per dataset generation as its own
        # single program (every bucket of every RE coordinate together,
        # including the in-trace unpacking of the ingest's packed plan
        # buffer); its outputs feed the fit program as plain operands.
        # Folding it into the fit would re-gather ~0.4s of slabs on every
        # repeated fit; leaving it per-bucket (the unfused device_blocks()
        # path) costs one compile round trip per bucket on a remote
        # backend.
        self._mat_jit = jax.jit(self._mat_fn)
        self._mat_cache: dict | None = None
        # Optional slab share across FusedFit instances (passed by the
        # estimator's program cache): the materialized slabs depend only
        # on the coordinate/dataset structure — identical for every
        # static-key variant of one estimator generation — so cached
        # sibling programs must reference ONE copy, not pin one per
        # optimizer config.
        self._mat_shared = mat_share
        # Zero warm-start tables, created once per generation: an eager
        # jnp.zeros([100k, S]) costs a ~250ms device round trip on the
        # tunneled backend, which would otherwise recur on every fit.
        self._zeros_cache: dict[tuple, Array] = {}
        self.static_key = None  # set by the estimator cache
        # Ingest pipeline's overlapped AOT compile: the estimator attaches
        # the background warm-compile future; run() consumes it — the
        # compiled materialize/fit executables are used directly when the
        # static key and operand avals match, else the normal jit path.
        self._aot_future = None
        self._aot: dict | None = None
        # Statics tuples already executed through the jit fallback: the
        # FIRST such call traces (and possibly compiles) INSIDE the
        # telemetry attribution window, so that window is not pure fit
        # execution and must not be attributed to coordinate records.
        self._jit_seen: set[tuple] = set()

    # ------------------------------------------------------------------
    # operand assembly (per run; cheap)
    # ------------------------------------------------------------------

    def _mat_fn(self, mat_ops: dict):
        """Unpack plan arrays + materialize every bucket slab, traced.

        Per RE coordinate: slice the packed ingest buffer into the plan
        arrays (static offsets — free in-trace), rebuild the BlockPlans,
        gather the [B, R, S] slabs, and emit (EntityBlocks, scoring plan
        arrays, projector table) — everything later fits consume."""
        from photon_tpu.data.random_effect import (
            PLAN_ARRAYS_PER_BUCKET as _PPB,
            BlockPlan,
            packed_len_with_score_inv,
            packed_proj_index,
            packed_score_inv_index,
        )

        out = {}
        for cid, op in mat_ops.items():
            meta = self._re_meta[cid]
            if "buf" in op:
                arrays = []
                for off, shape in meta["slices"]:
                    n = int(np.prod(shape)) if shape else 1
                    arrays.append(
                        jax.lax.slice_in_dim(
                            op["buf"], off, off + n).reshape(shape)
                    )
                plans = [
                    BlockPlan(
                        entity_codes=arrays[_PPB * i],
                        row_ids=arrays[_PPB * i + 1],
                        row_counts=arrays[_PPB * i + 2],
                        proj=arrays[_PPB * i + 3],
                        intercept_slots=arrays[_PPB * i + 4],
                        raw=op["raw"],
                        raw_labels=op["labels"],
                        raw_offsets=op["offsets"],
                        raw_weights=op["weights"],
                    )
                    for i in range(meta["n_blocks"])
                ]
                # Layout contract (build_random_effect_dataset): the
                # projector sits at 5*n_blocks; trailing arrays (the
                # score map) come AFTER it — arrays[-1] would pick those.
                proj_dev = arrays[packed_proj_index(meta["n_blocks"])]
            else:
                plans = list(op["plans"])
                proj_dev = op["proj_dev"]
            from photon_tpu.ops import precision as precision_mod

            # bf16 slab storage (mixed precision): the gather happens
            # once per dataset generation, so the cast is amortized —
            # every later sweep reads the slab at half HBM width.
            ebs = tuple(
                dataclasses.replace(
                    eb,
                    x_values=precision_mod.in_storage(
                        eb.x_values, self.precision),
                )
                for eb in (p.materialize(None) for p in plans)
            )
            out[cid] = {
                "ebs": ebs,
                "score_plans": tuple(
                    (p.row_ids, p.row_counts, p.entity_codes)
                    for p in plans
                ),
                "proj_dev": proj_dev,
                # Inverse score map (row -> flat bucket/passive score
                # position): present on packed layouts with the extra
                # trailing array; enables the gather-based scorer.
                "score_inv": (
                    arrays[packed_score_inv_index(meta["n_blocks"])]
                    if "buf" in op
                    and len(meta["slices"])
                    == packed_len_with_score_inv(meta["n_blocks"])
                    else None
                ),
            }
        return out

    def _zeros(self, shape, dtype) -> Array:
        key = (shape, jnp.dtype(dtype).name)
        z = self._zeros_cache.get(key)
        if z is None:
            z = jnp.zeros(shape, dtype)
            self._zeros_cache[key] = z
        return z

    def _operands(self, coords, initial_models):
        ops = []
        for cid in self.seq:
            coord = coords[cid]
            kind = self.kinds[cid]
            if kind == "locked":
                # Locked (partial-retrain) coordinates are score-only;
                # their model comes from initial_models exactly as in the
                # unfused CoordinateDescent (locked ids must come with a
                # model). Scoring runs eagerly — once per run, through the
                # coordinate's own jitted scorer.
                if isinstance(coord, ModelCoordinate):
                    z = coord.score()
                else:
                    if not initial_models or cid not in initial_models:
                        raise KeyError(
                            f"locked coordinate {cid!r} requires a model "
                            "in initial_models "
                            "(partialRetrainLockedCoordinates)")
                    z = coord.score(initial_models[cid])
                ops.append({"z": z})
                continue
            inner = getattr(coord, "inner", coord)
            if kind == "fixed":
                dtype = inner.batch.labels.dtype
                d = inner.batch.num_features
                init = None
                if initial_models and cid in initial_models:
                    m = initial_models[cid]
                    glm = m.model if hasattr(m, "model") else m
                    # padded_to covers models loaded with fewer features
                    # than the batch (the unfused FixedEffectCoordinate
                    # .train does the same before solving).
                    init = jnp.asarray(
                        glm.coefficients.padded_to(d).means, dtype=dtype)
                prior = None
                if inner.problem.prior is not None:
                    p = inner.problem.prior.padded_to(d)
                    prior = (jnp.asarray(p.means, dtype=dtype),
                             jnp.asarray(p.variances, dtype=dtype))
                cfg = inner.config
                ops.append({
                    "batch": inner.batch,
                    "w0": (init if init is not None
                           else self._zeros((d,), dtype)),
                    "l1": np.asarray(cfg.l1_weight, dtype=dtype),
                    "l2": np.asarray(cfg.l2_weight, dtype=dtype),
                    "iw": np.asarray(cfg.incremental_weight, dtype=dtype),
                    "prior": prior,
                })
            else:
                ds = inner.dataset
                dtype = jnp.dtype(ds.dtype)
                w0 = None
                if initial_models and cid in initial_models:
                    w0 = initial_models[cid].coefficients
                cfg = inner.config
                prior = None
                if inner.prior is not None:
                    prior = (inner.prior.coefficients,
                             inner.prior.variances)
                meta = self._re_meta[cid]
                ops.append({
                    "w0": (w0 if w0 is not None else self._zeros(
                        (ds.num_entities, ds.max_sub_dim), dtype)),
                    "l1": np.asarray(cfg.l1_weight, dtype=dtype),
                    "l2": np.asarray(cfg.l2_weight, dtype=dtype),
                    "iw": np.asarray(cfg.incremental_weight, dtype=dtype),
                    "prior": prior,
                    "factors": inner.normalization.factors,
                    "shifts": inner.normalization.shifts,
                    "score_codes": ds.score_codes,
                    "raw": ds.raw,
                    "passive": (None if meta["passive"] is None
                                else jnp.asarray(meta["passive"])),
                })
        return tuple(ops)

    def _mat_operands(self, coords) -> dict:
        mat_ops = {}
        for cid in self.seq:
            if self.kinds[cid] != "random":
                continue
            inner = getattr(coords[cid], "inner", coords[cid])
            ds = inner.dataset
            meta = self._re_meta[cid]
            if meta["slices"] is not None and ds.blocks:
                b0 = ds.blocks[0]
                mat_ops[cid] = {
                    "buf": meta["buf"],
                    "raw": ds.raw,
                    "labels": b0.raw_labels,
                    "offsets": b0.raw_offsets,
                    "weights": b0.raw_weights,
                }
            else:
                mat_ops[cid] = {
                    "plans": ds.device_plans(),
                    "proj_dev": ds.proj_device(),
                }
        return mat_ops

    def _statics(self, coords, initial_models) -> tuple:
        st = []
        for cid in self.seq:
            kind = self.kinds[cid]
            # has_init gates the in-program scoring of the warm-start
            # tables: scoring all-zero tables would waste passes on every
            # cold fit (trailing element, read as st[-1]).
            has_init = bool(initial_models and cid in initial_models)
            if kind == "locked":
                st.append(("locked",))
                continue
            inner = getattr(coords[cid], "inner", coords[cid])
            if kind == "fixed":
                cfg = inner.config
                st.append((
                    "fixed", inner.problem.task, cfg.optimizer,
                    cfg.l1_weight != 0.0, inner.problem.intercept_index,
                    cfg.variance_computation, has_init,
                ))
            else:
                s = _re_statics(inner)
                st.append((
                    "random", s["task"], s["opt_config"], s["use_owlqn"],
                    s["variance_computation"], s["direct"], s["newton"],
                    has_init,
                ))
        return tuple(st)

    # ------------------------------------------------------------------
    # the traced program
    # ------------------------------------------------------------------

    def _re_score(self, w, op, mat):
        """Model contribution per canonical row (active+passive), traced.

        With a packed score map this is scatter-FREE: per-bucket score
        blocks and the passive-row scores concatenate into one flat
        vector that a single gather distributes to canonical rows (a
        TPU scatter-add of the same pass measured ~4x slower). Otherwise
        mirrors models/game.py _score_via_buckets."""
        from photon_tpu.data.dataset import DenseFeatures

        n = op["score_codes"].shape[0]
        proj_dev = mat["proj_dev"]
        if any(eb.x_indices is not None for eb in mat["ebs"]):
            # ELL fallback bucket present: score straight off the raw shard.
            return score_raw_features(
                w, op["score_codes"], op["raw"], proj_dev)
        if mat.get("score_inv") is not None:
            parts = bucket_score_parts(
                w,
                tuple(eb.x_values for eb in mat["ebs"]),
                tuple(eb.entity_codes for eb in mat["ebs"]),
            )
            if op["passive"] is not None:
                parts.append(passive_raw_scores(
                    w, op["passive"], op["score_codes"], op["raw"],
                    proj_dev))
            if not parts:  # no active entities AND no passive rows
                return jnp.zeros(n, dtype=w.dtype)
            flat = jnp.concatenate(parts)
            return jnp.take(
                flat, mat["score_inv"], mode="clip").astype(w.dtype)
        z = jnp.zeros(n, dtype=w.dtype)
        for (row_ids, row_counts, codes), eb in zip(
            mat["score_plans"], mat["ebs"]
        ):
            z = _bucket_score_add(
                z, eb.x_values, row_ids, row_counts, codes, w,
            )
        if op["passive"] is not None:
            pr = op["passive"]
            if isinstance(op["raw"], DenseFeatures):
                z = _passive_score_set_dense(
                    z, pr, op["score_codes"], op["raw"].x, w, proj_dev)
            else:
                z = _passive_score_set_sparse(
                    z, pr, op["score_codes"], op["raw"].indices,
                    op["raw"].values, w, proj_dev)
        return z

    def _fe_score(self, means, batch):
        return Coefficients(means=means).compute_score(batch.features)

    def _store_score(self, z):
        """Score-carry storage cast: bf16 under mixed precision (the
        per-coordinate score vectors are re-read every sweep for the
        residual algebra — half-width storage halves that traffic), the
        identity on the default f32 path."""
        if self.precision == "bfloat16":
            return z.astype(jnp.bfloat16)
        return z

    def _quantize_score(self, z):
        """Round a fresh score through the storage dtype BEFORE it
        enters the residual total: the f32 total must equal the exact
        sum of the STORED carries, or each sweep's ``total - old``
        would leave the carry's quantization residue behind and the
        residual error would grow linearly with iteration count
        instead of staying at one rounding (bf16(f32(bf16(z))) ==
        bf16(z), so the round-trip is idempotent against the stored
        value). Returns ``z`` itself on the default f32 path."""
        if self.precision == "bfloat16":
            return z.astype(jnp.bfloat16).astype(jnp.float32)
        return z

    @staticmethod
    def _read_score(zs, dtype):
        """Upcast a stored score carry back to the f32 accumulator
        dtype (identity on the default path)."""
        return zs if zs.dtype == dtype else zs.astype(dtype)

    def _fit_fn(self, ops, ebs_all, *, statics):
        num_iters = self.num_iterations
        # Convergence telemetry rides the fit program UNCONDITIONALLY as
        # extra outputs (obs/convergence.py METRICS columns): the
        # telemetry enable flag is host-side only, so the traced program
        # — and with it the dispatch census and every recompile key — is
        # byte-identical with telemetry on or off (the audited
        # `telemetry` contract in photon_tpu/obs/__init__.py).
        conv_index = {
            i: j
            for j, i in enumerate(
                i for i, st in enumerate(statics) if st[0] != "locked"
            )
        }

        # --- initial state ------------------------------------------------
        # The running TOTAL stays in f32 (it is the accumulator every
        # residual derives from); the per-coordinate score CARRIES are
        # stored through _store_score — bf16 under mixed precision.
        states: list = []
        scores: list = []
        diags: list = []
        total = None
        for i, (op, st) in enumerate(zip(ops, statics)):
            kind = st[0]
            if kind == "locked":
                states.append(())
                z = op["z"]
                diags.append(())
            elif kind == "fixed":
                means = op["w0"]
                has_init = st[-1]
                variances = (
                    None
                    if st[5] == VarianceComputationType.NONE
                    else jnp.zeros_like(means)
                )
                states.append((means, variances))
                z = (
                    self._fe_score(means, op["batch"]) if has_init
                    else jnp.zeros(
                        op["batch"].num_samples, means.dtype)
                )
                diags.append((
                    jnp.zeros(num_iters, jnp.int32),
                    jnp.zeros(num_iters, jnp.int32),
                ))
            else:
                w_all = op["w0"]
                has_init = st[-1]
                e = w_all.shape[0]
                v_all = (
                    None
                    if st[4] == VarianceComputationType.NONE
                    else jnp.zeros_like(w_all)
                )
                states.append((w_all, v_all))
                z = (
                    self._re_score(w_all, op, ebs_all[self.seq[i]])
                    if has_init
                    else jnp.zeros(
                        op["score_codes"].shape[0], w_all.dtype)
                )
                diags.append((
                    jnp.zeros((num_iters, e), jnp.int32),
                    jnp.zeros((num_iters, e), jnp.int32),
                ))
            z = self._quantize_score(z)
            total = z if total is None else total + z
            scores.append(self._store_score(z))
        conv0 = jnp.zeros(
            (num_iters, len(conv_index), 5), dtype=total.dtype
        )

        def sweep(it, carry):
            states, scores, total, diags, conv = carry
            states = list(states)
            scores = list(scores)
            diags = list(diags)
            for i, (op, st) in enumerate(zip(ops, statics)):
                kind = st[0]
                if kind == "locked":
                    continue
                z_old = self._read_score(scores[i], total.dtype)
                residual = total - z_old
                if kind == "fixed":
                    _, task, opt_config, use_owlqn, intercept_index, \
                        var_comp = st[:6]
                    batch = op["batch"]
                    batch = batch.with_offsets(batch.offsets + residual)
                    prev_means = states[i][0]
                    means, variances, result = _run_impl(
                        batch,
                        states[i][0],
                        op["l1"], op["l2"],
                        self._fe_norm(i),
                        op["prior"],
                        op["iw"],
                        task=task,
                        opt_config=opt_config,
                        use_owlqn=use_owlqn,
                        intercept_index=intercept_index,
                        variance_computation=var_comp,
                    )
                    states[i] = (means, variances)
                    z = self._fe_score(means, op["batch"])
                    it_arr, rs_arr = diags[i]
                    diags[i] = (
                        it_arr.at[it].set(result.iterations),
                        rs_arr.at[it].set(result.convergence_reason),
                    )
                    # Solver-final objective/gradient come free from the
                    # OptResult — no extra passes over the batch.
                    conv_loss = result.value
                    conv_gnorm = result.gradient_norm
                    conv_wd = jnp.sum((means - prev_means) ** 2)
                    conv_wn = jnp.sum(means ** 2)
                else:
                    _, task, opt_config, use_owlqn, var_comp, direct, \
                        newton = st[:7]
                    w_prev, v_prev = states[i]
                    w_all = jnp.zeros_like(w_prev)
                    v_all = None if v_prev is None else jnp.zeros_like(
                        v_prev)
                    e = w_prev.shape[0]
                    its_e = jnp.zeros(e, jnp.int32)
                    rs_e = jnp.zeros(e, jnp.int32)
                    mat = ebs_all[self.seq[i]]
                    for (_, _, codes), eb in zip(
                        mat["score_plans"], mat["ebs"]
                    ):
                        w_all, v_all, its, rs = _solve_block(
                            eb,
                            residual,
                            op["factors"],
                            op["shifts"],
                            w_prev,
                            op["l1"], op["l2"], op["iw"],
                            op["prior"],
                            w_all, v_all,
                            sub_dim=eb.sub_dim,
                            task=task,
                            opt_config=opt_config,
                            use_owlqn=use_owlqn,
                            variance_computation=var_comp,
                            direct=direct,
                            newton=newton,
                            precision=self.precision,
                        )
                        its_e = its_e.at[codes].set(its)
                        rs_e = rs_e.at[codes].set(rs)
                    states[i] = (w_all, v_all)
                    z = self._re_score(w_all, op, mat)
                    it_arr, rs_arr = diags[i]
                    diags[i] = (
                        it_arr.at[it].set(its_e),
                        rs_arr.at[it].set(rs_e),
                    )
                    # The batched per-entity solvers return iteration
                    # counts, not objective values: loss/grad_norm are 0
                    # for random effects (obs/convergence.py documents
                    # the column contract); the deltas below are the
                    # convergence signal that exists for every kind.
                    conv_loss = jnp.zeros((), total.dtype)
                    conv_gnorm = jnp.zeros((), total.dtype)
                    conv_wd = jnp.sum((w_all - w_prev) ** 2)
                    conv_wn = jnp.sum(w_all ** 2)
                z = self._quantize_score(z)
                # residual_delta_sq: movement of this coordinate's score
                # contribution this sweep — computed on values the
                # residual bookkeeping already holds (no extra passes).
                conv = conv.at[it, conv_index[i]].set(
                    jnp.stack([
                        conv_loss.astype(total.dtype),
                        conv_gnorm.astype(total.dtype),
                        jnp.sum((z - z_old) ** 2).astype(total.dtype),
                        conv_wd.astype(total.dtype),
                        conv_wn.astype(total.dtype),
                    ])
                )
                total = total - z_old + z
                scores[i] = self._store_score(z)
            return tuple(states), tuple(scores), total, tuple(diags), conv

        carry = (tuple(states), tuple(scores), total, tuple(diags), conv0)
        carry = lax.fori_loop(0, num_iters, sweep, carry)
        states, scores, total, diags, conv = carry
        # Pack every diagnostic array into ONE int32 buffer: a host pull
        # costs a fixed round trip on remote backends, so one buffer beats
        # 2 x n_coordinates of them (_PackedDiags splits host-side).
        flat_parts = [
            d.reshape(-1) for pair in diags for d in pair
        ]
        packed = (
            jnp.concatenate(flat_parts) if flat_parts
            else jnp.zeros(0, jnp.int32)
        )
        return states, scores, total, packed, conv

    def _fe_norm(self, i):
        """NormalizationContext for coordinate i (host constant — factor
        arrays are tiny [d] vectors; embedding them as program constants
        is deliberate)."""
        return self._norms[i]

    def _attribute_seconds(
        self, total_seconds: float, ops, packed: _PackedDiags, diag_index
    ) -> dict[tuple[int, str], float] | None:
        """Per-(iteration, coordinate) attribution of the fit's measured
        wall — the span tracer's device-time split for fused records.

        The fit is ONE program, so per-coordinate time cannot be measured
        directly; this distributes ``total_seconds`` — the fit program's
        REAL dispatch->completion window, measured by the run span's
        root sync — proportionally to each block's analytic work estimate
        (the same counting family as bench.estimate_model_flops), using
        the MEASURED per-iteration solver counts from the packed
        diagnostics: fixed effects at iters x 4nd value/grad passes +
        scoring, random effects at mean-Newton-iters x (margins + Hessian
        contraction) over active rows + per-entity Cholesky + scoring.
        Shares sum to the measurement; they are attribution, not
        independent timings (CoordinateUpdateRecord documents the
        contract). Returns None when no work was attributable.
        """
        weights: dict[tuple[int, str], float] = {}
        for i, cid in enumerate(self.seq):
            kind = self.kinds[cid]
            if kind == "locked":
                continue
            it_idx, _ = diag_index[cid]
            iters = packed.get(it_idx)  # [T] fixed / [T, entities] random
            if kind == "fixed":
                n = ops[i]["batch"].num_samples
                d = ops[i]["batch"].num_features
                for it in range(self.num_iterations):
                    weights[(it, cid)] = (
                        (4.0 * max(float(iters[it]), 1.0) + 2.0) * n * d
                    )
            else:
                n_re = int(ops[i]["score_codes"].shape[0])
                _, s = ops[i]["w0"].shape
                # Only entities the blocks actually solve (the same keep
                # mask the diagnostics apply): phantom padded slots would
                # deflate the measured mean iteration count and inflate
                # the Cholesky term.
                keep = self._re_meta[cid]["keep"]
                kept = int(keep.sum())
                for it in range(self.num_iterations):
                    its_it = iters[it][keep] if kept else iters[it]
                    mean_it = max(
                        float(np.mean(its_it)) if its_it.size else 1.0,
                        1.0,
                    )
                    weights[(it, cid)] = (
                        mean_it * (6.0 * s + 2.0 * s * s) * n_re
                        + max(kept, 1) * s ** 3 / 3.0
                        + 2.0 * n_re * s
                    )
        total_w = sum(weights.values())
        if total_w <= 0.0:
            return None
        scale = float(total_seconds) / total_w
        return {k: v * scale for k, v in weights.items()}

    def _ledger_record(
        self, coords, sp, mat_window, t_fit0, rec_seconds, ebs_all
    ) -> None:
        """Cost-ledger accounting for one measured fit (obs/ledger.py).

        Registers the generation's two programs with LAZY static-cost
        thunks (pricing lowers at report time, never here), records the
        materialize/fit dispatch windows with per-coordinate attribution
        when the fit window was pure, accounts the slab buffers, and
        books the residual (operand assembly, AOT wait) as the explicit
        ``unattributed`` row. Only reached with telemetry on (``sp`` is
        the synced fit span — the one real measurement) and the ledger
        armed.
        """
        from photon_tpu.analysis import costmodel
        from photon_tpu.obs import ledger

        ledger.register_program(
            "materialize", phase="materialize",
            cost_thunk=lambda: costmodel.program_cost(
                self.lower_materialize(coords)),
        )
        ledger.register_program(
            "fused_fit", phase="fit",
            cost_thunk=lambda: costmodel.program_cost(
                self.lower(coords)),
        )
        # Segment-reduce kernel census rows: every instantiation the
        # tracer recorded (ops/segment_reduce._TRACED_SITES) registers
        # with its ANALYTIC cost — the kernel executes inside the fused
        # program, so it has no dispatch row of its own, but the census
        # prices its roofline next to the programs that embed it
        # (cli.profile asserts the row exists when the kernel engaged).
        from photon_tpu.ops import segment_reduce

        for site, info in segment_reduce.traced_sites().items():
            ledger.register_program(
                site, phase="score", cost=info["cost"],
            )
        mat_seconds = 0.0
        if mat_window is not None:
            t0, t1 = mat_window
            mat_seconds = t1 - t0
            ledger.record_dispatch(
                "materialize", mat_seconds, phase="materialize",
                start=t0, end=t1,
            )
            ledger.set_resident(
                "fused_fit/slabs", ledger.tree_nbytes(ebs_all)
            )
        fit_seconds = max(sp.t1 - t_fit0, 0.0)
        parts = None
        if rec_seconds:
            # Fold the per-(iteration, coordinate) attribution down to
            # per-coordinate shares; an impure window (cold fallback,
            # retried attempt) keeps parts=None and the whole fit
            # window lands as ONE measured-only row — degradation, not
            # a fabricated split.
            parts = {}
            for (_, cid), s in rec_seconds.items():
                parts[cid] = parts.get(cid, 0.0) + s
        ledger.record_dispatch(
            "fused_fit", fit_seconds, phase="fit",
            start=t_fit0, end=sp.t1, parts=parts,
        )
        ledger.record_unattributed(
            max(sp.seconds - fit_seconds - mat_seconds, 0.0)
        )

    # ------------------------------------------------------------------
    # abstract lowering (the semantic auditor / cost model entry)
    # ------------------------------------------------------------------

    def trace(self, coords, initial_models=None):
        """Abstractly trace (never execute) the whole-fit program.

        The slab-materialization outputs enter as ``jax.eval_shape``
        avals, so no gather runs. This is the ONE operand-assembly path
        the program auditor (analysis/program.py) and the static cost
        model (analysis/costmodel.py) share with ``run`` — the audited
        jaxpr is the production program by construction. Returns the
        ``jax.stages.Traced`` (``.jaxpr``, ``.lower()``).
        """
        ops = self._operands(coords, initial_models)
        statics = self._statics(coords, initial_models)
        ebs_avals = jax.eval_shape(
            self._mat_fn, self._mat_operands(coords)
        )
        return self._jit.trace(ops, ebs_avals, statics=statics)

    def lower(self, coords, initial_models=None):
        """Lower (never execute) the whole-fit program for these coords."""
        return self.trace(coords, initial_models).lower()

    def lower_materialize(self, coords):
        """Lower (never execute) the slab materialization program."""
        return self._mat_jit.lower(self._mat_operands(coords))

    def aot_lower(self, coords) -> dict:
        """Trace the materialize + cold-fit programs for AOT warm compile.

        The SAME operand assembly as ``trace``/``run`` (the audited
        ingest-pipeline contract pins that these jaxprs match the
        production generation's signatures exactly), packaged with the
        statics so the caller can key the compiled executables."""
        mat_ops = self._mat_operands(coords)
        mat_traced = self._mat_jit.trace(mat_ops)
        ebs_avals = jax.eval_shape(self._mat_fn, mat_ops)
        ops = self._operands(coords, None)
        statics = self._statics(coords, None)
        fit_traced = self._jit.trace(ops, ebs_avals, statics=statics)
        return {
            "mat_traced": mat_traced,
            "fit_traced": fit_traced,
            "statics": statics,
        }

    def _consume_aot(self) -> dict | None:
        """Resolve the pending warm-compile future (blocking if the
        compile is still running — that block is the measured
        ``compile_wait`` stage, the non-overlapped remainder) and keep
        the artifacts when they belong to this static structure."""
        fut = self._aot_future
        if fut is not None:
            from photon_tpu.data.pipeline import PIPELINE_STATS

            self._aot_future = None
            with PIPELINE_STATS.stage("compile_wait"):
                art = fut.result()
            if art is not None and art.get("key") == self.static_key:
                self._aot = art
        return self._aot

    def _run_mat(self, coords, aot):
        """Materialize slabs via the AOT executable when compatible."""
        mat_ops = self._mat_operands(coords)
        if aot is not None:
            try:
                return aot["mat"](mat_ops)
            except Exception:  # noqa: BLE001 — stale shape prediction
                logger.info(
                    "ingest pipeline: AOT materialize executable "
                    "incompatible with the built datasets; recompiling")
        return self._mat_jit(mat_ops)

    # ------------------------------------------------------------------
    # the public entry
    # ------------------------------------------------------------------

    def run(
        self,
        coords: dict[str, object],
        initial_models: dict[str, object] | None = None,
    ) -> CoordinateDescentResult:
        from photon_tpu import obs

        # The whole-fit span is the telemetry layer's device-time ROOT:
        # with telemetry enabled it syncs on the program outputs at exit
        # (the one host sync per fit, at the point the caller's first
        # blocking read would have paid anyway) so the host/device split
        # and the per-record attribution below come from a real
        # measurement. Disabled, the span is a no-op and the dispatch
        # stays fully asynchronous — the pre-telemetry behavior.
        with obs.span("fused_fit") as sp:
            ops = self._operands(coords, initial_models)
            statics = self._statics(coords, initial_models)
            aot = self._consume_aot()
            # Slabs materialize once per dataset generation (separate
            # cached program that also unpacks the ingest's packed plan
            # buffer); every fit's program receives the results as plain
            # operands. When the estimator provides a share, sibling
            # programs (other static keys of the same generation) reuse
            # the same device slabs.
            # The materialize window (cost-ledger row when armed): only
            # a run that actually gathered slabs records one — a cache
            # hit dispatched nothing.
            mat_window = None
            if self._mat_shared is not None:
                ebs_all = self._mat_shared.get("ebs")
                if ebs_all is None:
                    t_m0 = time.perf_counter()
                    ebs_all = self._mat_shared["ebs"] = self._run_mat(
                        coords, aot)
                    mat_window = (t_m0, time.perf_counter())
            else:
                if self._mat_cache is None:
                    t_m0 = time.perf_counter()
                    self._mat_cache = self._run_mat(coords, aot)
                    mat_window = (t_m0, time.perf_counter())
                ebs_all = self._mat_cache
            # The attribution window opens HERE: operand assembly, the
            # AOT compile wait, and slab materialization above are not
            # fit work and must not be charged to coordinate records.
            t_fit0 = time.perf_counter()
            fit_window_pure = True

            def dispatch_once():
                # The `fit.dispatch` injection point fires BEFORE any
                # executable is entered, so an injected transient fault
                # exercises the retry path without touching device
                # state; the retry wrapper re-runs this whole selection
                # (AOT-or-jit), which is idempotent — operands are
                # unchanged and both paths are pure dispatches.
                from photon_tpu.resilience import faults

                nonlocal fit_window_pure
                faults.check("fit.dispatch")
                res = None
                if aot is not None and statics == aot.get("statics"):
                    try:
                        res = aot["fit"](ops, ebs_all)
                    except Exception as exc:  # noqa: BLE001 — stale shape prediction
                        from photon_tpu.resilience import errors

                        if errors.is_transient(exc):
                            # A real backend fault (UNAVAILABLE /
                            # preempted), not a stale prediction: let
                            # the retry wrapper classify and re-enter —
                            # the executable is fine, dropping it would
                            # pay a jit fallback on every later fit and
                            # record zero retry stats for a real fault.
                            raise
                        logger.info(
                            "ingest pipeline: AOT fit executable "
                            "incompatible with the built datasets; "
                            "recompiling")
                        self._aot = None
                if res is None:
                    # A first jit-fallback entry traces + compiles inside
                    # the window: not pure fit execution (see _jit_seen).
                    # AND (not assign): a retried second attempt would
                    # find statics in _jit_seen and flip a window that
                    # already contained attempt 1's trace back to pure.
                    fit_window_pure = (
                        fit_window_pure and statics in self._jit_seen
                    )
                    res = self._jit(ops, ebs_all, statics=statics)
                    self._jit_seen.add(statics)
                return res

            def _mark_impure(attempt, exc):
                # Any retry puts a failed attempt + the backoff sleep
                # inside the t_fit0 window — never attribute it.
                nonlocal fit_window_pure
                fit_window_pure = False

            from photon_tpu.resilience import retry

            out = retry.call_with_retry(
                dispatch_once, site="fused_fit.dispatch",
                on_retry=_mark_impure,
            )
            states, scores, total, packed_flat, conv = out
            if sp is not None:
                sp.sync = out
        if sp is not None:
            obs.convergence.record(
                tuple(
                    cid for cid in self.seq
                    if self.kinds[cid] != "locked"
                ),
                conv,
            )
            obs.REGISTRY.counter("fused_fits_total").inc()
            obs.REGISTRY.histogram("fused_fit_wall_seconds").observe(
                sp.seconds)
            if sp.device_wait_seconds is not None:
                obs.REGISTRY.histogram(
                    "fused_fit_device_wait_seconds"
                ).observe(sp.device_wait_seconds)
        # Numerics sentinel (obs/health.py): park the SAME convergence
        # block — an output the fit program already computes — for lazy
        # non-finite scanning at gate/report time. Reference
        # bookkeeping only: no sync, no transfer, no program change
        # (the audited `health` contract), and it works with health
        # armed alone (telemetry's span sync is not required).
        if obs.health.enabled():
            obs.health.sentinel_watch(
                tuple(
                    cid for cid in self.seq
                    if self.kinds[cid] != "locked"
                ),
                conv,
            )
        # Diagnostic shapes, in the exact flattening order of _fit_fn's
        # packing; indices into _PackedDiags per coordinate.
        shapes: list[tuple] = []
        diag_index: dict[str, tuple[int, int]] = {}
        t = self.num_iterations
        for i, cid in enumerate(self.seq):
            kind = self.kinds[cid]
            if kind == "locked":
                continue
            if kind == "fixed":
                shape = (t,)
            else:
                e = ops[i]["w0"].shape[0]
                shape = (t, e)
            diag_index[cid] = (len(shapes), len(shapes) + 1)
            shapes.extend([shape, shape])
        packed = _PackedDiags(packed_flat, shapes)

        models: dict[str, object] = {}
        history: list[CoordinateUpdateRecord] = []
        # The whole descent is ONE device program here: per-coordinate
        # time is not independently measurable. With telemetry DISABLED,
        # records carry seconds=None (never a synthetic uniform split
        # consumers would read as measured). With telemetry ENABLED the
        # span above measured the fit program's real dispatch->
        # completion window (materialize/AOT-wait excluded), and each
        # record gets its analytic ATTRIBUTION of that measurement —
        # weighted by the coordinate's measured iteration counts x
        # static shape work (see _attribute_seconds and the
        # CoordinateUpdateRecord contract).
        rec_seconds = None
        if sp is not None and sp.device_wait_seconds is not None:
            # The attributed total is the FIT window only — from the fit
            # program's dispatch (t_fit0, after materialize/AOT wait) to
            # the span's post-sync completion — so compile_wait and slab
            # gathering never masquerade as per-coordinate device work.
            # A cold jit-fallback entry traces/compiles INSIDE that
            # window, so it is attributed only when pure: cold-fallback
            # records keep seconds=None (the pipeline stats report the
            # compile separately) and the span carries fit_window_pure
            # so exporters can say why.
            fit_seconds = max(sp.t1 - t_fit0, 0.0)
            if sp.attrs is None:
                sp.attrs = {}
            sp.attrs["fit_seconds"] = round(fit_seconds, 6)
            sp.attrs["fit_window_pure"] = fit_window_pure
            if fit_window_pure:
                # This forces the packed-diagnostics host pull per fit —
                # a deliberate trade against laziness: records carry a
                # plain float (frozen-dataclass API), the buffer is
                # already synced by the span root (zero-copy on CPU,
                # ~1ms DMA at bench scale on a local chip; only a
                # tunneled backend pays a latency round trip), and the
                # pull shares _PackedDiags' cache, so diagnostics
                # consumers never fetch a second time.
                rec_seconds = self._attribute_seconds(
                    fit_seconds, ops, packed, diag_index)
        from photon_tpu.obs import ledger

        if ledger.enabled() and sp is not None:
            self._ledger_record(
                coords, sp, mat_window, t_fit0, rec_seconds, ebs_all)
        for i, cid in enumerate(self.seq):
            coord = coords[cid]
            kind = self.kinds[cid]
            if kind == "locked":
                models[cid] = (
                    coord.model if isinstance(coord, ModelCoordinate)
                    else initial_models[cid]
                )
                continue
            inner = getattr(coord, "inner", coord)
            if kind == "fixed":
                means, variances = states[i]
                glm = GeneralizedLinearModel(
                    Coefficients(means=means, variances=variances),
                    inner.problem.task,
                )
                models[cid] = FixedEffectModel(
                    glm, coords[cid].feature_shard_id)
            else:
                ds = inner.dataset
                w_all, v_all = states[i]
                models[cid] = RandomEffectModel(
                    coefficients=w_all,
                    random_effect_type=ds.config.random_effect_type,
                    feature_shard_id=ds.config.feature_shard_id,
                    task=inner.task,
                    proj_all=ds.proj_all,
                    variances=v_all,
                    entity_keys=ds.entity_keys,
                )
        for it in range(self.num_iterations):
            for i, cid in enumerate(self.seq):
                kind = self.kinds[cid]
                if kind == "locked":
                    continue
                it_idx, rs_idx = diag_index[cid]
                if kind == "fixed":
                    diag = FusedFixedEffectStats(packed, it_idx, rs_idx, it)
                else:
                    keep = self._re_meta[cid]["keep"]
                    diag = RandomEffectTrainingStats.from_thunk(
                        lambda packed=packed, it_idx=it_idx,
                        rs_idx=rs_idx, it=it, keep=keep: (
                            packed.get(rs_idx)[it][keep],
                            packed.get(it_idx)[it][keep],
                        )
                    )
                history.append(CoordinateUpdateRecord(
                    iteration=it,
                    coordinate_id=cid,
                    seconds=(
                        None if rec_seconds is None
                        else rec_seconds[(it, cid)]
                    ),
                    diagnostics=diag,
                    evaluation=None,
                ))
        final = GameModel(dict(models))
        return CoordinateDescentResult(
            model=final,
            best_model=final,
            best_evaluation=None,
            history=tuple(history),
        )
