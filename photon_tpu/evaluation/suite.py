"""EvaluationSuite: bundle of evaluators over one validation dataset.

TPU-native counterpart of photon-lib evaluation/EvaluationSuite.scala:59-90
and EvaluationResults.scala. The reference left-joins label/offset/weight
RDDs with score RDDs; here validation rows live in fixed canonical order, so
evaluation is elementwise: evaluated score = model score + offset
(EvaluationSuite.scala:62-66).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.evaluation.evaluators import (
    EvaluatorSpec,
    evaluate_at_threshold,
    evaluate_single,
    grouped_auc,
    grouped_auc_per_group,
    grouped_precision_at_k,
    grouped_precision_at_k_per_group,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class EvaluationResults:
    """Reference: evaluation/EvaluationResults.scala."""

    evaluations: dict[str, float]
    primary_evaluator: EvaluatorSpec

    @property
    def primary_evaluation(self) -> float:
        return self.evaluations[self.primary_evaluator.name]


@dataclasses.dataclass(frozen=True)
class EvaluationSuite:
    """Evaluators + the validation data columns they run against.

    ``group_ids`` maps an id tag name (e.g. "queryId") to integer group codes
    aligned with the label rows; tags are produced by ingest (the reference
    extracts them from GameDatum.idTagToValueMap).
    The first spec is the primary evaluator used for model selection
    (EvaluationSuite primaryEvaluator).
    """

    specs: tuple[EvaluatorSpec, ...]
    labels: Array
    offsets: Array
    weights: Array
    group_ids: dict[str, tuple[Array, int]] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if not self.specs:
            raise ValueError("EvaluationSuite needs at least one evaluator")
        for spec in self.specs:
            if spec.group_tag is not None and spec.group_tag not in self.group_ids:
                raise ValueError(
                    f"evaluator {spec.name} needs id tag {spec.group_tag!r}, "
                    f"got {sorted(self.group_ids)}")

    @property
    def primary(self) -> EvaluatorSpec:
        return self.specs[0]

    def evaluate(self, scores: Array) -> EvaluationResults:
        z = scores + self.offsets
        out: dict[str, float] = {}
        for spec in self.specs:
            if spec.threshold_metric is not None:
                out[spec.name] = float(evaluate_at_threshold(
                    spec.threshold_metric, z, self.labels, spec.threshold,
                    self.weights))
                continue
            if spec.group_tag is not None:
                codes, num_groups = self.group_ids[spec.group_tag]
                if spec.precision_k is not None:
                    val = grouped_precision_at_k(
                        z, self.labels, codes, num_groups, spec.precision_k)
                else:
                    assert spec.evaluator_type is not None
                    if spec.evaluator_type.value != "AUC":
                        raise NotImplementedError(
                            f"grouped {spec.evaluator_type} not supported "
                            "as a summary metric (reference MultiEvaluator "
                            "supports AUC and precision@k); for per-group "
                            "values of the supported metrics use "
                            "EvaluationSuite.evaluate_per_group")
                    val = grouped_auc(z, self.labels, codes, num_groups,
                                      self.weights)
            else:
                assert spec.evaluator_type is not None
                val = evaluate_single(spec.evaluator_type, z, self.labels,
                                      self.weights)
            out[spec.name] = float(val)
        return EvaluationResults(out, self.primary)

    def evaluate_per_group(self, scores: Array) -> dict[str, np.ndarray]:
        """Per-group metric values for every grouped evaluator.

        Returns metric name -> [num_groups] float array with NaN for groups
        the metric is undefined on (single-class AUC groups) — the values
        behind the driver's per-group evaluation output
        (GameTrainingDriver.savePerGroupEvaluationToHDFS :878-901).
        """
        z = scores + self.offsets
        out: dict[str, np.ndarray] = {}
        for spec in self.specs:
            if spec.group_tag is None:
                continue
            codes, num_groups = self.group_ids[spec.group_tag]
            if spec.precision_k is not None:
                vals, valid = grouped_precision_at_k_per_group(
                    z, self.labels, codes, num_groups, spec.precision_k)
            else:
                assert spec.evaluator_type is not None
                if spec.evaluator_type.value != "AUC":
                    raise NotImplementedError(
                        f"grouped {spec.evaluator_type} not supported: "
                        "evaluate_per_group implements AUC and "
                        "precision@k only (reference MultiEvaluator's "
                        "grouped metric set)")
                vals, valid = grouped_auc_per_group(
                    z, self.labels, codes, num_groups, self.weights)
            out[spec.name] = np.where(
                np.asarray(valid), np.asarray(vals), np.nan)
        return out


def make_suite(
    specs: list[str | EvaluatorSpec],
    labels,
    offsets=None,
    weights=None,
    group_ids: dict[str, tuple[Array, int]] | None = None,
    # Deliberate: under default x64-disabled JAX this resolves to float32
    # (matching the training pipeline); when a debugging run enables x64,
    # evaluation accumulations get full precision for free.
    dtype=jnp.float64,  # photon: ignore[float64-literal] -- intended x64 opt-in; f32 under default config
) -> EvaluationSuite:
    labels = jnp.asarray(labels, dtype=dtype)
    n = labels.shape[0]
    parsed = tuple(
        s if isinstance(s, EvaluatorSpec) else EvaluatorSpec.parse(s)
        for s in specs
    )
    return EvaluationSuite(
        specs=parsed,
        labels=labels,
        offsets=jnp.zeros(n, dtype) if offsets is None else jnp.asarray(offsets, dtype),
        weights=jnp.ones(n, dtype) if weights is None else jnp.asarray(weights, dtype),
        group_ids=group_ids or {},
    )


def encode_group_ids(raw_ids) -> tuple[Array, int, dict]:
    """Host-side: map arbitrary group keys to dense int codes.

    Returns (codes [n] int32, num_groups, key->code vocab).
    """
    raw = np.asarray(raw_ids)
    uniq, codes = np.unique(raw, return_inverse=True)
    vocab = {k.item() if hasattr(k, "item") else k: i for i, k in enumerate(uniq)}
    return jnp.asarray(codes.astype(np.int32)), len(uniq), vocab
