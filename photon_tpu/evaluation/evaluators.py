"""Evaluation metrics as sort/segment-sum device kernels.

TPU-native counterpart of the reference's evaluation framework:
``EvaluatorType`` (photon-lib evaluation/EvaluatorType.scala:59-65),
``SingleEvaluator`` implementations (photon-api evaluation/*Evaluator.scala),
the weighted tie-aware local AUC (AreaUnderROCCurveLocalEvaluator.scala:72),
``PrecisionAtKLocalEvaluator`` (:76) and the grouped ``MultiEvaluator``
(photon-lib evaluation/MultiEvaluator.scala:36: per-group metric, NaN/Inf
groups dropped, unweighted mean over groups).

The RDD groupBy/sort machinery becomes one lexsort plus ``segment_sum``
passes, so a grouped AUC over millions of rows is a handful of fused XLA ops
instead of a shuffle.

Reference formula quirks preserved deliberately (documented for parity):
- loss evaluators return the weighted SUM of pointwise losses, not a mean
  (LogisticLossEvaluator.scala et al.);
- SQUARED_LOSS is sum(w * (s-y)^2): the pointwise loss's convenience 1/2 is
  undone by the evaluator (SquaredLossEvaluator.scala multiplies by 2), and
  RMSE = sqrt(squared_loss / n) over the unweighted count
  (RMSEEvaluator.scala);
- precision@k divides by k, not by min(k, group size)
  (PrecisionAtKLocalEvaluator.scala:50);
- AUPR is unweighted, with the (0, firstPrecision) anchor point of Spark's
  BinaryClassificationMetrics (AreaUnderPRCurveEvaluator.scala).
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from photon_tpu.ops import losses as losses_mod

Array = jax.Array

_POS = 0.5  # MathConst.POSITIVE_RESPONSE_THRESHOLD


class EvaluatorType(enum.Enum):
    """Names match EvaluatorType.scala so configs/CLIs stay compatible.

    MAE / MSE / PEAK_F1 come from the legacy driver's metric family
    (photon-client evaluation/Evaluation.scala:33-41: "Mean absolute
    error", "Mean square error", "Peak F1 score"), which the GAME
    EvaluatorType enum never absorbed upstream.
    """

    AUC = "AUC"
    AUPR = "AUPR"
    RMSE = "RMSE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"
    MAE = "MAE"
    MSE = "MSE"
    PEAK_F1 = "PEAK_F1"

    @property
    def bigger_is_better(self) -> bool:
        """The model-selection comparator direction (EvaluatorType.op)."""
        return self in (
            EvaluatorType.AUC, EvaluatorType.AUPR, EvaluatorType.PEAK_F1
        )

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.bigger_is_better else a < b


# Threshold-based binary metric names (legacy driver Evaluation.scala:196
# metric map: precision/recall/F1/accuracy at a score threshold).
THRESHOLD_METRICS = ("PRECISION", "RECALL", "F1", "ACCURACY")


# --------------------------------------------------------------------------
# Single (whole-dataset) evaluators
# --------------------------------------------------------------------------


def auc_roc(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted, tie-aware area under the ROC curve.

    Equivalent to the reference's sweep (AreaUnderROCCurveLocalEvaluator:72):
    ties contribute half credit; weights weight both the positive and
    negative counts. Returns NaN when a class is absent.
    """
    n = scores.shape[0]
    w = jnp.ones_like(scores) if weights is None else weights
    scores, labels, w = _grouped_sort(scores, labels, w)
    return _segment_auc(scores, labels, w, jnp.zeros(n, dtype=jnp.int32), 1)[0]


def auc_pr(scores: Array, labels: Array) -> Array:
    """Unweighted area under the precision-recall curve, Spark-style:
    thresholds at distinct scores, trapezoid rule, (0, firstPrecision)
    anchor (Spark BinaryClassificationMetrics.pr / SPARK-21806)."""
    order = jnp.argsort(-scores)
    s = scores[order]
    y = (labels[order] > _POS).astype(scores.dtype)
    tp = jnp.cumsum(y)
    fp = jnp.cumsum(1.0 - y)
    total_pos = tp[-1]
    # A point per position, but only threshold boundaries (last index of each
    # tie block) are real curve points; mask the rest out of the trapezoid.
    is_boundary = jnp.concatenate([s[1:] != s[:-1], jnp.ones(1, dtype=bool)])
    precision = tp / jnp.maximum(tp + fp, 1.0)
    recall = tp / jnp.maximum(total_pos, 1.0)
    # Trapezoid over boundary points; carry (0, p_first) as the left anchor.
    idx = jnp.nonzero(is_boundary, size=s.shape[0], fill_value=s.shape[0] - 1)[0]
    p_pts = precision[idx]
    r_pts = recall[idx]
    num_pts = jnp.sum(is_boundary)
    valid = jnp.arange(s.shape[0]) < num_pts
    p_prev = jnp.concatenate([p_pts[:1], p_pts[:-1]])
    r_prev = jnp.concatenate([jnp.zeros(1, dtype=s.dtype), r_pts[:-1]])
    areas = (r_pts - r_prev) * 0.5 * (p_pts + p_prev)
    return jnp.sum(jnp.where(valid, areas, 0.0))


def _weighted_loss_sum(loss: losses_mod.PointwiseLoss, scores, labels, weights):
    w = jnp.ones_like(scores) if weights is None else weights
    return jnp.sum(w * loss.loss(scores, labels))


def logistic_loss(scores, labels, weights=None) -> Array:
    return _weighted_loss_sum(losses_mod.LOGISTIC, scores, labels, weights)


def poisson_loss(scores, labels, weights=None) -> Array:
    return _weighted_loss_sum(losses_mod.POISSON, scores, labels, weights)


def squared_loss(scores, labels, weights=None) -> Array:
    """sum(w * (s - y)^2). The pointwise loss carries the optimizer's
    convenience factor 1/2; the evaluator undoes it
    (SquaredLossEvaluator.scala: ``2 * weight * lossAndDzLoss(...)._1``)."""
    return 2.0 * _weighted_loss_sum(losses_mod.SQUARED, scores, labels, weights)


def smoothed_hinge_loss(scores, labels, weights=None) -> Array:
    return _weighted_loss_sum(losses_mod.SMOOTHED_HINGE, scores, labels, weights)


def mae(scores, labels, weights=None) -> Array:
    """Weighted mean absolute error (Evaluation.scala MEAN_ABSOLUTE_ERROR;
    Spark RegressionMetrics.meanAbsoluteError at unit weights)."""
    w = jnp.ones_like(scores) if weights is None else weights
    return jnp.sum(w * jnp.abs(scores - labels)) / jnp.sum(w)


def mse(scores, labels, weights=None) -> Array:
    """Weighted mean squared error (Evaluation.scala MEAN_SQUARE_ERROR)."""
    w = jnp.ones_like(scores) if weights is None else weights
    d = scores - labels
    return jnp.sum(w * d * d) / jnp.sum(w)


def _confusion_weights(scores, labels, threshold, weights):
    """Weighted (tp, fp, fn, tn) at a mean-space threshold.

    ``threshold`` lives in probability space (the reference thresholds the
    model MEAN, Evaluation.scala computeMeanFunctionWithOffset); scores are
    margins, so the cut is margin >= logit(threshold).
    """
    t = jnp.log(threshold) - jnp.log1p(-threshold)  # logit
    w = jnp.ones_like(scores) if weights is None else weights
    pred = scores >= t
    pos = labels > _POS
    tp = jnp.sum(jnp.where(pred & pos, w, 0.0))
    fp = jnp.sum(jnp.where(pred & ~pos, w, 0.0))
    fn = jnp.sum(jnp.where(~pred & pos, w, 0.0))
    tn = jnp.sum(jnp.where(~pred & ~pos, w, 0.0))
    return tp, fp, fn, tn


def precision_at_threshold(scores, labels, threshold, weights=None) -> Array:
    tp, fp, _, _ = _confusion_weights(scores, labels, threshold, weights)
    return jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1e-300), 0.0)


def recall_at_threshold(scores, labels, threshold, weights=None) -> Array:
    tp, _, fn, _ = _confusion_weights(scores, labels, threshold, weights)
    return jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1e-300), 0.0)


def f1_at_threshold(scores, labels, threshold, weights=None) -> Array:
    tp, fp, fn, _ = _confusion_weights(scores, labels, threshold, weights)
    denom = 2.0 * tp + fp + fn
    return jnp.where(denom > 0, 2.0 * tp / jnp.maximum(denom, 1e-300), 0.0)


def accuracy_at_threshold(scores, labels, threshold, weights=None) -> Array:
    tp, fp, fn, tn = _confusion_weights(scores, labels, threshold, weights)
    total = tp + fp + fn + tn
    return jnp.where(total > 0, (tp + tn) / jnp.maximum(total, 1e-300), 0.0)


def peak_f1(scores, labels, weights=None) -> Array:
    """Max F1 over all score thresholds, tie-aware.

    Reference: Evaluation.scala PEAK_F1_SCORE =
    ``binaryMetrics.fMeasureByThreshold().map(_._2).max`` — the F1 sweep
    over every distinct score treated as a cut. Sorted descending, with
    cumulative true positives tp_i and predicted-positive mass p_i, F1 at a
    cut equals 2*tp / (p + pos_total); only tie-block ends are valid cuts.
    """
    w = jnp.ones_like(scores) if weights is None else weights
    order = jnp.argsort(-scores)
    s = scores[order]
    pos_w = jnp.where(labels[order] > _POS, w[order], 0.0)
    w_sorted = w[order]
    tp = jnp.cumsum(pos_w)
    pred = jnp.cumsum(w_sorted)
    pos_total = tp[-1]
    f1 = 2.0 * tp / jnp.maximum(pred + pos_total, 1e-300)
    # A position is a valid cut only if the next score differs (tie block end).
    block_end = jnp.concatenate(
        [s[:-1] != s[1:], jnp.ones(1, dtype=bool)]
    )
    return jnp.max(jnp.where(block_end, f1, -jnp.inf))


def rmse(scores, labels, weights=None) -> Array:
    """sqrt(sum(w * (s-y)^2) / n) (RMSEEvaluator.scala: squared loss over
    the unweighted count)."""
    n = scores.shape[0]
    return jnp.sqrt(squared_loss(scores, labels, weights) / n)


_SINGLE = {
    EvaluatorType.AUC: lambda s, y, w: auc_roc(s, y, w),
    EvaluatorType.AUPR: lambda s, y, w: auc_pr(s, y),
    EvaluatorType.RMSE: rmse,
    EvaluatorType.LOGISTIC_LOSS: logistic_loss,
    EvaluatorType.POISSON_LOSS: poisson_loss,
    EvaluatorType.SMOOTHED_HINGE_LOSS: smoothed_hinge_loss,
    EvaluatorType.SQUARED_LOSS: squared_loss,
    EvaluatorType.MAE: mae,
    EvaluatorType.MSE: mse,
    EvaluatorType.PEAK_F1: peak_f1,
}

_THRESHOLD = {
    "PRECISION": precision_at_threshold,
    "RECALL": recall_at_threshold,
    "F1": f1_at_threshold,
    "ACCURACY": accuracy_at_threshold,
}


def evaluate_at_threshold(
    metric: str, scores, labels, threshold: float, weights=None
) -> Array:
    return _THRESHOLD[metric](scores, labels, threshold, weights)


def evaluate_single(
    evaluator_type: EvaluatorType, scores, labels, weights=None
) -> Array:
    return _SINGLE[evaluator_type](scores, labels, weights)


# --------------------------------------------------------------------------
# Grouped (multi) evaluators: segment-sum machinery
# --------------------------------------------------------------------------


def _grouped_sort(scores, labels, weights, group_ids=None):
    """Sort by (group asc, score asc); returns permuted columns (+groups)."""
    if group_ids is None:
        order = jnp.argsort(scores)
        return scores[order], labels[order], weights[order]
    order = jnp.lexsort((scores, group_ids))
    return scores[order], labels[order], weights[order], group_ids[order]


def _segment_auc(s, y, w, gid, num_groups):
    """Per-group weighted tie-aware AUC; inputs sorted by (gid, score asc).

    For each positive row: credit = (negative weight strictly below within
    group) + 0.5 * (negative weight in its tie block). Normalized by
    (pos total * neg total) per group; NaN where a class is missing.
    """
    n = s.shape[0]
    pos_w = jnp.where(y > _POS, w, 0.0)
    neg_w = jnp.where(y > _POS, 0.0, w)

    # Tie blocks: new block when group or score changes.
    first = jnp.ones(1, dtype=bool)
    new_block = jnp.concatenate([first, (s[1:] != s[:-1]) | (gid[1:] != gid[:-1])])
    tid = jnp.cumsum(new_block) - 1  # [n] tie-block ids, 0-based

    neg_per_tie = jax.ops.segment_sum(neg_w, tid, num_segments=n)
    # Exclusive cumsum over tie blocks = negative weight strictly below the block.
    neg_below_tie = jnp.cumsum(neg_per_tie) - neg_per_tie
    # Subtract the group's own offset (negatives in previous groups).
    neg_per_group = jax.ops.segment_sum(neg_w, gid, num_segments=num_groups)
    group_offset = jnp.cumsum(neg_per_group) - neg_per_group
    credit = pos_w * (neg_below_tie[tid] - group_offset[gid] + 0.5 * neg_per_tie[tid])

    raw = jax.ops.segment_sum(credit, gid, num_segments=num_groups)
    pos_per_group = jax.ops.segment_sum(pos_w, gid, num_segments=num_groups)
    denom = pos_per_group * neg_per_group
    return raw / denom  # NaN or inf where a class is absent — filtered upstream


def grouped_auc_per_group(
    scores, labels, group_ids, num_groups, weights=None
) -> tuple[Array, Array]:
    """(per-group AUC [G], validity mask [G]): single-class groups invalid.

    Reference: the per-group values MultiEvaluator computes before its mean
    (MultiEvaluator.scala:50-65) — also what the driver's per-group
    evaluation output writes (GameTrainingDriver.scala:878-901).
    """
    w = jnp.ones_like(scores) if weights is None else weights
    s, y, w, g = _grouped_sort(scores, labels, w, group_ids)
    per_group = _segment_auc(s, y, w, g, num_groups)
    return per_group, jnp.isfinite(per_group)


def grouped_auc(scores, labels, group_ids, num_groups, weights=None) -> Array:
    """Mean per-group AUC, skipping single-class groups.

    Reference: AreaUnderROCCurveMultiEvaluator via MultiEvaluator.evaluate
    (MultiEvaluator.scala:50-65, NaN/Inf filtered before the mean).
    """
    per_group, finite = grouped_auc_per_group(
        scores, labels, group_ids, num_groups, weights)
    return jnp.sum(jnp.where(finite, per_group, 0.0)) / jnp.maximum(
        jnp.sum(finite), 1)


def grouped_precision_at_k_per_group(
    scores, labels, group_ids, num_groups, k: int
) -> tuple[Array, Array]:
    """(per-group precision@k [G], presence mask [G])."""
    order = jnp.lexsort((-scores, group_ids))
    g = group_ids[order]
    y = labels[order]
    # rank within group = position - group start position
    n = scores.shape[0]
    pos = jnp.arange(n)
    start = jax.ops.segment_min(pos, g, num_segments=num_groups)
    rank = pos - start[g]
    hit = (rank < k) & (y > _POS)
    hits_per_group = jax.ops.segment_sum(
        hit.astype(scores.dtype), g, num_segments=num_groups)
    # Guard for group ids with no rows (possible when num_groups over-counts).
    group_sizes = jax.ops.segment_sum(
        jnp.ones_like(scores), g, num_segments=num_groups)
    return hits_per_group / k, group_sizes > 0


def grouped_precision_at_k(
    scores, labels, group_ids, num_groups, k: int
) -> Array:
    """Mean per-group precision@k (hits in top-k by score, divided by k).

    Reference: PrecisionAtKMultiEvaluator + PrecisionAtKLocalEvaluator.
    Groups always produce a finite value, so no filtering applies.
    """
    per_group, present = grouped_precision_at_k_per_group(
        scores, labels, group_ids, num_groups, k)
    return jnp.sum(jnp.where(present, per_group, 0.0)) / jnp.maximum(
        jnp.sum(present), 1)


@dataclasses.dataclass(frozen=True)
class EvaluatorSpec:
    """One requested metric: a single evaluator, or a multi evaluator bound
    to an id tag (grouping column).

    String forms mirror the reference's evaluator id syntax
    (MultiEvaluatorType: e.g. ``PRECISION@5:queryId``, ``AUC:userId``).
    """

    evaluator_type: EvaluatorType | None = None
    group_tag: str | None = None
    precision_k: int | None = None
    # Threshold-based binary metric: one of THRESHOLD_METRICS at a
    # mean-space score threshold (legacy driver Evaluation.scala:196).
    # Spec syntax: "PRECISION=0.5", "F1=0.25", ...
    threshold_metric: str | None = None
    threshold: float | None = None

    @property
    def name(self) -> str:
        if self.threshold_metric is not None:
            return f"{self.threshold_metric}={self.threshold:g}"
        if self.precision_k is not None:
            return f"PRECISION@{self.precision_k}:{self.group_tag}"
        assert self.evaluator_type is not None
        if self.group_tag is not None:
            return f"{self.evaluator_type.value}:{self.group_tag}"
        return self.evaluator_type.value

    @property
    def bigger_is_better(self) -> bool:
        if self.precision_k is not None or self.threshold_metric is not None:
            return True
        assert self.evaluator_type is not None
        return self.evaluator_type.bigger_is_better

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.bigger_is_better else a < b

    @staticmethod
    def parse(spec: str) -> "EvaluatorSpec":
        spec = spec.strip()
        if "=" in spec:
            head, t = spec.split("=", 1)
            head = head.strip().upper()
            if ":" in t:
                raise ValueError(
                    f"threshold metrics do not support group tags "
                    f"(got {spec!r}); the reference's per-group evaluation "
                    f"covers AUC and precision@k only "
                    f"(MultiEvaluatorType.scala:52-66)"
                )
            if head not in THRESHOLD_METRICS:
                raise ValueError(
                    f"unknown threshold metric {head!r}; expected one of "
                    f"{THRESHOLD_METRICS}"
                )
            threshold = float(t)
            if not 0.0 < threshold < 1.0:
                raise ValueError(
                    f"threshold metric cut must be in (0, 1) — it applies "
                    f"to the model mean — got {threshold}"
                )
            return EvaluatorSpec(
                threshold_metric=head, threshold=threshold
            )
        if ":" in spec:
            head, tag = spec.split(":", 1)
            if head.upper().startswith("PRECISION@"):
                return EvaluatorSpec(group_tag=tag,
                                     precision_k=int(head.split("@", 1)[1]))
            return EvaluatorSpec(EvaluatorType(head.upper()), group_tag=tag)
        return EvaluatorSpec(EvaluatorType(spec.upper()))
