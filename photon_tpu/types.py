"""Domain type aliases and enums.

Mirrors the reference's typed-ID vocabulary (photon-lib Types.scala:21-44):
``UniqueSampleId``, ``CoordinateId``, ``REType``, ``REId``, ``FeatureShardId``.
On TPU these stay host-side Python types; device-side everything is integer
row/bucket indices.
"""

from __future__ import annotations

import enum

# Unique identifier of one sample (row) in a dataset.
UniqueSampleId = int
# Name of one coordinate in a GAME model update sequence (e.g. "global", "per-user").
CoordinateId = str
# A random-effect type, i.e. the name of the grouping column (e.g. "userId").
REType = str
# The id of one entity of a random-effect type (one user, one movie, ...).
REId = str
# Name of a feature shard (a bag-of-feature-bags a coordinate trains on).
FeatureShardId = str
# Feature name/term key: the reference joins Avro (name, term) pairs with a
# delimiter into a flat string key (photon-client Constants).
FeatureKey = str

DELIMITER = "\x01"
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
# Reference parity: Constants.INTERCEPT_KEY = getFeatureKey(name, term), i.e.
# the delimiter-joined (name, term) pair — "(INTERCEPT)\x01"
# (photon-client Constants.scala:40-42).
INTERCEPT_KEY: FeatureKey = f"{INTERCEPT_NAME}{DELIMITER}{INTERCEPT_TERM}"


class TaskType(enum.Enum):
    """Training task, determining loss function and link function.

    Reference: photon-lib TaskType enum (LINEAR_REGRESSION, LOGISTIC_REGRESSION,
    POISSON_REGRESSION, SMOOTHED_HINGE_LOSS_LINEAR_SVM).
    """

    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"


def make_feature_key(name: str, term: str = "") -> FeatureKey:
    """Join an Avro (name, term) pair into the flat feature key used by index maps.

    Reference: Constants.DELIMITER usage in AvroDataReader.scala.
    """
    return f"{name}{DELIMITER}{term}"


def split_feature_key(key: FeatureKey) -> tuple[str, str]:
    """Inverse of make_feature_key (Utils.getFeatureNameFromKey /
    getFeatureTermFromKey); keys without a delimiter have an empty term."""
    parts = key.split(DELIMITER)
    return (parts[0], parts[1]) if len(parts) == 2 else (parts[0], "")
