"""GameEstimator: the main fit() API orchestrating GAME training.

TPU-native counterpart of photon-api estimators/GameEstimator.scala:55. The
reference's fit (:397-491) converts a DataFrame to a GameDatum RDD, builds
per-coordinate datasets (prepareTrainingDatasets :557-638), prepares the
validation evaluation suite (:649-673), constructs coordinates via
CoordinateFactory (:783) and runs coordinate descent once per optimization
configuration, warm-starting each run from the previous one (:452-468).

Here ingest already produced a columnar GameDataset; fit builds device-side
coordinate datasets once (random-effect block construction is the expensive
step and is cached across the lambda-grid configs, like the reference reuses
its persisted RDD datasets), then runs one CoordinateDescent per
configuration.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import OrderedDict
from typing import Union

import jax

from photon_tpu.algorithm.coordinate import FixedEffectCoordinate
from photon_tpu.algorithm.coordinate_descent import (
    CoordinateDescent,
    CoordinateDescentResult,
    ValidationContext,
)
from photon_tpu.algorithm.problems import (
    GLMOptimizationConfiguration,
    GLMOptimizationProblem,
)
from photon_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_tpu.data.game_data import GameDataset
from photon_tpu.data.random_effect import (
    PendingRandomEffectDataset,
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_tpu.transformers import (
    fixed_effect_scorer,
    random_effect_scorer,
)
from photon_tpu.evaluation.evaluators import EvaluatorSpec
from photon_tpu.evaluation.suite import EvaluationResults, make_suite
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    remap_random_effect_model,
)
from photon_tpu.ops.normalization import NormalizationContext
from photon_tpu.parallel.mesh import (
    resolve_mesh,
    shard_batch,
    shard_random_effect_dataset,
)
from photon_tpu.types import TaskType

Array = jax.Array
logger = logging.getLogger(__name__)

# Distinct fused whole-fit programs retained per estimator. Each entry
# pins one compiled fit executable; the dataset-scale device buffers (the
# materialized bucket slabs) are shared across entries through the
# generation's _fused_mat_share, so the bound limits executables, not
# slab HBM. A handful covers realistic mixed-optimizer config grids.
_FUSED_CACHE_SIZE = 8

# Program contracts (audited by `python -m photon_tpu.analysis
# --semantic`; machinery in analysis/program.py). The first pins the
# _fused_cache static-key discipline: a λ-grid sweep maps to ONE cache
# key (one whole-fit executable re-entered with new traced weights) and
# only a genuinely-static change (optimizer swap) mints a second. The
# second pins the unfused coordinate update (_run_impl under jit): λ and
# warm-start coefficients are traced operands, so one executable serves
# the entire grid.
# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The estimator owns no locks: all mutable estimator
# state (_fit_cache, _fused_cache, _aot_future, _primed_datasets) is
# written by the single training thread only. What it DOES own is
# thread entries — per-coordinate planners on the ingest plan pool
# (`build_one`), the background AOT warm compile on the compile pool
# (`_warm_compile`), and the compile-priming thunks (`thunk` inside
# `warmup_thunks`; the ModelCoordinate lambda in `_prime_compilations`
# is the same shape) — and the declared reasons why the JAX entries on
# those threads are safe. Results always come back to the training
# thread through Futures (every one consumed — see consume_futures).
CONCURRENCY_AUDIT = dict(
    name="game-estimator-host",
    locks={},
    thread_entries=(
        "_build_datasets.build_one",
        "_warm_compile",
        "warmup_thunks.thunk",
    ),
    jax_dispatch_ok={
        "_warm_compile": "XLA compiles in C++ with the GIL released — "
        "that release IS the overlap win; the traced skeletons are "
        "thread-private, the persistent compile cache is thread-safe "
        "in JAX, and FusedFit.run serializes consumption through the "
        "future (compile_wait measures any residual block)",
        "warmup_thunks.thunk": "priming executes real warm-up solves "
        "concurrently BY DESIGN (the compiler handles concurrent "
        "requests ~2.5x faster); single-device only — the mesh path "
        "returns before submitting because collective rendezvous must "
        "not interleave (see _prime_compilations docstring)",
    },
)

PROGRAM_AUDIT = [
    dict(
        name="fused-cache-key",
        entry="estimators.game_estimator.GameEstimator._fused_for "
        "(fused_static_key discipline)",
        builder="build_fused_cache_keys",
        max_programs=1,
        stable_under=("lambda_grid",),
        recompiles_on=("optimizer_swap", "precision"),
    ),
    dict(
        name="unfused-coordinate-update",
        entry="algorithm.problems._run_impl "
        "(via GLMOptimizationProblem.run)",
        builder="build_unfused_update",
        max_programs=1,
        stable_under=("lambda_grid", "warm_start"),
        recompiles_on=("optimizer_swap",),
        hot_loop=True,
    ),
]

# Default primary evaluator per task (GameEstimator.scala:673
# prepareValidationEvaluators falls back to the task's default evaluator).
_DEFAULT_EVALUATOR = {
    TaskType.LOGISTIC_REGRESSION: "AUC",
    TaskType.LINEAR_REGRESSION: "RMSE",
    TaskType.POISSON_REGRESSION: "POISSON_LOSS",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "AUC",
}


# Feature count above which "auto" feature sharding goes column-wise — the
# reference's own threshold for switching to off-heap PalDB indexes
# (index/FeatureIndexingDriver.scala:40-41 recommends them >200k features).
AUTO_COLUMN_SHARDING_THRESHOLD = 200_000


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinateConfiguration:
    """Reference: FixedEffectDataConfiguration + its optimization config.

    ``feature_sharding`` picks the coefficient placement on a mesh:

    - ``"replicated"`` (default): coefficients replicated per device, batch
      rows sharded (dp) — right for d that fits every chip's HBM.
    - ``"column"``: the FEATURE axis is sharded (tp): each device owns a
      contiguous coefficient range and the ELL entries whose feature falls
      in it; margins psum over ICI, gradient scatters stay device-local
      (parallel/mesh.py FeatureShardedSparse). This is the product path for
      the reference's "hundreds of billions of coefficients" axis
      (README.md:56, carried there by PalDB off-heap indexes,
      index/PalDBIndexMap.scala:43 + sparse vectors).
    - ``"auto"``: column when a mesh is active and the shard's feature count
      exceeds AUTO_COLUMN_SHARDING_THRESHOLD, else replicated.

    Without a mesh every mode degrades to the single-device replicated path.
    """

    feature_shard_id: str
    optimization: GLMOptimizationConfiguration = dataclasses.field(
        default_factory=GLMOptimizationConfiguration
    )
    feature_sharding: str = "replicated"

    def __post_init__(self):
        if self.feature_sharding not in ("replicated", "column", "auto"):
            raise ValueError(
                f"feature_sharding must be 'replicated', 'column' or "
                f"'auto', got {self.feature_sharding!r}")


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinateConfiguration:
    """Reference: RandomEffectDataConfiguration + its optimization config."""

    data: RandomEffectDataConfiguration
    optimization: GLMOptimizationConfiguration = dataclasses.field(
        default_factory=GLMOptimizationConfiguration
    )


CoordinateConfiguration = Union[
    FixedEffectCoordinateConfiguration, RandomEffectCoordinateConfiguration
]


@dataclasses.dataclass(frozen=True)
class _FixedEffectModelAdapter:
    """Adapts FixedEffectCoordinate (which speaks bare GLMs) to the GAME
    model vocabulary: train/score exchange shard-tagged FixedEffectModels so
    the composite GameModel knows each sub-model's feature shard."""

    inner: FixedEffectCoordinate
    feature_shard_id: str

    def train(self, residuals=None, initial_model=None, *, seed: int = 0):
        init = initial_model.model if initial_model is not None else None
        glm, diag = self.inner.train(residuals, init, seed=seed)
        return FixedEffectModel(glm, self.feature_shard_id), diag

    def score(self, model: FixedEffectModel):
        return self.inner.score(model.model)

    def warmup_thunks(self):
        def thunk():
            model, _ = self.train()
            jax.block_until_ready(self.score(model))

        return [thunk]


@dataclasses.dataclass(frozen=True)
class GameFitResult:
    """One (configuration, trained model) pair from the config sequence.

    Reference: GameEstimator.fit returns Seq[(GameModel, Option[EvaluationResults],
    GameOptimizationConfiguration)].
    """

    model: GameModel  # best-by-validation model of this config's CD run
    config: dict[str, GLMOptimizationConfiguration]
    evaluation: EvaluationResults | None
    # None for a completed-config result rebuilt on resume (the config
    # ran to completion in the interrupted process; its per-update
    # history died with it — model/evaluation are reconstructed from
    # the retained config-final checkpoint).
    descent: CoordinateDescentResult | None


def _log_orphaned_compile(fut) -> None:
    """Done-callback consuming an orphaned warm-compile future (a
    prepare() superseded it mid-compile): the result is discarded by
    design, but an exception must be seen, not dropped."""
    exc = fut.exception()
    if exc is not None:
        logger.warning(
            "orphaned AOT warm compile raised after being superseded "
            "(should be impossible — _warm_compile catches): %r", exc,
        )


class GameEstimator:
    """Reference: estimators/GameEstimator.scala:55.

    ``coordinate_configs`` is ordered; its key order is the default update
    sequence (the reference's coordinateUpdateSequence param).
    """

    def __init__(
        self,
        task: TaskType,
        coordinate_configs: dict[str, CoordinateConfiguration],
        *,
        update_sequence: list[str] | None = None,
        num_iterations: int = 1,
        normalization: dict[str, NormalizationContext] | None = None,
        intercept_indices: dict[str, int] | None = None,
        evaluators: list[str | EvaluatorSpec] | None = None,
        locked_coordinates: set[str] | None = None,
        incremental_training: bool = False,
        mesh="auto",
        listeners=None,
        non_finite_guard: bool = False,
        precision: str = "float32",
    ):
        self.task = task
        self.coordinate_configs = dict(coordinate_configs)
        self.update_sequence = (
            list(update_sequence)
            if update_sequence is not None
            else list(coordinate_configs)
        )
        for cid in self.update_sequence:
            if cid not in self.coordinate_configs:
                raise KeyError(f"update sequence id {cid!r} has no config")
        self.num_iterations = num_iterations
        self.normalization = dict(normalization or {})
        self.intercept_indices = dict(intercept_indices or {})
        self.evaluators = list(evaluators or [])
        self.locked_coordinates = set(locked_coordinates or ())
        # Incremental training: the initial model becomes a per-coefficient
        # Gaussian prior (GameEstimator.scala incrementalTraining param;
        # invariants validated at fit time, :241-382).
        self.incremental_training = incremental_training
        # Multi-device execution. The reference's drivers are distributed by
        # default — GameTrainingDriver.run executes on the cluster session
        # (SparkSessionConfiguration.scala:109) — so "auto" spans all visible
        # devices: fixed-effect batches are row-sharded (dp) and
        # random-effect entity axes are sharded (ep) over a one-axis mesh.
        # Pass "off"/None for single-device, or a jax.sharding.Mesh / device
        # count to control placement explicitly.
        self.mesh = mesh
        # Resilience: per-update NaN/inf guard with rollback in the CD
        # loop (needs a host boundary per update, so it rides the
        # unfused path — see fit()'s fused gating).
        self.non_finite_guard = bool(non_finite_guard)
        # Mixed-precision policy (ops/precision.py; PERFORMANCE.md):
        # "bfloat16" stores random-effect slabs + fused score carries in
        # bf16 with f32 accumulators everywhere a sum crosses a row
        # axis; "float32" (default) is the historical path. Part of the
        # fused static key — the declared `precision` recompile family
        # (the λ grid still adds ZERO programs at either setting).
        from photon_tpu.ops import precision as _precision_mod

        self.precision = _precision_mod.resolve(precision)
        # Training-event fan-out (events.EventEmitter listener registry):
        # CoordinateUpdateEvent per coordinate update, FitEndEvent per
        # optimization config (EventEmitter.scala:24 for the GAME path).
        self.emitter = None
        if listeners:
            from photon_tpu.events import EventEmitter

            self.emitter = EventEmitter(listeners)

    def resolve_mesh(self):
        """mesh param -> Mesh | None (resolved once; devices don't change)."""
        if not hasattr(self, "_resolved_mesh"):
            self._resolved_mesh = resolve_mesh(self.mesh)
        return self._resolved_mesh

    # ------------------------------------------------------------------
    # dataset / coordinate construction (prepareTrainingDatasets + factory)
    # ------------------------------------------------------------------

    def _shard_norm(self, shard: str) -> NormalizationContext:
        return self.normalization.get(shard, NormalizationContext())

    def _build_datasets(
        self, data: GameDataset, initial_model: GameModel | None = None
    ) -> dict[str, object]:
        """The expensive one-time step: per-coordinate device datasets.

        A prior model's per-entity feature support is unioned into the
        subspace projectors (RandomEffectDataset.scala:390-426) so its
        coefficients keep their slots under warm start.

        With a mesh, fixed-effect batches are padded and row-sharded (dp)
        and random-effect entity axes sharded (ep) — the product-surface
        analog of GameTrainingDriver running on the cluster session
        (GameTrainingDriver.scala:363-516).
        """
        from photon_tpu.data.dataset import DualEllFeatures

        mesh = self.resolve_mesh()

        def build_one(cid: str, cfg):
            from photon_tpu.resilience import faults

            # Chaos boundary: a planner thunk dying on the plan pool
            # must propagate through consume_futures, not hang the fit.
            faults.check("ingest.plan")
            if isinstance(cfg, RandomEffectCoordinateConfiguration):
                extra = None
                if initial_model is not None and cid in initial_model:
                    prior = initial_model[cid]
                    if isinstance(prior, RandomEffectModel):
                        tag = data.id_tags[cfg.data.random_effect_type]
                        extra = {}
                        for eo, key in enumerate(prior.entity_keys):
                            # vocab keys are str-normalized at ingest;
                            # models saved before normalization may carry
                            # numeric keys.
                            code = tag.vocab.get(str(key))
                            if code is not None:
                                p = prior.proj_all[eo]
                                extra[code] = p[p >= 0]
                # Device placement is deferred: every coordinate's plan
                # arrays ride ONE packed transfer below (PendingRandomEffect
                # Dataset), so the host link's per-transfer setup is paid
                # once per fit, not once per coordinate. Materialized
                # layouts (DualEll shards etc.) come back finalized and are
                # sharded here; pendings shard after _resolve_pending.
                ds = build_random_effect_dataset(
                    data,
                    cfg.data,
                    intercept_index=self.intercept_indices.get(
                        cfg.data.feature_shard_id
                    ),
                    extra_features=extra,
                    defer_transfer=True,
                )
                if mesh is not None and not isinstance(
                    ds, PendingRandomEffectDataset
                ):
                    ds = shard_random_effect_dataset(ds, mesh)
                return ds
            if mesh is not None and self._wants_column_sharding(data, cfg):
                return self._build_column_sharded_batch(data, cfg, mesh)
            batch = data.shard_batch(cfg.feature_shard_id)
            if mesh is not None:
                if isinstance(batch.features, DualEllFeatures):
                    logger.info(
                        "coordinate %s: DualEll features are not "
                        "row-shardable; leaving replicated", cid)
                else:
                    batch = shard_batch(batch, mesh)
            return batch

        # Per-coordinate planning runs CONCURRENTLY on the ingest pipeline's
        # plan pool: the planners' hot numpy ops (radix argsort, bincount,
        # fancy gathers, segment-OR) release the GIL, and each coordinate's
        # within-pass chunking rides the separate chunk pool (pipeline.py
        # owns the two-level layout and the deadlock argument). Results are
        # bit-identical to the serial order — builds are independent and the
        # ordered wait below reproduces the dict order exactly; device
        # placement for ALL coordinates is still deferred into one packed
        # transfer. PHOTON_TPU_SERIAL_INGEST=1 restores the in-line path.
        from photon_tpu.data import pipeline

        futs = {
            cid: pipeline.plan_executor.submit(build_one, cid, cfg)
            for cid, cfg in self.coordinate_configs.items()
            if isinstance(cfg, RandomEffectCoordinateConfiguration)
        }
        # consume_futures: every planner's exception is observed even
        # when an earlier coordinate's build already failed (the naive
        # per-future .result() loop abandons — and silences — the rest).
        planned = dict(
            zip(futs, pipeline.consume_futures(futs.values()))
        )
        out = {
            cid: (
                planned[cid] if cid in planned else build_one(cid, cfg)
            )
            for cid, cfg in self.coordinate_configs.items()
        }
        return self._resolve_pending(out, mesh)

    def _resolve_pending(self, out: dict[str, object], mesh):
        """Place all deferred plan arrays with one packed transfer."""
        from photon_tpu.data.random_effect import (
            PendingRandomEffectDataset,
            _plan_arrays_to_device,
        )

        pending = {
            cid: d for cid, d in out.items()
            if isinstance(d, PendingRandomEffectDataset)
        }
        if not pending:
            return out
        all_flat: list = []
        spans: dict[str, tuple[int, int]] = {}
        for cid, p in pending.items():
            spans[cid] = (len(all_flat), len(all_flat) + len(p.flat))
            all_flat.extend(p.flat)
        devs = _plan_arrays_to_device(all_flat)
        for cid, p in pending.items():
            lo, hi = spans[cid]
            ds = p.finalize(devs.view(lo, hi))
            if mesh is not None:
                ds = shard_random_effect_dataset(ds, mesh)
            out[cid] = ds
        return out

    def _wants_column_sharding(
        self, data: GameDataset, cfg: FixedEffectCoordinateConfiguration
    ) -> bool:
        mode = cfg.feature_sharding
        if mode == "column":
            return True
        if mode == "auto":
            feats = data.feature_shards[cfg.feature_shard_id]
            if feats.num_features <= AUTO_COLUMN_SHARDING_THRESHOLD:
                return False
            # The auto heuristic degrades to replicated on shards the
            # column path can't take (explicit "column" hard-fails instead).
            why = self._column_sharding_blocker(data, cfg.feature_shard_id)
            if why is not None:
                logger.info(
                    "shard %s: auto feature sharding staying replicated "
                    "(%s)", cfg.feature_shard_id, why)
                return False
            return True
        return False

    def _column_sharding_blocker(
        self, data: GameDataset, shard: str
    ) -> str | None:
        """Why ``shard`` can't go column-sharded, or None if it can."""
        norm = self.normalization.get(shard)
        if norm is not None and not norm.is_identity:
            return "feature normalization is active"
        if data.host_shard_tail(shard) is not None:
            return "DualEll overflow tail present"
        return None

    def _build_column_sharded_batch(
        self, data: GameDataset, cfg, mesh
    ):
        """Feature-axis-sharded (tp) fixed-effect batch.

        Coefficients and ELL feature entries are split by feature range over
        the mesh; rows stay at canonical length with labels/offsets/weights
        replicated, so residual routing needs no padding bookkeeping.
        """
        from photon_tpu.data.dataset import GLMBatch
        from photon_tpu.parallel.mesh import (
            replicated,
            shard_features_by_column,
        )

        shard = cfg.feature_shard_id
        why = self._column_sharding_blocker(data, shard)
        if why is not None:
            raise ValueError(
                f"coordinate shard {shard!r}: column feature sharding is "
                f"unsupported here ({why}); normalize at ingest / raise the "
                "DualEll slab width cap, or use replicated sharding")
        idx, val, d = data.host_shard_coo(shard)
        feats = shard_features_by_column(
            idx, val, d, mesh,
            axis_name=mesh.axis_names[0],
            dtype=data.labels.dtype,
        )
        rep = replicated(mesh)
        return GLMBatch(
            features=feats,
            labels=jax.device_put(data.labels, rep),
            offsets=jax.device_put(data.offsets, rep),
            weights=jax.device_put(data.weights, rep),
        )

    def _build_coordinates(
        self,
        datasets: dict[str, object],
        opt_configs: dict[str, GLMOptimizationConfiguration],
        priors: dict[str, object] | None = None,
        logical_rows: int | None = None,
    ) -> dict[str, object]:
        """CoordinateFactory.build equivalent (CoordinateFactory.scala:52);
        ``priors`` carries incremental-training prior models per coordinate
        (the factory's priorModelOpt, DistributedGLMLossFunction.scala:184)."""
        priors = priors or {}
        coords: dict[str, object] = {}
        for cid, cfg in self.coordinate_configs.items():
            opt = opt_configs.get(cid, cfg.optimization)
            if isinstance(cfg, RandomEffectCoordinateConfiguration):
                coords[cid] = RandomEffectCoordinate(
                    datasets[cid],
                    self.task,
                    opt,
                    self._shard_norm(cfg.data.feature_shard_id),
                    prior=priors.get(cid),
                    precision=self.precision,
                )
            else:
                problem = GLMOptimizationProblem(
                    task=self.task,
                    config=opt,
                    normalization=self._shard_norm(cfg.feature_shard_id),
                    intercept_index=self.intercept_indices.get(
                        cfg.feature_shard_id
                    ),
                    prior=priors.get(cid),
                )
                coords[cid] = _FixedEffectModelAdapter(
                    FixedEffectCoordinate(
                        datasets[cid], problem, logical_rows=logical_rows
                    ),
                    cfg.feature_shard_id,
                )
        return coords

    def _prime_compilations(self, coords: dict[str, object], datasets):
        """Compile every coordinate's programs CONCURRENTLY before CD runs.

        The first CD sweep otherwise serializes one XLA compile per bucket
        per coordinate (each 2-4s on the TPU backend); the compiler handles
        concurrent requests ~2.5x faster in wall-clock. Thunks run the real
        jitted entry points with zero inputs, so the jit cache is warm when
        coordinate descent starts; results are discarded. Primed once per
        prepared dataset set (repeat fits hit the cache anyway).

        SINGLE-DEVICE ONLY: on a mesh the thunks' programs carry
        collectives, and two collective-bearing executions in flight from
        different threads can interleave their rendezvous (the same hazard
        coordinate_descent._serialize_on_cpu_mesh guards) — there, the
        first CD sweep compiles serially as before. With fewer than two
        thunks there is no overlap to win and the discarded warm-up solve
        would just double the first fit's work.

        The thunks EXECUTE (one extra discarded solve per program, ~one CD
        iteration of device work) rather than AOT-compiling via
        jit(...).lower().compile(): AOT results don't land in the jit
        dispatch cache, so the real call would re-trace and re-load the
        executable — and on the tunneled TPU backend the per-program LOAD
        (not only the compile) is seconds, which executing the thunk pays
        once and the CD sweep then reuses.
        """
        # Identity (not id()): a dead dict's address can be reused, which
        # would silently skip priming for a NEW dataset set. prepare()
        # clears this on every rebuild, so the reference held here never
        # outlives the _fit_cache generation it belongs to (no double
        # retention of device datasets across fits).
        if getattr(self, "_primed_datasets", None) is datasets:
            return
        if self.resolve_mesh() is not None:
            return
        from concurrent.futures import ThreadPoolExecutor

        from photon_tpu.algorithm.coordinate import ModelCoordinate

        thunks = []
        for coord in coords.values():
            if isinstance(coord, ModelCoordinate):
                thunks.append(
                    lambda c=coord: jax.block_until_ready(c.score())
                )
            elif hasattr(coord, "warmup_thunks"):
                thunks.extend(coord.warmup_thunks())
        if len(thunks) < 2:
            return
        from photon_tpu.data.pipeline import consume_futures

        with ThreadPoolExecutor(max_workers=min(8, len(thunks))) as pool:
            # consume_futures: a thunk that fails after another already
            # raised must still be awaited and its exception surfaced —
            # the pool's __exit__ would otherwise swallow it silently.
            consume_futures([pool.submit(t) for t in thunks])
        self._primed_datasets = datasets

    def _fused_for(self, coords, datasets):
        """The whole-fit fused program for this coordinate structure, or
        None when ineligible (mesh execution, listeners, down-sampling,
        materialized datasets — see fused_fit.fuse_eligible).

        Cached per (dataset generation, static structure) in a small LRU
        keyed by the static key: a lambda-grid config sequence re-enters
        the SAME compiled executable with new traced weights (the
        warm-start ladder of GameEstimator.scala:452-468 with zero
        recompiles), and a grid that ALTERNATES static keys (e.g. mixed
        optimizer configs) round-robins among cached programs instead of
        rebuilding the whole-fit trace on every entry."""
        from photon_tpu.algorithm.fused_fit import (
            FusedFit,
            fuse_ineligibility_reasons,
            fused_static_key,
        )

        if fuse_ineligibility_reasons(
            coords, mesh=self.resolve_mesh(), emitter=self.emitter
        ):
            return None
        key = fused_static_key(
            coords, self.update_sequence, self.num_iterations,
            self.locked_coordinates, self.precision,
        )
        cache = getattr(self, "_fused_cache", None)
        share = getattr(self, "_fused_mat_share", None)
        if cache is None or share is None or share["datasets"] is not datasets:
            # New dataset generation (or first use): every cached program
            # and the materialized-slab set are stale together. The share
            # carries its generation's datasets identity so the check is
            # symmetric for hits and misses.
            cache = self._fused_cache = OrderedDict()
            share = self._fused_mat_share = {"datasets": datasets}
        fused = cache.get(key)
        if fused is not None:
            cache.move_to_end(key)
            return self._attach_aot(fused)
        fused = FusedFit(
            coords, self.update_sequence, self.num_iterations,
            self.locked_coordinates,
            mat_share=share,
            precision=self.precision,
        )
        fused.static_key = key
        cache[key] = fused
        while len(cache) > _FUSED_CACHE_SIZE:
            cache.popitem(last=False)
        return self._attach_aot(fused)

    def _attach_aot(self, fused):
        """Hand prepare()'s pending AOT warm-compile future to the fused
        program; FusedFit.run consumes it (waiting if still compiling —
        that wait is the measured non-overlapped remainder)."""
        fut = getattr(self, "_aot_future", None)
        if fut is not None and getattr(fused, "_aot_future", None) is None:
            fused._aot_future = fut
            self._aot_future = None
        return fused

    def _warm_compile_eligible(
        self, validation, initial_model
    ) -> bool:
        """Whether prepare() may kick off the background AOT warm compile.

        The overlapped compile targets the fused single-device path with
        the base configs and no warm start — exactly the first fit of a
        validation-free ``fit()`` call. Anything else (mesh collectives,
        listeners, incremental priors, initial models whose per-entity
        support changes the subspace shapes) either can't fuse or can't be
        shape-predicted, so the compile would be wasted by construction."""
        from photon_tpu.data import pipeline

        return (
            validation is None
            and initial_model is None
            and not self.incremental_training
            and self.emitter is None
            and self.resolve_mesh() is None
            and not pipeline.serial_ingest()
        )

    def _warm_compile(self, data: GameDataset):
        """AOT-compile the fused materialize + whole-fit programs from
        PREDICTED block shapes — the ingest pipeline's overlapped-compile
        stage, run on a background thread while the real planner is still
        working (XLA compiles in C++ with the GIL released, so planning
        and compiling genuinely overlap).

        Shape-faithful skeleton datasets (data/random_effect.py
        ``skeleton_random_effect_dataset``) stand in for the coordinates;
        the traced programs are the production ones BY CONSTRUCTION (same
        FusedFit code path — the ingest-pipeline PROGRAM_AUDIT contract
        pins that the signatures match). Returns the compiled artifact
        dict, or None when prediction/fusion is unavailable; a stale
        prediction only wastes this compile — ``FusedFit.run`` falls back
        to the normal jit path (which may still hit the persistent
        compile cache this compile populated).
        """
        from photon_tpu.algorithm.fused_fit import (
            FusedFit,
            fuse_ineligibility_reasons,
            fused_static_key,
        )
        from photon_tpu.data.pipeline import PIPELINE_STATS
        from photon_tpu.data.random_effect import (
            skeleton_random_effect_dataset,
        )
        from photon_tpu.utils.compile_cache import aot_compile

        try:
            # Eligibility + skeleton construction OUTSIDE the "compile"
            # stage: a declined prediction must leave compile_seconds at
            # 0 (a truthy near-zero value would both fake an overlap
            # fraction and let bench.py under-report compile_seconds
            # past its regression floor).
            skeleton: dict[str, object] = {}
            for cid, cfg in self.coordinate_configs.items():
                if isinstance(cfg, RandomEffectCoordinateConfiguration):
                    ds = skeleton_random_effect_dataset(data, cfg.data)
                    if ds is None:
                        return None
                    skeleton[cid] = ds
                else:
                    if self._wants_column_sharding(data, cfg):
                        return None
                    skeleton[cid] = data.shard_batch(
                        cfg.feature_shard_id
                    )
            coords = self._build_coordinates(
                skeleton, {}, {}, logical_rows=data.num_samples
            )
            if fuse_ineligibility_reasons(
                coords, mesh=None, emitter=self.emitter
            ):
                return None
            fused = FusedFit(
                coords, self.update_sequence, self.num_iterations,
                self.locked_coordinates,
                precision=self.precision,
            )
            key = fused_static_key(
                coords, self.update_sequence, self.num_iterations,
                self.locked_coordinates, self.precision,
            )
            with PIPELINE_STATS.stage("compile"):
                art = fused.aot_lower(coords)
                return {
                    "key": key,
                    "statics": art["statics"],
                    "mat": aot_compile(
                        art["mat_traced"].lower(),
                        ledger_key="fused_fit/materialize",
                    ),
                    "fit": aot_compile(
                        art["fit_traced"].lower(),
                        ledger_key="fused_fit/fit",
                    ),
                    "mat_text": str(art["mat_traced"].jaxpr),
                    "fit_text": str(art["fit_traced"].jaxpr),
                }
        except Exception as exc:  # noqa: BLE001 — warm compile is best-effort
            logger.info(
                "ingest pipeline: AOT warm compile skipped (%r)", exc
            )
            return None

    def _build_validation(
        self,
        datasets: dict[str, object],
        validation: GameDataset,
    ) -> ValidationContext:
        """prepareValidationDatasetAndEvaluators equivalent (:649-673).

        Validation scorers ride the same mesh as training: the remapped
        score tables are row-sharded, so per-CD-iteration validation
        scoring scales with the device count too."""
        mesh = self.resolve_mesh()
        specs = list(self.evaluators) or [_DEFAULT_EVALUATOR[self.task]]
        group_ids = {
            name: (tag.codes, tag.num_groups)
            for name, tag in validation.id_tags.items()
        }
        suite = make_suite(
            specs,
            validation.labels,
            offsets=validation.offsets,
            weights=validation.weights,
            group_ids=group_ids,
            dtype=validation.labels.dtype,
        )
        scorers = {}
        for cid, cfg in self.coordinate_configs.items():
            if isinstance(cfg, RandomEffectCoordinateConfiguration):
                ds = datasets[cid]
                scorers[cid] = random_effect_scorer(
                    validation,
                    re_type=cfg.data.random_effect_type,
                    feature_shard_id=cfg.data.feature_shard_id,
                    entity_keys=ds.entity_keys,
                    proj_all=ds.proj_all,
                    width_cap=cfg.data.score_table_width_cap,
                    mesh=mesh,
                )
            else:
                scorers[cid] = fixed_effect_scorer(
                    validation, cfg.feature_shard_id, mesh
                )
        return ValidationContext(suite=suite, scorers=scorers)

    @staticmethod
    def _score_with_validation(val_ctx, model, score_sink=None):
        """Rescore a (re)loaded model against the validation set — same
        model, same scores, so it reproduces a previously recorded
        metric to float-reassociation tolerance.

        Ledger-armed runs book each coordinate's validation scorer and
        the metric suite as ``eval``-phase rows (measured host windows —
        the scorers dispatch asynchronously, so these are enqueue-to-
        enqueue costs; the suite's evaluate is the sync).

        ``score_sink`` (optional) receives the EVALUATED scores as host
        numpy — ``(scores + offsets, labels)``, the exact values the
        suite judged — after the metrics are computed. The health
        layer's calibration sketch rides this (obs/health.py
        ``calibration_sink``); the transfer happens once, post-sync,
        never inside a fit loop."""
        import time as _time

        import numpy as _np

        from photon_tpu.obs import ledger

        armed = ledger.enabled()
        total = None
        for cid, m in model.items():
            t0 = _time.perf_counter() if armed else 0.0
            vs = val_ctx.scorers[cid](m)
            total = vs if total is None else total + vs
            if armed:
                t1 = _time.perf_counter()
                ledger.record_dispatch(
                    "eval/score", t1 - t0, phase="eval",
                    coordinate=cid, start=t0, end=t1,
                )
        t0 = _time.perf_counter() if armed else 0.0
        out = val_ctx.suite.evaluate(total)
        if armed:
            t1 = _time.perf_counter()
            ledger.record_dispatch(
                "eval/suite", t1 - t0, phase="eval",
                start=t0, end=t1,
            )
        if score_sink is not None:
            score_sink(
                _np.asarray(total) + _np.asarray(val_ctx.suite.offsets),
                _np.asarray(val_ctx.suite.labels),
            )
        return out

    def evaluate_model(
        self,
        model: GameModel,
        data: GameDataset,
        validation: GameDataset,
        *,
        initial_model: GameModel | None = None,
        score_sink=None,
    ) -> EvaluationResults:
        """Evaluate an ARBITRARY GameModel (e.g. the currently-serving
        generation) against ``validation`` with this estimator's
        evaluator suite — the same scorers and metric path a
        ``fit(validation=...)`` run records, so the pilot's promotion
        gate compares candidate and incumbent through one ruler.

        ``data`` provides the per-coordinate layouts the scorers remap
        onto (the same dataset the candidate trained on); pass the same
        ``initial_model`` the fit used so ``prepare``'s cache is reused
        instead of rebuilt. Random-effect sub-models whose entity
        vocabulary or projector layout differ from the dataset's are
        remapped by (entity key, feature id) first — entities the
        layout lacks score through the fixed effect, photon-ml's
        left-join semantics. ``score_sink`` receives the evaluated
        host scores + labels (see ``_score_with_validation``) — the
        health layer's calibration feed.
        """
        import numpy as np

        datasets, val_ctx = self.prepare(
            data, validation=validation, initial_model=initial_model
        )
        if val_ctx is None:  # pragma: no cover — prepare always builds
            # a context when validation is given; belt for refactors.
            raise ValueError("evaluate_model needs a validation dataset")
        for cid in self.update_sequence:
            if cid not in model:
                continue
            m = model[cid]
            if not isinstance(m, RandomEffectModel):
                continue
            ds = datasets[cid]
            if (
                tuple(str(k) for k in m.entity_keys)
                != tuple(str(k) for k in ds.entity_keys)
                or not np.array_equal(
                    np.asarray(m.proj_all), np.asarray(ds.proj_all)
                )
            ):
                model = model.updated(
                    cid,
                    remap_random_effect_model(
                        m,
                        entity_keys=ds.entity_keys,
                        proj_all=ds.proj_all,
                    ),
                )
        return self._score_with_validation(
            val_ctx, model, score_sink=score_sink
        )

    def _full_config(self, opt_configs):
        return {
            cid: opt_configs.get(
                cid, self.coordinate_configs[cid].optimization)
            for cid in self.update_sequence
        }

    def _rebuild_completed_config(
        self, checkpointer, resume, i, opt_configs, val_ctx
    ) -> GameFitResult:
        """Rebuild a completed config's result from its retained
        config-final checkpoint (resume path). The model is the best
        model that config committed; the evaluation is recomputed by
        rescoring it against the validation set."""
        from photon_tpu.resilience.checkpoint import load_config_final

        directory = self._checkpoint_directory(checkpointer, resume)
        model = load_config_final(directory, i, resume.static_key)
        return GameFitResult(
            model=model,
            config=self._full_config(opt_configs),
            evaluation=(
                self._score_with_validation(val_ctx, model)
                if val_ctx is not None else None
            ),
            descent=None,
        )

    def _finalize_from_checkpoint(
        self, checkpointer, resume, i, opt_configs, val_ctx
    ) -> GameFitResult:
        """The crash window AFTER a config's last-iteration checkpoint
        committed but BEFORE its config-final artifact was retained:
        the descent finished (the chain holds iteration
        num_iterations-1), so rebuild the result from the chain itself —
        the final model IS the checkpoint's, the best-by-validation
        comes from the retained best artifact — and heal the missing
        config-final so later resumes take the normal path. Without
        this, a valid checkpoint is refused with 'nothing to resume' /
        'retrain from scratch' even though the run produced no results."""
        from photon_tpu.resilience.checkpoint import load_config_best

        directory = self._checkpoint_directory(checkpointer, resume)
        best_model = None
        if val_ctx is not None:
            best_model = load_config_best(
                directory, i, resume.static_key
            )
        if best_model is None:
            best_model = resume.model
        logger.info(
            "GameEstimator: config %d completed its descent before the "
            "interruption but never retained its final artifact; "
            "finalizing it from the checkpoint chain", i)
        result = GameFitResult(
            model=best_model,
            config=self._full_config(opt_configs),
            evaluation=(
                self._score_with_validation(val_ctx, best_model)
                if val_ctx is not None else None
            ),
            descent=None,
        )
        if checkpointer is not None:
            checkpointer.save_config_final(best_model, config_index=i)
        return result

    @staticmethod
    def _checkpoint_directory(checkpointer, resume) -> str:
        import os

        return (
            checkpointer.directory if checkpointer is not None
            else os.path.dirname(resume.path)
        )

    # ------------------------------------------------------------------
    # fit (GameEstimator.scala:397)
    # ------------------------------------------------------------------

    def prepare(
        self,
        data: GameDataset,
        validation: GameDataset | None = None,
        initial_model: GameModel | None = None,
    ):
        """Build (or reuse) the per-coordinate device datasets for ``data``.

        Repeated fits on the same objects (the lambda grid re-entered by the
        hyperparameter tuner, GameEstimatorEvaluationFunction.scala:40) reuse
        the ingested datasets: the build is the expensive host-side step and
        is pure in (data, initial_model, validation). Call explicitly to
        separate ingest from training (the driver's Timed sections around
        prepareTrainingDatasets)."""
        cache_key = (data, initial_model, validation)
        cached = getattr(self, "_fit_cache", None)
        if cached is not None and all(
            a is b for a, b in zip(cached[0], cache_key)
        ):
            return cached[1]
        # Release the previous generation's datasets BEFORE building the
        # new one — _primed_datasets / the fused program's operand cache
        # would otherwise pin the old device arrays through the build
        # (2x peak HBM).
        self._primed_datasets = None
        self._fused_cache = None
        self._fused_mat_share = None
        self._fit_cache = None
        # Ingest pipeline: fresh stage accounting per dataset generation
        # (raw_transfer survives — it was recorded at make_game_dataset
        # time, before any estimator existed; a still-running previous
        # warm compile is cancelled if unstarted, else its late stage
        # write is discarded by the generation token), and — when the
        # fused path and shape prediction apply — the AOT warm compile
        # starts NOW, before planning, so compile_seconds hides under
        # ingest_seconds instead of adding to it.
        from photon_tpu.data import pipeline

        stale = getattr(self, "_aot_future", None)
        if stale is not None and not stale.cancel():
            # Already running: the compile finishes in the background
            # (its stage write is discarded by the generation token).
            # Consume the orphaned future so its outcome is never
            # dropped — _warm_compile is internally exception-safe, so
            # a late exception here means that safety net broke.
            stale.add_done_callback(_log_orphaned_compile)
        pipeline.PIPELINE_STATS.reset(keep=("raw_transfer",))
        self._aot_future = None
        if self._warm_compile_eligible(validation, initial_model):
            self._aot_future = pipeline.compile_executor.submit(
                self._warm_compile, data
            )
        from photon_tpu import obs

        with obs.span("prepare"):
            datasets = self._build_datasets(data, initial_model)
            val_ctx = (
                self._build_validation(datasets, validation)
                if validation is not None
                else None
            )
        self._fit_cache = (cache_key, (datasets, val_ctx))
        return datasets, val_ctx

    def fit(
        self,
        data: GameDataset,
        validation: GameDataset | None = None,
        opt_config_sequence: (
            list[dict[str, GLMOptimizationConfiguration]] | None
        ) = None,
        initial_model: GameModel | None = None,
        *,
        init_model=None,
        checkpointer=None,
        resume=None,
    ) -> list[GameFitResult]:
        """Train one GAME model per optimization configuration.

        Configs warm-start from the previous config's trained model
        (GameEstimator.train :452-468); ``initial_model`` seeds the first
        (warm-start / partial-retrain model loading,
        GameTrainingDriver.scala:395-404).

        ``init_model`` is the day-over-day warm-start form of the same
        parameter: a ``GameModel``, or a PATH to yesterday's saved model
        loaded via ``io/model_io.load_initial_model`` (a native
        checkpoint ``.npz`` here — Avro model directories need feature
        index maps, which the CLI layer owns). Exactly one of
        ``initial_model`` / ``init_model`` may be given.

        ``checkpointer`` (a ``resilience.TrainingCheckpointer``) commits
        a crash-safe recovery point after every outer CD iteration;
        ``resume`` (a ``resilience.TrainingCheckpoint``) restarts
        mid-descent from one — the manifest's static key must match this
        estimator + config sequence (``ResumeMismatchError`` otherwise),
        completed configs are skipped, and the in-progress config
        continues at its next iteration with the SAME per-iteration
        seeds, so the resumed run converges to the uninterrupted run's
        model (within float reassociation tolerance; the initial score
        total is re-accumulated in sequence order on resume).
        Best-by-validation selection survives the crash too: the best
        model is retained as its own checkpoint artifact and reseeds
        CD's tracking on resume, and a config whose descent finished
        but whose final artifact was never retained (the crash window
        before ``save_config_final``) is finalized from the checkpoint
        chain instead of being refused.
        Checkpointing needs a host boundary per outer iteration, so an
        active checkpointer (or resume, or the non-finite guard) rides
        the unfused CD loop — crash safety trades away the whole-fit
        fused program by design.
        """
        if init_model is not None:
            if initial_model is not None:
                raise ValueError(
                    "pass exactly one of initial_model / init_model")
            if isinstance(init_model, str):
                from photon_tpu.io.model_io import load_initial_model

                init_model, digest = load_initial_model(init_model)
                logger.info(
                    "warm start from init model (digest %s...)",
                    digest[:12])
            initial_model = init_model
        if self.incremental_training:
            self._validate_incremental(initial_model)
        datasets, val_ctx = self.prepare(
            data, validation=validation, initial_model=initial_model
        )
        if opt_config_sequence is None:
            opt_config_sequence = [{}]

        start_config = 0
        resume_iteration = 0
        if resume is not None:
            from photon_tpu.resilience.checkpoint import (
                training_static_key,
            )
            from photon_tpu.resilience.errors import ResumeMismatchError

            expected = training_static_key(self, opt_config_sequence)
            if resume.static_key != expected:
                raise ResumeMismatchError(
                    "checkpoint was written by a different training "
                    f"configuration (manifest static key "
                    f"{resume.static_key[:12]}..., this run "
                    f"{expected[:12]}...): change the config back, or "
                    "start fresh / warm-start instead of resuming")
            start_config = resume.config_index
            resume_iteration = resume.iteration + 1
            if resume_iteration >= self.num_iterations:
                start_config += 1
                resume_iteration = 0
            if start_config >= len(opt_config_sequence):
                from photon_tpu.resilience.checkpoint import (
                    has_config_final,
                )

                if has_config_final(
                    self._checkpoint_directory(checkpointer, resume),
                    len(opt_config_sequence) - 1,
                ):
                    raise ValueError(
                        "checkpoint records the final configuration's "
                        "last iteration: training already completed; "
                        "nothing to resume")
                # The crash landed between the final config's last-
                # iteration checkpoint and its config-final retention:
                # nothing descends, but every config's result still
                # rebuilds below (the last one finalizing from the
                # checkpoint chain itself) — refusing here would strand
                # a run that produced no results behind 'nothing to
                # resume'.
            # The checkpoint model carries the full mid-descent state —
            # it supersedes any initial_model for the warm-start chain.
            initial_model = resume.model

        # Externally loaded RE models carry their own entity vocab / slot
        # layout; remap each ONCE onto this dataset's layout — the result
        # serves both the config-0 warm start and the incremental prior.
        if initial_model is not None:
            for cid in self.update_sequence:
                if cid not in initial_model:
                    continue
                m = initial_model[cid]
                if isinstance(m, RandomEffectModel):
                    ds = datasets[cid]
                    if (m.entity_keys is not ds.entity_keys
                            or m.proj_all is not ds.proj_all):
                        initial_model = initial_model.updated(
                            cid,
                            remap_random_effect_model(
                                m,
                                entity_keys=ds.entity_keys,
                                proj_all=ds.proj_all,
                            ),
                        )

        # Incremental training: the ORIGINAL initial model (not the previous
        # config's result) becomes the Gaussian prior for every config.
        priors: dict[str, object] = {}
        if self.incremental_training:
            for cid in self.update_sequence:
                if cid in self.locked_coordinates:
                    continue
                m = initial_model[cid]
                if isinstance(m, RandomEffectModel):
                    priors[cid] = m
                else:
                    priors[cid] = m.model.coefficients

        results: list[GameFitResult] = []
        prev_model: GameModel | None = initial_model
        primed = False
        # Crash safety needs a host boundary after every outer CD
        # iteration (the checkpoint write / the non-finite guard's
        # sync); the fused whole-fit program has none until the fit
        # completes, so these features ride the unfused loop.
        needs_host_boundary = (
            checkpointer is not None
            or resume is not None
            or self.non_finite_guard
        )
        for i, opt_configs in enumerate(opt_config_sequence):
            if i < start_config:
                # Completed before the interruption: rebuild its result
                # from the retained config-final artifact so the
                # returned list lines up with the FULL grid — otherwise
                # select_best / tuning observations / per-index artifact
                # writes silently shift and the resumed run can pick a
                # different "best" model than the uninterrupted one.
                # The config the checkpoint chain itself completed may
                # have died before retaining its final — finalize it
                # from the chain instead of refusing the resume.
                from photon_tpu.resilience.checkpoint import (
                    has_config_final,
                )

                if (
                    i == resume.config_index
                    and resume.iteration + 1 >= self.num_iterations
                    and not has_config_final(
                        self._checkpoint_directory(checkpointer, resume),
                        i,
                    )
                ):
                    results.append(self._finalize_from_checkpoint(
                        checkpointer, resume, i, opt_configs, val_ctx
                    ))
                else:
                    results.append(self._rebuild_completed_config(
                        checkpointer, resume, i, opt_configs, val_ctx
                    ))
                continue
            coords = self._build_coordinates(
                datasets, opt_configs, priors,
                logical_rows=data.num_samples,
            )
            fused = (
                self._fused_for(coords, datasets)
                if val_ctx is None and not needs_host_boundary else None
            )
            if fused is None and not primed:
                self._prime_compilations(coords, datasets)
                primed = True
            cd = CoordinateDescent(
                self.update_sequence,
                self.num_iterations,
                locked_coordinates=self.locked_coordinates,
                emitter=self.emitter,
                non_finite_guard=self.non_finite_guard,
            )
            initial_models = {}
            if prev_model is not None:
                for cid in self.update_sequence:
                    if cid not in prev_model:
                        continue
                    m = prev_model[cid]
                    if isinstance(m, RandomEffectModel):
                        ds = datasets[cid]
                        # Externally loaded models carry their own entity
                        # vocab / slot layout; re-route onto this dataset's.
                        # Within-fit warm starts share the dataset's layout
                        # objects, so the identity check skips the remap.
                        if (m.entity_keys is not ds.entity_keys
                                or m.proj_all is not ds.proj_all):
                            m = remap_random_effect_model(
                                m,
                                entity_keys=ds.entity_keys,
                                proj_all=ds.proj_all,
                            )
                    initial_models[cid] = m
            logger.info(
                "GameEstimator: config %d/%d", i + 1, len(opt_config_sequence)
            )
            # Injective seed spacing: CD uses seed+iteration internally, so
            # stride by num_iterations to keep down-sampling draws
            # independent across the lambda-config grid.
            from photon_tpu import obs

            # Resuming mid-config with validation: seed CD's best
            # tracking from the retained best artifact — the iteration
            # chain holds final-iteration state, and restarting best
            # selection from scratch would discard a pre-crash best
            # that never recurs (silently returning a worse model than
            # the uninterrupted run). The evaluation is recovered by
            # rescoring the loaded best.
            initial_best = None
            if (
                resume is not None
                and i == start_config
                and resume_iteration > 0
                and val_ctx is not None
            ):
                from photon_tpu.resilience.checkpoint import (
                    load_config_best,
                )

                best = load_config_best(
                    self._checkpoint_directory(checkpointer, resume),
                    i, resume.static_key,
                )
                if best is not None:
                    initial_best = (
                        best, self._score_with_validation(val_ctx, best)
                    )

            on_iteration = None
            if checkpointer is not None:
                # The best artifact commits BEFORE the iteration's
                # manifest: a crash in between leaves a best at most
                # one replayed iteration ahead of the cursor, which the
                # resumed replay regenerates (same seeds). Identity
                # tracking skips the write when the best didn't change.
                _saved_best = [
                    initial_best[0] if initial_best is not None else None
                ]

                def on_iteration(it, model, best, _ci=i):
                    if best is not None and best is not _saved_best[0]:
                        checkpointer.save_best(best, config_index=_ci)
                        _saved_best[0] = best
                    checkpointer.save(
                        model, config_index=_ci, iteration=it
                    )
            with obs.span(f"fit/config:{i}"):
                if fused is not None:
                    descent = fused.run(coords, initial_models or None)
                else:
                    descent = cd.run(
                        coords, initial_models or None, val_ctx,
                        seed=i * self.num_iterations,
                        start_iteration=(
                            resume_iteration if i == start_config else 0
                        ),
                        on_iteration=on_iteration,
                        initial_best=initial_best,
                    )
            full_config = self._full_config(opt_configs)
            result = GameFitResult(
                model=descent.best_model,
                config=full_config,
                evaluation=descent.best_evaluation,
                descent=descent,
            )
            results.append(result)
            if checkpointer is not None:
                # Retain this config's BEST model so a later resume can
                # rebuild this result (the per-iteration chain holds
                # final-iteration state, not best-by-validation).
                checkpointer.save_config_final(
                    descent.best_model, config_index=i
                )
            if self.emitter is not None:
                from photon_tpu.events import FitEndEvent

                self.emitter.send_event(
                    FitEndEvent(config_index=i, result=result)
                )
            prev_model = descent.model
        return results

    def _validate_incremental(self, initial_model: GameModel | None) -> None:
        """Incremental-training invariants (GameEstimator.validateParams
        :241-382): an initial model must cover every trained coordinate with
        matching shard / random-effect type and carry variances."""
        if initial_model is None:
            raise ValueError(
                "incremental training is enabled but no initial model "
                "provided")
        to_train = [
            cid for cid in self.update_sequence
            if cid not in self.locked_coordinates
        ]
        missing = [cid for cid in to_train if cid not in initial_model]
        if missing:
            raise ValueError(
                "coordinate sets don't match for incremental training; "
                f"missing coordinates: {', '.join(missing)}")
        for cid in to_train:
            cfg = self.coordinate_configs[cid]
            m = initial_model[cid]
            if isinstance(cfg, RandomEffectCoordinateConfiguration):
                if not isinstance(m, RandomEffectModel):
                    raise ValueError(
                        f"incremental training error: coordinate {cid!r} is "
                        "random-effect but the initial model is not")
                if m.feature_shard_id != cfg.data.feature_shard_id:
                    raise ValueError(
                        f"incremental training error: feature shard ID "
                        f"mismatch for coordinate {cid!r} "
                        f"({cfg.data.feature_shard_id!r} vs. "
                        f"{m.feature_shard_id!r})")
                if m.random_effect_type != cfg.data.random_effect_type:
                    raise ValueError(
                        f"incremental training error: random effect type "
                        f"mismatch for coordinate {cid!r} "
                        f"({cfg.data.random_effect_type!r} vs. "
                        f"{m.random_effect_type!r})")
                if m.variances is None:
                    raise ValueError(
                        f"incremental training error: coordinate {cid!r} "
                        "missing variance information")
            else:
                if isinstance(m, RandomEffectModel):
                    raise ValueError(
                        f"incremental training error: coordinate {cid!r} is "
                        "fixed-effect but the initial model is random-effect")
                if m.feature_shard_id != cfg.feature_shard_id:
                    raise ValueError(
                        f"incremental training error: feature shard ID "
                        f"mismatch for coordinate {cid!r} "
                        f"({cfg.feature_shard_id!r} vs. "
                        f"{m.feature_shard_id!r})")
                if m.model.coefficients.variances is None:
                    raise ValueError(
                        f"incremental training error: coordinate {cid!r} "
                        "missing variance information")

    def select_best(self, results: list[GameFitResult]) -> GameFitResult:
        """Best config by validation primary metric (selectBestModel,
        GameTrainingDriver.scala:753-793); first config when no validation."""
        best = results[0]
        for r in results[1:]:
            if r.evaluation is not None and (
                best.evaluation is None
                or best.evaluation.primary_evaluator.better_than(
                    r.evaluation.primary_evaluation,
                    best.evaluation.primary_evaluation,
                )
            ):
                best = r
        return best
