"""AOT-compiled fixed-shape score programs: the serving shape ladder.

Online traffic arrives one request at a time; XLA wants fixed shapes.
The bridge is a LADDER of batch rungs (default 1/8/64/512): one jitted
scoring function per model structure, ahead-of-time compiled at server
start for every rung through ``utils.compile_cache.aot_compile`` (the
persistent-cache wiring makes warm server starts skip the compiles
entirely), with each request batch padded up to the nearest rung.
Padded rows carry zero features and code -1, so they score 0 and are
sliced away — and because every batch size maps into the closed rung
set, the steady-state serving loop adds ZERO programs. That is the
tier-2 ``serving`` PROGRAM_AUDIT contract (declared in
``serve/__init__``, machinery in ``analysis/program.build_serving``),
which also pins that a model reload (new coefficient VALUES, same
shapes) re-enters the same executables: tables are traced operands of
the score function, never baked constants.

The scoring math is the SAME fused kernels batch scoring uses
(``models/game._score_raw_dense`` / ``_score_raw_sparse``), summed over
coordinates — online, dataset-batch, and training-time scores agree by
construction. ``score_dataset`` chunks an arbitrary ``GameDataset``
through the ladder, which is how ``cli/score.py`` routes batch scoring.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from photon_tpu.serve.tables import CoefficientTables

# Memory contract (audited by `python -m photon_tpu.analysis --memory`,
# machinery in analysis/memory.py): the expected peak-HBM of a score
# rung as a formula over the audit fixture's dims. One formula covers
# every rung (the `score_b*` pattern): the resident tables (weights at
# storage width + int32 projector) plus a fixed program scaffold, plus
# a per-row live set — the padded feature payloads, gathered per-row
# coefficients, row codes, and partial scores. The reload path's
# donating swap (tables._swap_values) must alias in compiled HLO or a
# structure reload holds both table generations resident.
MEMORY_AUDIT = dict(
    name="serving-memory",
    entry="serve.programs.ScorePrograms (score ladder rungs)",
    covers=("serving",),
    builder="build_serving_memory",
    budgets={
        "score_b*": (
            "e * s * (wbytes + 4) + d * wbytes + 120 * wbytes"
            " + rung * (d + du + 2 * s + 16) * wbytes"
        ),
    },
    donations={"serve.tables._swap_values": (0,)},
    tolerance=1.5,
)

# Tier-5 numerics contract (`--numerics`, ANALYSIS.md): the score
# ladder traced over bf16 CoefficientTables — the production serving
# precision. Score reductions against the bf16 tables must accumulate
# f32 (models/game.py acc_sum/acc_einsum); request payloads stay f32.
# Budget per rung: one table storage rounding + one f32 accumulation
# step per reduced coefficient column.
NUMERICS_AUDIT = dict(
    name="serving-numerics",
    entry="serve.programs.ScorePrograms (score ladder rungs)",
    covers=("serving",),
    builder="build_serving_numerics",
    budgets={
        "score_b*": "u16 + u32 * (d + du + 2 * s)",
    },
    deterministic={
        "score_b*:scatter": (
            "the passive-row score set (models/game.py "
            "_passive_score_set_*) scatters into unique request-row "
            "indices — each row is written at most once per batch, so "
            "no colliding writes exist to order"
        ),
    },
    tolerance=1.5,
)


@dataclasses.dataclass(frozen=True)
class ShapeLadder:
    """The closed set of batch shapes the server compiles."""

    rungs: tuple[int, ...] = (1, 8, 64, 512)

    def __post_init__(self):
        rungs = tuple(sorted(set(int(r) for r in self.rungs)))
        if not rungs or rungs[0] < 1:
            raise ValueError(f"ladder rungs must be >= 1, got {self.rungs}")
        object.__setattr__(self, "rungs", rungs)

    @property
    def max_batch(self) -> int:
        return self.rungs[-1]

    def rung_for(self, n: int) -> int:
        """Smallest rung that holds ``n`` requests."""
        if n < 1:
            raise ValueError("empty batch has no rung")
        for r in self.rungs:
            if n <= r:
                return r
        raise ValueError(
            f"batch of {n} exceeds the ladder max {self.max_batch}; "
            "split it (the queue's max_batch is clamped to the ladder)"
        )

    def chunk_plan(self, n: int) -> list[tuple[int, int, int]]:
        """(lo, hi, rung) chunks covering ``n`` rows: full max-batch
        chunks plus one padded tail rung."""
        plan: list[tuple[int, int, int]] = []
        lo = 0
        while n - lo > self.max_batch:
            plan.append((lo, lo + self.max_batch, self.max_batch))
            lo += self.max_batch
        if n - lo > 0:
            plan.append((lo, n, self.rung_for(n - lo)))
        return plan


@dataclasses.dataclass(frozen=True)
class FeatureSpec:
    """Static request layout of one feature shard.

    ``dense``: requests carry a [d] vector (stacked to [B, d]).
    ``sparse``: requests carry an ELL row pair ([k] int32 indices,
    [k] values) — the dataset batch path's layout.
    """

    kind: str  # "dense" | "sparse"
    d: int
    k: int = 0

    def __post_init__(self):
        if self.kind not in ("dense", "sparse"):
            raise ValueError(f"unknown feature spec kind {self.kind!r}")

    def sds(self, batch: int, dtype):
        import jax

        if self.kind == "dense":
            return jax.ShapeDtypeStruct((batch, self.d), dtype)
        return (
            jax.ShapeDtypeStruct((batch, self.k), np.int32),
            jax.ShapeDtypeStruct((batch, self.k), dtype),
        )

    def stack(self, rows: list, batch: int, dtype):
        """Pad ``rows`` (one request leaf each) up to [batch, ...].

        Padding rows are all-zero: zero values contribute zero margin
        whatever the padded code ends up gathering."""
        if self.kind == "dense":
            out = np.zeros((batch, self.d), dtype=dtype)
            for i, r in enumerate(rows):
                out[i] = np.asarray(r, dtype=dtype)
            return out
        idx = np.zeros((batch, self.k), dtype=np.int32)
        val = np.zeros((batch, self.k), dtype=dtype)
        for i, r in enumerate(rows):
            ri, rv = r
            idx[i] = np.asarray(ri, dtype=np.int32)
            val[i] = np.asarray(rv, dtype=dtype)
        return idx, val

    def slice_rows(self, host_leaf, lo: int, hi: int, batch: int, dtype):
        """Padded [batch, ...] chunk of a full host array set."""
        if self.kind == "dense":
            out = np.zeros((batch, self.d), dtype=dtype)
            out[: hi - lo] = host_leaf[lo:hi]
            return out
        hi_idx, hi_val = host_leaf
        idx = np.zeros((batch, self.k), dtype=np.int32)
        val = np.zeros((batch, self.k), dtype=dtype)
        idx[: hi - lo] = hi_idx[lo:hi]
        val[: hi - lo] = hi_val[lo:hi]
        return idx, val


def default_specs(tables: CoefficientTables) -> dict[str, FeatureSpec]:
    """Dense request layout per shard, sized by the widest consumer.

    A random table's implied width (max projected feature id + 1) is a
    lower bound on the true shard width; the fixed effect's is exact.
    Features beyond a random table's implied width have no subspace
    slot, so clipping there drops only coefficients that do not exist.
    """
    dims: dict[str, int] = {}
    for t in tables.fixed.values():
        dims[t.feature_shard_id] = max(
            dims.get(t.feature_shard_id, 1), t.num_features
        )
    for t in tables.random.values():
        if t.num_entities:
            dims[t.feature_shard_id] = max(
                dims.get(t.feature_shard_id, 1), t.num_features
            )
    return {s: FeatureSpec("dense", d) for s, d in dims.items()}


def specs_from_dataset(data) -> dict[str, FeatureSpec]:
    """Request layout matching a GameDataset's shards (batch path)."""
    from photon_tpu.data.dataset import DenseFeatures, SparseFeatures

    specs: dict[str, FeatureSpec] = {}
    for name, feats in data.feature_shards.items():
        if isinstance(feats, DenseFeatures):
            specs[name] = FeatureSpec("dense", int(feats.x.shape[1]))
        elif isinstance(feats, SparseFeatures):
            specs[name] = FeatureSpec(
                "sparse", int(feats.d), k=int(feats.indices.shape[1])
            )
        else:
            raise TypeError(
                f"shard {name!r}: {type(feats).__name__} has no fixed "
                "per-row serving layout (DualEll tails span rows); "
                "score it through GameTransformer"
            )
    return specs


@dataclasses.dataclass(frozen=True)
class _Inflight:
    """One dispatched-but-unfetched rung: the device value, its rung,
    the caller's live row count, and the dispatch timestamp the ledger
    window opens at."""

    out: object
    batch: int
    n: int
    t0: float


class ScorePrograms:
    """The compiled score ladder for one model structure.

    Coefficient tables are TRACED OPERANDS: ``tables.reload`` with an
    unchanged structure needs no recompile and no rebuild here — the
    next dispatch simply passes the swapped buffers. A structure change
    (``reload`` returned False) requires constructing a fresh
    ``ScorePrograms``.
    """

    def __init__(
        self,
        tables: CoefficientTables,
        *,
        ladder: ShapeLadder | None = None,
        specs: dict[str, FeatureSpec] | None = None,
        compile_now: bool = True,
    ):
        import jax

        self.tables = tables
        self.ladder = ladder or ShapeLadder()
        # Active coordinates: an EMPTY random-effect table (a model saved
        # before any entity trained, photon-ml's partial-retrain layout)
        # contributes identically zero — it is dropped from the program
        # statically rather than gathered from a zero-row array.
        self._fe_names = tuple(tables.fixed)
        self._re_names = tuple(
            n for n, t in tables.random.items() if t.num_entities
        )
        fe_shards = [tables.fixed[n].feature_shard_id for n in self._fe_names]
        re_shards = [
            tables.random[n].feature_shard_id for n in self._re_names
        ]
        self.shard_order = tuple(dict.fromkeys(fe_shards + re_shards))
        self.retype_order = tuple(
            dict.fromkeys(
                tables.random[n].random_effect_type for n in self._re_names
            )
        )
        self.specs = dict(
            specs if specs is not None else default_specs(tables)
        )
        missing = [s for s in self.shard_order if s not in self.specs]
        if missing:
            raise ValueError(f"no FeatureSpec for shard(s) {missing}")
        if not self._fe_names and not self._re_names:
            raise ValueError("model has no active coordinates to serve")
        w0 = (
            tables.fixed[self._fe_names[0]].weights
            if self._fe_names
            else tables.random[self._re_names[0]].weights
        )
        # Request/feature payload dtype: always a numpy-native float —
        # bf16-stored TABLES narrow the gathered coefficient rows, not
        # the request payloads (the score kernels cast features to the
        # table dtype at the contraction and accumulate f32).
        self.dtype = (
            np.dtype(np.float32)
            if str(w0.dtype) == "bfloat16"
            else np.dtype(str(w0.dtype))
        )

        shard_idx = {s: i for i, s in enumerate(self.shard_order)}
        fe_feat = tuple(shard_idx[s] for s in fe_shards)
        re_feat = tuple(shard_idx[s] for s in re_shards)
        # One code vector PER RANDOM-EFFECT COORDINATE, never per
        # re_type: two coordinates may share a type while training
        # distinct entity vocabularies, so a row index is only
        # meaningful against the table whose entity_keys produced it.
        re_code = tuple(range(len(self._re_names)))
        spec_kinds = tuple(
            self.specs[s].kind for s in self.shard_order
        )
        # Fused-kernel engagement is decided ONCE, at construction (the
        # PHOTON_SERVE_KERNEL auto/force/off gate + table dtype): the
        # choice is baked into the traced program, so the AOT ladder,
        # the zero-recompile contract and values-only reloads behave
        # identically on both paths — tables stay traced operands.
        from photon_tpu.ops import serve_kernel as serve_kernel_mod

        self.use_kernel = serve_kernel_mod.kernel_supported(
            str(w0.dtype)
        )

        def score_fn(fe_ws, re_ws, re_projs, feats, codes):
            import jax.numpy as jnp

            from photon_tpu.models.game import (
                _score_raw_dense,
                _score_raw_sparse,
            )
            from photon_tpu.ops import precision as precision_mod

            if self.use_kernel:
                # One fusion-boundary-free dispatch for the whole rung
                # (ops/serve_kernel.py); the per-coordinate chain below
                # stays as the fallback and the parity reference.
                return serve_kernel_mod.fused_score(
                    fe_ws, re_ws, re_projs, feats, codes,
                    spec_kinds=spec_kinds,
                    fe_feat=fe_feat,
                    re_feat=re_feat,
                )
            total = None
            for w, fi in zip(fe_ws, fe_feat):
                if spec_kinds[fi] == "dense":
                    z = precision_mod.acc_einsum(
                        "bd,d->b", feats[fi].astype(w.dtype), w
                    )
                else:
                    idx, val = feats[fi]
                    z = precision_mod.acc_sum(
                        val.astype(w.dtype) * jnp.take(w, idx), axis=-1
                    )
                total = z if total is None else total + z
            for w, proj, fi, ci in zip(re_ws, re_projs, re_feat, re_code):
                if spec_kinds[fi] == "dense":
                    z = _score_raw_dense(w, codes[ci], feats[fi], proj)
                else:
                    idx, val = feats[fi]
                    z = _score_raw_sparse(w, codes[ci], idx, val, proj)
                total = z if total is None else total + z
            if total is None:
                raise ValueError("model has no active coordinates")
            return total

        self._jitted = jax.jit(score_fn)
        self._compiled: dict[int, object] = {}
        self.stats = {
            "programs_compiled": 0,
            "aot_compile_seconds": 0.0,
            "dispatches": {int(r): 0 for r in self.ladder.rungs},
        }
        if compile_now:
            self.compile_all()

    # -- operand assembly (shared by compile, trace, and dispatch) --------

    def _table_args(self):
        t = self.tables
        # Each coordinate's table object is read ONCE so a concurrent
        # table rebuild can never pair one generation's weights with
        # another's projector within a coordinate.
        rand = [t.random[n] for n in self._re_names]
        fe_ws = tuple(t.fixed[n].weights for n in self._fe_names)
        re_ws = tuple(x.weights for x in rand)
        re_projs = tuple(x.proj for x in rand)
        return fe_ws, re_ws, re_projs

    def _sds_args(self, batch: int):
        import jax

        fe_ws, re_ws, re_projs = self._table_args()
        feats = tuple(
            self.specs[s].sds(batch, self.dtype) for s in self.shard_order
        )
        codes = tuple(
            jax.ShapeDtypeStruct((batch,), np.int32)
            for _ in self._re_names
        )
        return fe_ws, re_ws, re_projs, feats, codes

    def trace(self, batch: int):
        """Abstract trace of one rung's program — the audit entry
        (analysis/program.build_serving); the SAME operand assembly
        ``compile_rung`` lowers, so the audited jaxpr is the production
        program by construction."""
        return self._jitted.trace(*self._sds_args(batch))

    # -- compile ----------------------------------------------------------

    def compile_rung(self, batch: int):
        from photon_tpu.utils import compile_cache

        compiled = self._compiled.get(batch)
        if compiled is None:
            t0 = time.perf_counter()
            lowered = self._jitted.lower(*self._sds_args(batch))
            compiled = compile_cache.aot_compile(
                lowered, ledger_key=f"serve/score@{batch}"
            )
            self._compiled[batch] = compiled
            self.stats["programs_compiled"] += 1
            self.stats["aot_compile_seconds"] += time.perf_counter() - t0
            from photon_tpu.obs import ledger

            if ledger.enabled():
                from photon_tpu.analysis import costmodel

                # The cost thunk RE-lowers at report time rather than
                # closing over `lowered` (holding every rung's Lowered
                # alive for the server's lifetime costs more than one
                # off-path re-lower).
                ledger.register_program(
                    f"serve/score@{batch}", phase="serve",
                    cost_thunk=lambda b=batch: costmodel.program_cost(
                        self._jitted.lower(*self._sds_args(b))),
                )
        return compiled

    def compile_all(self) -> None:
        """AOT-compile every rung (server start). Warm starts hit the
        persistent compile cache; either way the request loop never
        compiles again."""
        from photon_tpu import obs

        with obs.span("serve/compile_ladder"):
            for r in self.ladder.rungs:
                self.compile_rung(r)

    # -- dispatch ---------------------------------------------------------

    def dispatch_padded(self, feats: dict, codes: dict, n: int):
        """Dispatch ``n`` stacked requests WITHOUT syncing: returns an
        in-flight handle whose device value ``fetch_padded`` pulls.

        The split exists for the queue's double-buffered staging: batch
        k+1's host pack runs while batch k is in flight, and the
        ledger's measured device window must exclude that overlapped
        host time (``fetch_padded(exclude_seconds=...)``) or staging
        would silently inflate ``vs_roofline`` on the serve rows.
        Operand validation and assembly happen HERE, before the timing
        window opens.
        """
        if not feats and not codes:
            raise ValueError("score dispatch needs at least one operand")
        some = next(iter(feats.values())) if feats else None
        batch = (
            some.shape[0]
            if isinstance(some, np.ndarray)
            else some[0].shape[0]
            if some is not None
            else next(iter(codes.values())).shape[0]
        )
        if batch not in self._compiled:
            raise ValueError(
                f"batch {batch} is not a compiled rung "
                f"{self.ladder.rungs}; pad with FeatureSpec.stack first"
            )
        fe_ws, re_ws, re_projs = self._table_args()
        f = tuple(feats[s] for s in self.shard_order)
        c = tuple(
            np.asarray(codes[nm], dtype=np.int32) for nm in self._re_names
        )
        t0 = time.perf_counter()
        out = self._compiled[batch](fe_ws, re_ws, re_projs, f, c)
        self.stats["dispatches"][batch] += 1
        return _Inflight(out=out, batch=batch, n=n, t0=t0)

    def fetch_padded(
        self, handle: "_Inflight", *, exclude_seconds: float = 0.0
    ) -> np.ndarray:
        """Block on an in-flight dispatch; returns the first ``n``
        scores as numpy (the fetch is the one host sync of the request
        path).

        ``exclude_seconds`` is host time the CALLER spent between
        dispatch and fetch on work that was overlapped with the device
        (the queue's staging pack): it is subtracted from the ledger's
        measured window so the booked seconds stay device execution,
        not an enqueue-to-fetch wall span.
        """
        scores = np.asarray(handle.out)
        t1 = time.perf_counter()
        from photon_tpu.obs import ledger

        if ledger.enabled():
            seconds = max(
                (t1 - handle.t0) - max(exclude_seconds, 0.0), 0.0
            )
            ledger.record_dispatch(
                f"serve/score@{handle.batch}", seconds, phase="serve",
                start=handle.t0, end=t1,
            )
        return scores[: handle.n]

    def score_padded(self, feats: dict, codes: dict, n: int) -> np.ndarray:
        """Score ``n`` requests already stacked per shard/coordinate.

        ``feats[shard]`` is the spec's stacked leaf at some rung batch;
        ``codes[coordinate]`` the matching [rung] int32 row-code vector
        for that random-effect coordinate's OWN table. Serial
        dispatch + fetch (the batch-scoring path and the fallback for
        duck-typed program objects without the split API).
        """
        return self.fetch_padded(self.dispatch_padded(feats, codes, n))

    def pack_requests(
        self, requests: list[tuple[dict, dict]]
    ) -> tuple[dict, dict, int]:
        """Stack [(features, entity_ids)] into padded rung operands.

        Returns (feats, codes, rung). Cold entities (and padding rows)
        get code -1 — fixed-effect-only scores.
        """
        n = len(requests)
        rung = self.ladder.rung_for(n)
        feats = {
            s: self.specs[s].stack(
                [r[0][s] for r in requests], rung, self.dtype
            )
            for s in self.shard_order
        }
        codes = {}
        for nm in self._re_names:
            table = self.tables.random[nm]
            rt = table.random_effect_type
            vec = np.full(rung, -1, dtype=np.int32)
            for i, (_, ids) in enumerate(requests):
                vec[i] = table.code_for(ids.get(rt, ""))
            codes[nm] = vec
        return feats, codes, rung

    # -- dataset batch path ----------------------------------------------

    def score_dataset(self, data) -> np.ndarray:
        """Score a whole GameDataset through the ladder (the batch-
        scoring route of ``cli/score.py`` — one scoring implementation
        for online and offline).
        """
        from photon_tpu.data.dataset import DenseFeatures
        from photon_tpu.data.random_effect import scoring_codes

        n = data.num_samples
        plan = self.ladder.chunk_plan(n)
        # Compile only the rungs this dataset's plan dispatches: a
        # 100-row file must not pay the top rung's compile (batch
        # callers construct with compile_now=False for exactly this).
        for rung in sorted({r for _, _, r in plan}):
            self.compile_rung(rung)
        host: dict[str, object] = {}
        for s in self.shard_order:
            feats = data.feature_shards[s]
            if isinstance(feats, DenseFeatures):
                host[s] = np.asarray(feats.x)
            else:
                host[s] = (
                    np.asarray(feats.indices),
                    np.asarray(feats.values),
                )
        full_codes: dict[str, np.ndarray] = {}
        for nm in self._re_names:
            table = self.tables.random[nm]
            full_codes[nm] = scoring_codes(
                data, table.random_effect_type, table.entity_keys
            ).astype(np.int32)
        out = np.zeros(n, dtype=self.dtype)
        for lo, hi, rung in plan:
            feats = {
                s: self.specs[s].slice_rows(
                    host[s], lo, hi, rung, self.dtype
                )
                for s in self.shard_order
            }
            codes = {}
            for nm, fc in full_codes.items():
                vec = np.full(rung, -1, dtype=np.int32)
                vec[: hi - lo] = fc[lo:hi]
                codes[nm] = vec
            out[lo:hi] = self.score_padded(feats, codes, hi - lo)
        return out
