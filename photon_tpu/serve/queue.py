"""The serving micro-batch queue: bounded, lingering, draining, degrading.

One worker thread owns all device dispatch; producers (request handler
threads, the synchronous driver) hand ``(features, entity_ids)`` pairs
to ``submit`` and get a ``Future`` back. The flush policy is the usual
latency/throughput dial: a batch dispatches when it reaches
``max_batch`` requests (clamped to the score ladder's top rung) OR when
the OLDEST queued request has lingered ``max_linger_s`` — small linger
= low p99, large linger = fuller batches = higher QPS. The queue is
bounded (``max_queue``): producers block for space, so an overloaded
server applies backpressure instead of growing an unbounded heap.

Degraded mode (the resilience layer). Deadlines, shedding, and the
circuit breaker default OFF, so those stay off the clean path entirely;
dispatch retry is the one knob that defaults ON
(``dispatch_retry=_DISPATCH_RETRY``: 3 attempts, 5 ms base backoff) —
a transient device fault is retried in place before any error fans
out, and a retry's backoff does stack onto that batch's latency. Pass
``dispatch_retry=None`` for the old fail-on-first-attempt semantics.

- **Deadlines**: a request submitted with ``deadline_s`` (or a queue
  ``default_deadline_s``) that is still queued when it expires FAILS
  FAST with ``DeadlineExceededError`` — before any padding or device
  work is spent on it. A late response is worth nothing; the capacity
  goes to requests that can still make their deadline. Deadlines also
  CUT THE LINGER SHORT: a batch whose earliest deadline would lapse
  mid-linger flushes early enough to dispatch in time, so a deadline
  tighter than ``max_linger_s`` is served, not expired on an idle
  device.
- **Shedding**: with ``shed_watermark`` set, a submit finding that many
  requests already queued is rejected immediately with
  ``OverloadedError`` (typed, countable) instead of blocking — the
  overloaded server stays responsive about being overloaded.
- **Circuit breaker**: ``breaker_threshold`` consecutive dispatch
  failures open the breaker — the pending queue drains with
  ``CircuitOpenError``, new submits fail fast, and ``reset_breaker()``
  re-arms after the operator (or a supervisor) intervenes. A wedged
  model never spins the worker through an unbounded failure loop.
- **Dispatch retry**: transient dispatch failures (``TransientError``,
  e.g. the injected ``serve.dispatch`` fault) are retried with backoff
  before any error fans out; deterministic failures (``PoisonError``, a
  malformed request) fan out to exactly their batch on the first
  attempt.
- **health()**: one locked snapshot — queue depth, shed / deadline /
  error / retry / breaker counters, coefficient-table generation — the
  CLI and bench surface it.
- **reload_model() / quiesce()**: hot model swap on the LIVE queue — a
  values-only refresh flips table references with dispatch running; a
  structure change compiles the new generation's ladder off-path, then
  swaps tables and the queue's program binding inside one ``quiesce``
  window (the worker parks before popping; producers keep queueing, no
  request is dropped). The pilot's promotion path and ``cli.serve
  --reload-model`` both ride this.

Request-scoped tracing (``photon_tpu.obs.trace``): with telemetry
enabled, every ``submit`` mints a process-unique request id and every
request resolves to exactly one trace record — outcome ``served``,
``expired``, ``shed``, ``breaker``, ``closed``, ``error``, or
``shutdown`` — with served requests carrying the
queue-wait → batch-fill → dispatch → scatter segment timestamps that
render as per-request async span trees in the exported ``trace.json``
(OBSERVABILITY.md). Telemetry off, each boundary is one flag check and
nothing is recorded.

Shutdown drains: ``close()`` wakes the worker, which keeps flushing
until the queue is empty, then exits; every in-flight future resolves.
``close(timeout=...)`` bounds the drain: if the worker is wedged in a
dispatch past the timeout, every still-queued future fails with
``ShutdownError`` and close returns False (the worker thread is a
daemon, so a wedged executable cannot hang process exit). A submit
after close fails fast. Exceptions from a batch dispatch fan out to
THAT batch's futures (each waiter sees the error; the worker keeps
serving subsequent batches).
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import logging
import threading
import time

import numpy as np

from photon_tpu.resilience import retry as _retry
from photon_tpu.resilience.errors import (
    CircuitOpenError,
    DeadlineExceededError,
    OverloadedError,
    ShutdownError,
)

logger = logging.getLogger(__name__)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The threading model is single-consumer: ONE worker
# thread pops, pads, dispatches, and scatters; any number of producer
# threads push. `_cond` (a Condition, which is also the mutex) guards
# the pending deque, the closed flag, the stats dict, the degraded-
# mode state (breaker open/failure-streak, the deadline-scan latch),
# and the double-buffer staging slot: `_staged` holds the NEXT batch,
# popped and host-packed by `_stage_next` while the previous batch's
# dispatch is in flight on the device (the PR 18 pipelined worker).
# The slot is only ever filled and emptied on the one worker thread —
# the lock covers its visibility to the breaker drain and to quiesce —
# so the single-consumer invariant is unchanged. The worker snapshots
# a batch UNDER the lock and dispatches OUTSIDE it, so producers never
# queue behind an XLA execution — and every future resolution
# (results, errors, deadline expiry, breaker drain, shutdown strand)
# also runs OUTSIDE the lock, because resolution runs user callbacks.
# Futures are created here (not executor-submitted) and every one is
# resolved — by the batch's results, by the batch's exception, by
# deadline expiry, by the breaker drain (which drains the staged batch
# alongside the pending deque), or by close()'s drain/timeout — so no
# waiter can hang on a dropped future.
CONCURRENCY_AUDIT = dict(
    name="serve-queue",
    locks={
        "MicroBatchQueue._cond": (
            "MicroBatchQueue._pending",
            "MicroBatchQueue._closed",
            "MicroBatchQueue._stats",
            "MicroBatchQueue._coord_stats",
            "MicroBatchQueue._breaker_open",
            "MicroBatchQueue._consecutive_failures",
            "MicroBatchQueue._has_deadlines",
            "MicroBatchQueue._close_stranded",
            "MicroBatchQueue._paused",
            "MicroBatchQueue._dispatching",
            "MicroBatchQueue._staged",
            "MicroBatchQueue.programs",
            "MicroBatchQueue._re_types",
            "MicroBatchQueue.hotness",
        ),
        "_Future._lock": (
            "_Future._callbacks",
            "_Future._value",
            "_Future._exc",
            "_Future._resolved",
        ),
    },
    thread_entries=(
        "MicroBatchQueue._worker",
        "MicroBatchQueue._dispatch",
        "MicroBatchQueue._stage_next",
        "MicroBatchQueue._pop_staged",
    ),
    jax_dispatch_ok={
        "_worker": "the worker loop itself only pops/waits/expires; "
        "all device work is in _dispatch (declared below)",
        "_dispatch": "dispatches PRE-COMPILED AOT executables only "
        "(ScorePrograms.dispatch_padded / score_padded) — no tracing, "
        "no compilation can occur on this thread (the ladder is "
        "compiled at construction on the caller's thread and the "
        "dispatch raises on an un-compiled rung); the single worker "
        "thread serializes every dispatch (the transient-retry loop "
        "re-enters the same executables with the same operands), and "
        "the fetch_padded/np.asarray fetch is the request path's one "
        "intended host sync",
        "_stage_next": "host work only: pops the next batch under "
        "_cond and packs it with ScorePrograms.pack_requests (pure "
        "numpy pad/stack/vocab lookup — no jax entry point); the "
        "device work it overlaps is the PREVIOUS batch's "
        "already-dispatched executable",
        "_pop_staged": "pops/waits under _cond only; the staged "
        "batch's device work happens in _dispatch",
    },
)


class QueueClosed(RuntimeError):
    """submit() after close()."""


# Request ids are minted at submit (every submit, including rejected
# ones) so EVERY request — served, expired, shed, breaker-failed —
# yields exactly one trace record under a process-unique id
# (obs/trace.py request-span taxonomy, OBSERVABILITY.md).
_REQUEST_IDS = itertools.count(1)


class _Request:
    __slots__ = (
        "features", "entity_ids", "future", "enqueued_at", "deadline",
        "rid", "take_ts",
    )

    def __init__(self, features: dict, entity_ids: dict,
                 deadline_s: float | None = None):
        self.features = features
        self.entity_ids = entity_ids
        self.future = _Future()
        self.rid = next(_REQUEST_IDS)
        self.enqueued_at = time.perf_counter()
        # Stamped (telemetry on only) when the worker pops the request
        # into a batch: submit→take is the queue_wait trace segment.
        self.take_ts: float | None = None
        self.deadline = (
            None if deadline_s is None
            else self.enqueued_at + float(deadline_s)
        )


def _record_request(req: _Request, outcome: str, **extra) -> None:
    """Emit one request-scoped trace record (no-op when telemetry is
    disabled). ``extra`` carries the served path's segment timestamps
    (``dispatch_ts``/``scatter_ts``/``batch``/``batch_size``) or the
    failure path's ``error``."""
    from photon_tpu import obs

    if not obs.enabled():
        return
    rec = {
        "id": req.rid,
        "outcome": outcome,
        "submit_ts": req.enqueued_at,
        "done_ts": time.perf_counter(),
    }
    if req.take_ts is not None:
        rec["take_ts"] = req.take_ts
    rec.update(extra)
    obs.trace.request(rec)


class _Future:
    """Minimal single-shot future (no executor): set exactly once by
    the worker, waited on by the producer. Done callbacks run on the
    worker thread at resolution — the driver uses them to timestamp
    completion without a per-request host thread. ``_lock`` closes the
    register-vs-resolve race: without it a callback added while the
    worker resolves could be dropped silently."""

    __slots__ = (
        "_lock", "_event", "_value", "_exc", "_callbacks", "_resolved"
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._value = None
        self._exc: BaseException | None = None
        self._callbacks: list = []
        self._resolved = False

    def _resolve(self, value, exc: BaseException | None) -> None:
        with self._lock:
            if self._resolved:
                raise RuntimeError("future resolved twice")
            self._resolved = True
            self._value = value
            self._exc = exc
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:  # outside the lock: callbacks are user code
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a raising callback must
                # not kill the worker thread (stranding every queued
                # future); same logged-and-continue contract as
                # concurrent.futures.
                logger.exception("serve future done-callback raised")
        # The event flips only AFTER the registered callbacks ran, so a
        # waiter that observes done() may rely on its callback's side
        # effects (the driver's latency append). Callbacks therefore
        # must never wait on this future themselves.
        self._event.set()

    def set_result(self, value) -> None:
        self._resolve(value, None)

    def set_exception(self, exc: BaseException) -> None:
        self._resolve(None, exc)

    def add_done_callback(self, cb) -> None:
        with self._lock:
            if not self._resolved:
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("score request still queued")
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("score request still queued")
        return self._exc


class _Staged:
    """One double-buffered batch: popped from the pending deque and
    host-packed (pad/stack/code resolution) by ``_stage_next`` while
    the PREVIOUS batch's dispatch is still in flight on the device.
    ``programs`` pins the generation the operands were packed against:
    a structure reload that adopts new programs between stage and
    dispatch invalidates ``packed`` (codes resolve against the OLD
    vocabulary), so ``_dispatch`` re-packs from ``requests`` whenever
    the identity check fails. A values-only reload keeps the programs
    object (tables swap in place) and the packed operands stay valid."""

    __slots__ = ("requests", "packed", "programs")

    def __init__(self, requests, packed, programs):
        self.requests = requests
        self.packed = packed
        self.programs = programs


# Dispatch retry default: two quick re-attempts. A transient dispatch
# failure clears in milliseconds or not at all; long backoff would just
# stack linger on every queued request behind the batch.
_DISPATCH_RETRY = _retry.RetryPolicy(
    max_attempts=3, base_delay_s=0.005, max_delay_s=0.1
)

# How far BEFORE the earliest pending deadline the linger wait flushes:
# waking exactly at the deadline would expire the request in the same
# scan that was meant to save it, and Condition.wait oversleeps by
# scheduler jitter (tens of ms observed on the loaded 2-core CI box).
# Erring early is safe — the batch just dispatches a little less full —
# erring late expires a servable request, so the slack is generous. A
# request with less budget left than this was unservable anyway.
_DEADLINE_FLUSH_SLACK_S = 25e-3


class MicroBatchQueue:
    """Bounded micro-batching front of a ``ScorePrograms`` ladder."""

    def __init__(
        self,
        programs,
        *,
        max_batch: int | None = None,
        max_linger_s: float = 0.002,
        max_queue: int = 4096,
        default_deadline_s: float | None = None,
        shed_watermark: int | None = None,
        breaker_threshold: int | None = None,
        dispatch_retry: "_retry.RetryPolicy | None" = _DISPATCH_RETRY,
        pipeline_staging: bool = True,
        close_timeout_s: float | None = None,
        slo=None,
        latency_window_s: float = 10.0,
        latency_windows: int = 6,
        hotness_k: int = 64,
    ):
        self.programs = programs
        top = programs.ladder.max_batch
        self.max_batch = min(
            top if max_batch is None else int(max_batch), top
        )
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_linger_s = float(max_linger_s)
        self.max_queue = max(int(max_queue), self.max_batch)
        self.default_deadline_s = default_deadline_s
        self.shed_watermark = (
            None if shed_watermark is None
            else max(int(shed_watermark), 1)
        )
        self.breaker_threshold = (
            None if breaker_threshold is None
            else max(int(breaker_threshold), 1)
        )
        self.dispatch_retry = dispatch_retry
        # Double-buffered staging (PR 18): while batch k's dispatch is
        # in flight, the worker pops + host-packs batch k+1 into
        # `_staged` so pad/stack/code-resolution time overlaps the
        # device round trip instead of serializing with it. False
        # restores the strictly serial worker (the byte-identical
        # parity reference for the pipelined path).
        self.pipeline_staging = bool(pipeline_staging)
        # Bounds the context-manager exit (``with`` blocks call close()
        # with no argument, which would otherwise join a wedged
        # dispatch forever).
        self.close_timeout_s = close_timeout_s
        self._cond = threading.Condition()
        self._pending: collections.deque[_Request] = collections.deque()
        self._closed = False
        self._close_stranded = False
        self._breaker_open = False
        self._consecutive_failures = 0
        # Quiesce state (``quiesce()`` / ``reload_model``): while
        # ``_paused`` the worker parks BEFORE popping a batch;
        # ``_dispatching`` is True from batch pop to dispatch return so
        # the quiescer can wait out an in-flight batch.
        self._paused = False
        self._dispatching = False
        # Staging hand-off slot: filled by _stage_next (worker thread,
        # while the previous dispatch is in flight), emptied by
        # _pop_staged / the breaker drain. Guarded by _cond.
        self._staged: _Staged | None = None
        # Latched on the first deadline-bearing submit so the worker's
        # expiry scan stays off the clean path entirely.
        self._has_deadlines = default_deadline_s is not None
        self._stats = {
            "requests": 0,
            "batches": 0,
            "batched_requests": 0,
            "cold_lookups": 0,
            "entity_lookups": 0,
            "rejected": 0,
            "dispatch_errors": 0,
            "dispatch_retries": 0,
            "deadline_expired": 0,
            "shed": 0,
            "breaker_trips": 0,
            "breaker_rejected": 0,
            "shutdown_stranded": 0,
            # Staging pipeline accounting: staged_batches counts
            # batches popped + packed AHEAD of their dispatch;
            # staging_seconds is ALL host pack time (staged or not),
            # staging_overlapped_seconds the part hidden behind an
            # in-flight device dispatch. overlap/total is the
            # `staging_overlap_fraction` surfaced on /metrics.
            "staged_batches": 0,
            "staging_seconds": 0.0,
            "staging_overlapped_seconds": 0.0,
        }
        # Live-monitoring surfaces (photon_tpu.obs.monitor; PR 9).
        # Per-COORDINATE cold/lookups counters (the global
        # cold_entity_rate hides a cold coordinate when two coordinates
        # share a re_type with different vocab coverage) ride the one
        # queue lock next to _stats; the latency window ring, the SLO
        # burn tracker, and the per-coordinate hotness sketches each
        # keep their OWN lock (obs-monitor CONCURRENCY_AUDIT) so a
        # /metrics scrape never queues behind the dispatch worker.
        from photon_tpu.obs.monitor import (
            RollingHistogram,
            SloTracker,
            SpaceSavingSketch,
        )

        random_tables = getattr(
            getattr(programs, "tables", None), "random", None
        ) or {}
        self._coord_stats = {
            name: {"entity_lookups": 0, "cold_lookups": 0}
            for name in random_tables
        }
        self._re_types = {
            name: t.random_effect_type
            for name, t in random_tables.items()
        }
        self.latency = RollingHistogram(
            window_s=latency_window_s, num_windows=latency_windows
        )
        self.slo_tracker = None if slo is None else SloTracker(slo)
        self._hotness_k = int(hotness_k)
        self.hotness = {
            name: SpaceSavingSketch(hotness_k)
            for name in random_tables
        }
        self._thread = threading.Thread(
            target=self._worker, name="photon-serve-worker",
            # Daemon: a dispatch wedged in native code past a
            # close(timeout=...) must not be able to hang process exit.
            daemon=True,
        )
        self._thread.start()

    # -- producer side ----------------------------------------------------

    def submit(self, features: dict, entity_ids: dict | None = None,
               *, deadline_s: float | None = None):
        """Queue one request; returns its Future.

        ``features`` maps feature shard id -> the spec's request leaf
        (dense: [d] vector; sparse: ([k] indices, [k] values));
        ``entity_ids`` maps random-effect type -> entity key;
        ``deadline_s`` (or the queue's ``default_deadline_s``) bounds
        how long the request may wait before it fails fast. Blocks
        while the queue is at ``max_queue`` (backpressure) unless a
        ``shed_watermark`` rejects first; raises typed errors instead
        of queueing when the queue is closed, shedding, or the
        dispatch circuit breaker is open.
        """
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = _Request(features, dict(entity_ids or {}), deadline_s)
        rejection = None  # (outcome, exc), resolved OUTSIDE the lock
        with self._cond:
            while True:
                if self._closed:
                    self._stats["rejected"] += 1
                    rejection = (
                        "closed", QueueClosed("serve queue is closed"))
                    break
                if self._breaker_open:
                    self._stats["breaker_rejected"] += 1
                    rejection = ("breaker", CircuitOpenError(
                        "serve dispatch circuit breaker is open "
                        f"(tripped after {self.breaker_threshold} "
                        "consecutive batch failures); reset_breaker() "
                        "to resume"))
                    break
                if (
                    self.shed_watermark is not None
                    and len(self._pending) >= self.shed_watermark
                ):
                    self._stats["shed"] += 1
                    rejection = ("shed", OverloadedError(
                        f"serve queue depth {len(self._pending)} is at "
                        f"the shed watermark {self.shed_watermark}; "
                        "request rejected instead of queued"))
                    break
                if len(self._pending) < self.max_queue:
                    break
                self._cond.wait()
            if rejection is None:
                if req.deadline is not None:
                    self._has_deadlines = True
                self._pending.append(req)
                self._stats["requests"] += 1
                self._cond.notify_all()
        if rejection is not None:
            # Trace emission (ring lock, registry lock on eviction)
            # stays off the queue lock — overload, the exact state that
            # takes these paths, is when the cond is hottest.
            outcome, exc = rejection
            _record_request(req, outcome)
            if self.slo_tracker is not None:
                self.slo_tracker.observe_errors(1)
            raise exc
        return req.future

    def close(self, timeout: float | None = None) -> bool:
        """Stop accepting requests, drain everything queued, join the
        worker. Idempotent.

        ``timeout`` bounds the drain-and-join: a dispatch wedged in
        native code can otherwise hang shutdown forever. On timeout,
        every request still QUEUED (never handed to the worker) fails
        with ``ShutdownError`` and close returns False — the in-flight
        batch's futures stay owned by the (daemon) worker, which will
        resolve them if the dispatch ever returns. Returns True when
        the drain completed. Once a bounded close has stranded the
        queue, a later ``close()`` with no timeout polls the wedged
        worker instead of joining it forever (the caller already opted
        into bounded shutdown).
        """
        with self._cond:
            self._closed = True
            already_stranded = self._close_stranded
            self._cond.notify_all()
        if already_stranded and timeout is None:
            # A prior bounded close already timed out and failed every
            # queued request; an unbounded join now (e.g. the ``with``
            # block exiting after a failed close(timeout=...)) would
            # reintroduce exactly the hang that close was bounded to
            # avoid. Poll the wedged worker instead of waiting on it.
            timeout = 0.0
        self._thread.join(timeout)
        if not self._thread.is_alive():
            return True
        if already_stranded:
            return False
        with self._cond:
            self._close_stranded = True
            stranded = list(self._pending)
            self._pending.clear()
            self._stats["shutdown_stranded"] += len(stranded)
            self._cond.notify_all()
        logger.error(
            "serve queue close(): drain did not finish in %.3fs; "
            "failing %d still-queued request(s) with ShutdownError",
            timeout, len(stranded))
        exc = ShutdownError(
            f"serve queue drain exceeded its {timeout}s close timeout; "
            "request abandoned before dispatch")
        for r in stranded:
            r.future.set_exception(exc)
            _record_request(r, "shutdown")
        if self.slo_tracker is not None:
            self.slo_tracker.observe_errors(len(stranded))
        return False

    def reset_breaker(self) -> None:
        """Re-arm a tripped dispatch circuit breaker (operator action
        after the underlying failure — bad model reload, device loss —
        is addressed)."""
        with self._cond:
            self._breaker_open = False
            self._consecutive_failures = 0
            self._cond.notify_all()

    @contextlib.contextmanager
    def quiesce(self):
        """Pause dispatch for the duration of the block — the swap
        window ``CoefficientTables.rebuild_from`` needs.

        Entering waits out any in-flight batch; while held, the worker
        parks BEFORE popping (no request is dispatched, none is
        dropped — producers keep queueing against the normal
        backpressure bound). Exiting resumes dispatch. Not reentrant;
        ``close()`` overrides a held pause so shutdown still drains."""
        with self._cond:
            self._paused = True
            while self._dispatching:
                self._cond.wait()
        try:
            yield self
        finally:
            with self._cond:
                self._paused = False
                self._cond.notify_all()

    def _adopt_programs_locked(self, programs) -> None:
        """Rebind the queue to a new generation's ``ScorePrograms``
        (caller holds ``_cond`` AND the quiesce pause — the worker is
        parked, so no dispatch can straddle generations). Per-coordinate
        counters and hotness sketches carry over where the coordinate
        survives the structure change and start fresh where it doesn't."""
        from photon_tpu.obs.monitor import SpaceSavingSketch

        self.programs = programs  # photon: ignore[unlocked-shared-write] -- reload_model's adopt callback holds _cond (the _locked suffix is the calling convention)
        self.max_batch = min(self.max_batch, programs.ladder.max_batch)
        random_tables = getattr(
            getattr(programs, "tables", None), "random", None
        ) or {}
        self._coord_stats = {  # photon: ignore[unlocked-shared-write] -- reload_model's adopt callback holds _cond (see docstring)
            name: self._coord_stats.get(
                name, {"entity_lookups": 0, "cold_lookups": 0}
            )
            for name in random_tables
        }
        self._re_types = {  # photon: ignore[unlocked-shared-write] -- same: caller holds _cond
            name: t.random_effect_type
            for name, t in random_tables.items()
        }
        self.hotness = {  # photon: ignore[unlocked-shared-write] -- same: caller holds _cond
            name: self.hotness.get(name)
            or SpaceSavingSketch(self._hotness_k)
            for name in random_tables
        }

    def reload_model(self, model) -> dict:
        """Hot-swap a refreshed ``GameModel`` into the LIVE queue.

        Values-only delta (the daily-retrain case): the tables' in-place
        reference swap — safe against live dispatch, zero recompiles,
        no pause. Structure change: the full ``rebuild_from`` dance —
        new tables + AOT ladder compiled off-path while the old
        generation keeps serving, then tables AND the queue's program
        binding swap inside one ``quiesce`` window. Either way no
        queued request is dropped. Returns
        ``{"values_only", "generation", "programs_compiled"}``."""
        from photon_tpu.serve.tables import CoefficientTables

        tables = self.programs.tables
        # Build the candidate at the LIVE precision: a bf16-serving
        # queue reloading an f32-trained model must stay values-only.
        new = CoefficientTables.from_game_model(model, tables.precision)
        if tables._values_only_delta(new):
            tables._reload_built(new)
            return {
                "values_only": True,
                "generation": tables.generation,
                "programs_compiled": 0,
            }

        def adopt(new_programs):
            with self._cond:
                self._adopt_programs_locked(new_programs)

        new_programs = tables.rebuild_from(
            model,
            programs=self.programs,
            quiesce=self.quiesce,
            adopt=adopt,
            prebuilt=new,
        )
        return {
            "values_only": False,
            "generation": tables.generation,
            "programs_compiled": new_programs.stats["programs_compiled"],
        }

    def __enter__(self) -> "MicroBatchQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close(self.close_timeout_s)

    def stats(self) -> dict:
        """Snapshot of the queue counters (+ derived fill/cold rates,
        per-coordinate cold counters)."""
        with self._cond:
            snap = dict(self._stats)
            snap["queued_now"] = len(self._pending)
            per_coord = {
                nm: dict(cs) for nm, cs in self._coord_stats.items()
            }
        for nm, cs in per_coord.items():
            cs["cold_entity_rate"] = (
                round(cs["cold_lookups"] / cs["entity_lookups"], 4)
                if cs["entity_lookups"]
                else None
            )
        snap["per_coordinate"] = per_coord
        if snap["batches"]:
            snap["batch_fill_fraction"] = round(
                snap["batched_requests"]
                / (snap["batches"] * self.max_batch),
                4,
            )
            snap["mean_batch_size"] = round(
                snap["batched_requests"] / snap["batches"], 2
            )
        else:
            snap["batch_fill_fraction"] = None
            snap["mean_batch_size"] = None
        snap["cold_entity_rate"] = (
            round(snap["cold_lookups"] / snap["entity_lookups"], 4)
            if snap["entity_lookups"]
            else None
        )
        # Fraction of host pack (pad/stack/code-resolution) time that
        # the pipelined worker hid behind an in-flight device dispatch.
        # 0 on the serial worker; None before any batch packed.
        snap["staging_overlap_fraction"] = (
            round(
                snap["staging_overlapped_seconds"]
                / snap["staging_seconds"],
                4,
            )
            if snap["staging_seconds"] > 0
            else None
        )
        return snap

    def health(self) -> dict:
        """One consistent degraded-mode snapshot: queue depth, breaker
        state, shed/deadline/error/retry counters, and the coefficient
        tables' reload generation — what a load balancer's health probe
        (and ``cli.serve`` / ``bench.py``) reads."""
        with self._cond:
            per_coord = {
                nm: dict(cs) for nm, cs in self._coord_stats.items()
            }
            snap = {
                "queue_depth": len(self._pending),
                "closed": self._closed,
                "breaker_open": self._breaker_open,
                "consecutive_failures": self._consecutive_failures,
                "requests": self._stats["requests"],
                "shed": self._stats["shed"],
                "deadline_expired": self._stats["deadline_expired"],
                "dispatch_errors": self._stats["dispatch_errors"],
                "dispatch_retries": self._stats["dispatch_retries"],
                "breaker_trips": self._stats["breaker_trips"],
                "breaker_rejected": self._stats["breaker_rejected"],
                "shutdown_stranded": self._stats["shutdown_stranded"],
                "staged_batches": self._stats["staged_batches"],
                "staging_overlap_fraction": (
                    round(
                        self._stats["staging_overlapped_seconds"]
                        / self._stats["staging_seconds"],
                        4,
                    )
                    if self._stats["staging_seconds"] > 0
                    else None
                ),
            }
        snap["pipeline_staging"] = self.pipeline_staging
        snap["max_queue"] = self.max_queue
        snap["shed_watermark"] = self.shed_watermark
        snap["breaker_threshold"] = self.breaker_threshold
        snap["default_deadline_s"] = self.default_deadline_s
        snap["table_generation"] = getattr(
            self.programs.tables, "generation", 0
        )
        # Live-monitoring block (obs/monitor.py): sliding-window
        # latency quantiles — the LAST N seconds, not the whole run —
        # per-coordinate cold rates (copied under the same _cond hold
        # as the rest of the snapshot), and the declared-SLO burn
        # report. The ring and the tracker snapshot under their own
        # locks, outside _cond.
        window = self.latency.quantiles_ms()
        window["window_seconds"] = (
            self.latency.window_s * self.latency.num_windows
        )
        snap["window_latency"] = window
        snap["cold_entity_rate_by_coordinate"] = {
            nm: (
                round(cs["cold_lookups"] / cs["entity_lookups"], 4)
                if cs["entity_lookups"] else None
            )
            for nm, cs in per_coord.items()
        }
        if self.slo_tracker is not None:
            snap["slo"] = self.slo_tracker.report()
        return snap

    def hotness_top(self, n: int = 10) -> dict:
        """Per-coordinate top-``n`` hottest entities (space-saving
        sketch: counts overestimate by at most their recorded error) —
        the shard/cache-planning signal of ROADMAP items 1 and 4."""
        return {
            nm: sketch.top(n) for nm, sketch in self.hotness.items()
        }

    def metrics_families(self) -> list[dict]:
        """The queue's ``/metrics`` collector (register with
        ``MonitorServer(collectors=[queue.metrics_families])``): live
        depth/breaker gauges, per-coordinate cold counters, the
        windowed-latency histogram + quantile gauges, hotness top-K,
        and the SLO burn gauges. Every number is copied under its own
        surface's lock and rendered lockless."""
        from photon_tpu.obs import monitor

        with self._cond:
            depth = len(self._pending)
            breaker = self._breaker_open
            closed = self._closed
            stats = dict(self._stats)
            per_coord = {
                nm: dict(cs) for nm, cs in self._coord_stats.items()
            }
        fams = [
            monitor.family(
                "serve_queue_depth_live", "gauge",
                "requests queued at scrape time", [("", {}, depth)],
            ),
            monitor.family(
                "serve_breaker_open_live", "gauge",
                "1 when the dispatch circuit breaker is open",
                [("", {}, float(breaker))],
            ),
            monitor.family(
                "serve_queue_closed", "gauge",
                "1 once close() was called", [("", {}, float(closed))],
            ),
            monitor.family(
                "serve_queue_requests_total", "counter",
                "requests accepted by the queue",
                [("", {}, float(stats["requests"]))],
            ),
            monitor.family(
                "serve_staging_overlap_fraction", "gauge",
                "fraction of host pad/stack time overlapped with "
                "in-flight device dispatch by the pipelined worker",
                [(
                    "", {},
                    (
                        stats["staging_overlapped_seconds"]
                        / stats["staging_seconds"]
                    )
                    if stats["staging_seconds"] > 0
                    else 0.0,
                )],
            ),
            monitor.family(
                "serve_staged_batches_total", "counter",
                "batches popped and host-packed ahead of dispatch",
                [("", {}, float(stats["staged_batches"]))],
            ),
            monitor.family(
                "serve_queue_events_total", "counter",
                "degraded-mode queue events by kind",
                [
                    ("", {"kind": k}, float(stats[k]))
                    for k in (
                        "shed", "deadline_expired", "dispatch_errors",
                        "dispatch_retries", "breaker_trips",
                        "breaker_rejected", "shutdown_stranded",
                    )
                ],
            ),
            monitor.family(
                "serve_entity_lookups_total", "counter",
                "entity lookups per random-effect coordinate",
                [
                    ("", {"coordinate": nm}, float(cs["entity_lookups"]))
                    for nm, cs in sorted(per_coord.items())
                ],
            ),
            monitor.family(
                "serve_cold_entity_lookups_total", "counter",
                "cold (out-of-vocabulary) lookups per coordinate",
                [
                    ("", {"coordinate": nm}, float(cs["cold_lookups"]))
                    for nm, cs in sorted(per_coord.items())
                ],
            ),
            self.latency.prometheus_family(
                "serve_request_latency_window_seconds",
                "submit-to-scatter latency over the sliding window "
                f"(last {self.latency.window_s * self.latency.num_windows:g}s)",
            ),
        ]
        quantiles = self.latency.quantiles_ms()
        fams.append(
            monitor.family(
                "serve_request_latency_window_ms", "gauge",
                "sliding-window latency quantiles, milliseconds",
                [
                    ("", {"quantile": str(int(q[1:q.index('_')]) / 100)}, v)
                    for q, v in quantiles.items()
                    if q.startswith("p") and v is not None
                ],
            )
        )
        hot_samples = [
            ("", {"coordinate": nm, "entity": item["key"]},
             float(item["count"]))
            for nm, items in sorted(self.hotness_top(10).items())
            for item in items
        ]
        if hot_samples:
            fams.append(
                monitor.family(
                    "serve_hot_entity_requests", "gauge",
                    "space-saving sketch count per hot entity "
                    "(overestimates by at most the sketch error)",
                    hot_samples,
                )
            )
        if self.slo_tracker is not None:
            fams.extend(self.slo_tracker.prometheus_families())
        return fams

    # -- worker side ------------------------------------------------------

    def _expire_locked(self) -> list[_Request]:
        """Pull every pending request whose deadline has passed (caller
        holds ``_cond``; the returned requests are resolved OUTSIDE the
        lock). Skipped entirely until a deadline-bearing request has
        ever been submitted."""
        if not self._has_deadlines or not self._pending:
            return []
        now = time.perf_counter()
        expired = [
            r for r in self._pending
            if r.deadline is not None and now >= r.deadline
        ]
        if expired:
            self._pending = collections.deque(  # photon: ignore[unlocked-shared-write] -- _expire_locked is called only from _take_batch's `with self._cond` scope (the _locked suffix is the calling convention)
                r for r in self._pending
                if r.deadline is None or now < r.deadline
            )
            self._stats["deadline_expired"] += len(expired)  # photon: ignore[unlocked-shared-write] -- same: caller holds _cond (see _expire_locked docstring)
            self._cond.notify_all()  # space freed: wake producers
        return expired

    def _take_batch(self):
        """Block for the next batch per the flush policy.

        Runs on the worker thread. Returns
        ``(batch, expired, depth, breaker_open)``: ``batch`` is None
        when the queue closed AND drained (exit), possibly-empty when
        only expirations happened this round; ``expired`` requests
        failed their deadline while queued and must be resolved by the
        caller (outside the lock), BEFORE any device work is spent on
        the batch; ``depth``/``breaker_open`` are sampled under the
        same lock hold so the worker's wakeup gauges cost no extra
        acquisition.
        """
        with self._cond:
            while True:
                # Quiesced: park WITHOUT popping — requests keep
                # queueing (backpressure holds) while reload_model
                # swaps the program generation. close() overrides the
                # pause so a quiesced queue still drains on shutdown.
                while self._paused and not self._closed:
                    self._cond.wait()
                expired = self._expire_locked()
                if self._pending:
                    linger_end = (
                        self._pending[0].enqueued_at + self.max_linger_s
                    )
                    while (
                        len(self._pending) < self.max_batch
                        and not self._closed
                        and not self._paused
                    ):
                        # The linger is cut short by request deadlines:
                        # a deadline that would lapse mid-linger flushes
                        # the batch _DEADLINE_FLUSH_SLACK_S early so the
                        # request DISPATCHES in time instead of expiring
                        # on an idle device (linger 200ms + deadline
                        # 100ms must serve, not fail 100%).
                        flush_at = linger_end
                        if self._has_deadlines:
                            earliest = min(
                                (r.deadline for r in self._pending
                                 if r.deadline is not None),
                                default=None,
                            )
                            if earliest is not None:
                                flush_at = min(
                                    flush_at,
                                    earliest - _DEADLINE_FLUSH_SLACK_S,
                                )
                        remaining = flush_at - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                    # A quiesce can begin WHILE the worker lingers (the
                    # pause check at the loop top is behind us): popping
                    # now would dispatch the old ladder against a
                    # mid-swap table generation. Re-park before taking
                    # anything — the pop below must only ever run with
                    # the pause flag observed clear under this lock.
                    # Already-pulled expirations are handed back first
                    # (their futures must resolve, pause or not).
                    if self._paused and not self._closed:
                        if expired:
                            return (
                                [], expired,
                                len(self._pending), self._breaker_open,
                            )
                        continue
                    # Deadlines may have lapsed during the linger wait;
                    # a request must never reach dispatch already dead.
                    expired.extend(self._expire_locked())
                    batch = [
                        self._pending.popleft()
                        for _ in range(
                            min(len(self._pending), self.max_batch)
                        )
                    ]
                    if batch:
                        self._stats["batches"] += 1
                        self._stats["batched_requests"] += len(batch)
                        # Pinned under the SAME lock hold that popped
                        # the batch: a quiescer entering now waits for
                        # this dispatch to finish — there is no window
                        # where a popped batch is invisible to quiesce.
                        self._dispatching = True
                        from photon_tpu import obs

                        if obs.enabled():
                            # submit→take is the queue_wait segment of
                            # every batched request's span tree.
                            now = time.perf_counter()
                            for r in batch:
                                r.take_ts = now
                    self._cond.notify_all()  # space freed: wake producers
                    return (
                        batch, expired,
                        len(self._pending), self._breaker_open,
                    )
                if self._closed or expired:
                    return (
                        (None if self._closed else []), expired,
                        len(self._pending), self._breaker_open,
                    )
                self._cond.wait()

    def _resolve_expired(self, expired: list[_Request]) -> None:
        """Fail a round's deadline-expired requests (worker thread,
        OUTSIDE the lock — resolution runs user callbacks). Shared by
        the serial take path and the staging pre-pop."""
        from photon_tpu import obs

        if not expired:
            return
        exc = DeadlineExceededError(
            "request deadline expired while queued; failed "
            "fast before dispatch")
        for r in expired:
            r.future.set_exception(exc)
            _record_request(r, "expired")
        if self.slo_tracker is not None:
            self.slo_tracker.observe_errors(len(expired))
        if obs.enabled():
            obs.REGISTRY.counter(
                "serve_deadline_expired_total"
            ).inc(len(expired))

    def _pop_staged(self) -> "_Staged | None":
        """Claim the staged batch, if any (worker thread). Parks while
        quiesced — same gate as ``_take_batch`` — so a staged batch can
        never dispatch inside a reload's swap window; ``_dispatching``
        flips True under the SAME lock hold that claims the batch, so
        quiesce waits out a claimed-but-not-yet-dispatched batch
        exactly as it waits out an in-flight one."""
        with self._cond:
            while self._paused and not self._closed:
                self._cond.wait()
            staged = self._staged
            if staged is None:
                return None
            self._staged = None
            self._dispatching = True
            self._cond.notify_all()
            return staged

    def _stage_next(self) -> float:
        """Pop + host-pack the NEXT batch while the current batch's
        dispatch is in flight (called from ``_dispatch`` on the worker
        thread, after ``dispatch_padded`` and before the fetch).
        Returns the seconds of pack work overlapped with the device —
        ``fetch_padded`` subtracts them from its ledger window so the
        overlap cannot inflate the serve rows' vs_roofline. No-ops
        (returns 0.0) when a staged batch already exists (a dispatch
        retry re-entered), when quiesced (the swap window must not see
        popped-but-undispatched requests pile up), or when nothing is
        pending. Pops with the same bookkeeping as ``_take_batch`` —
        expiry scan first, batches/batched_requests counters, take_ts
        stamps — but never lingers: the staging pop only fires when the
        device is already busy, so waiting for a fuller batch would
        waste exactly the overlap window this path exists to use."""
        from photon_tpu import obs

        with self._cond:
            if self._staged is not None or self._paused:
                return 0.0
            expired = self._expire_locked()
            # Pop only what the flush policy would already release — a
            # full batch, a head request whose linger lapsed (it has
            # been waiting at least as long as _take_batch would have
            # let it), or a closing queue's drain. Anything younger
            # keeps accumulating toward a fuller batch; the worker
            # falls back to the lingering _take_batch after the fetch,
            # so no request waits longer than the serial policy allows.
            flush = bool(self._pending) and (
                len(self._pending) >= self.max_batch
                or self._closed
                or (
                    self._pending[0].enqueued_at + self.max_linger_s
                    <= time.perf_counter()
                )
            )
            reqs = (
                [
                    self._pending.popleft()
                    for _ in range(
                        min(len(self._pending), self.max_batch)
                    )
                ]
                if flush
                else []
            )
            if reqs:
                self._stats["batches"] += 1
                self._stats["batched_requests"] += len(reqs)
                self._stats["staged_batches"] += 1
                if obs.enabled():
                    now = time.perf_counter()
                    for r in reqs:
                        r.take_ts = now
                self._cond.notify_all()  # space freed: wake producers
        self._resolve_expired(expired)
        if not reqs:
            return 0.0
        t0 = time.perf_counter()
        try:
            packed = self.programs.pack_requests(
                [(r.features, r.entity_ids) for r in reqs]
            )
        except Exception:  # noqa: BLE001 — staging is an optimization:
            # a pack failure here (malformed request) must surface on
            # the DISPATCH path where the retry/breaker machinery and
            # the batch's futures handle it, not kill the in-flight
            # batch's fetch. _dispatch re-packs when packed is None.
            packed = None
        dt = time.perf_counter() - t0
        with self._cond:
            self._staged = _Staged(reqs, packed, self.programs)
            self._stats["staging_seconds"] += dt
            self._stats["staging_overlapped_seconds"] += dt
            self._cond.notify_all()
        return dt

    def _worker(self) -> None:
        from photon_tpu import obs

        while True:
            # A staged batch (popped + packed while the previous
            # dispatch was in flight) goes first: its requests are
            # already off the pending deque, so _take_batch cannot see
            # them — and close() must drain them before the None exit.
            staged = self._pop_staged()
            if staged is not None:
                try:
                    self._dispatch(staged.requests, staged=staged)
                finally:
                    with self._cond:
                        self._dispatching = False
                        self._cond.notify_all()
                continue
            # depth/breaker ride out of the lock hold _take_batch
            # already has — no second _cond acquisition per wakeup.
            batch, expired, depth, breaker = self._take_batch()
            if obs.enabled():
                # Queue-pressure sampling on EVERY worker wakeup: the
                # depth gauge and breaker state land in the metrics
                # registry (where /metrics reads them) — not just in
                # the end-of-run health() snapshot. The trace counter
                # TRACK is fed from _dispatch (one sample per batch).
                obs.REGISTRY.gauge("serve_queue_depth").set(depth)
                obs.REGISTRY.gauge("serve_breaker_open").set(
                    float(breaker)
                )
            self._resolve_expired(expired)
            if batch is None:
                return
            if batch:
                try:
                    self._dispatch(batch)
                finally:
                    with self._cond:
                        self._dispatching = False
                        self._cond.notify_all()

    def _dispatch(self, batch: list[_Request],
                  staged: "_Staged | None" = None) -> None:
        """Pad, score, scatter — outside the lock (producers keep
        queuing while XLA runs). Runs on the worker thread only.
        ``staged`` carries a batch ``_stage_next`` already host-packed
        during the previous dispatch; its operands are reused when the
        program generation still matches, re-packed otherwise (a
        structure reload swapped the vocabulary out from under them).
        On the pipelined path the dispatch is split — enqueue the
        device work (``dispatch_padded``), host-pack the NEXT batch
        while it runs, then fetch — with the overlapped pack seconds
        excluded from the ledger's device window. Transient failures
        retry with backoff (``dispatch_retry``); anything else fans out
        to THIS batch's futures and feeds the circuit breaker's
        consecutive-failure count."""
        from photon_tpu import obs

        t0 = time.perf_counter()
        # Segment stamps for the request span trees (take→dispatch is
        # batch_fill, dispatch→scatter is the device round trip). A
        # retried dispatch keeps the LAST attempt's stamps — the one
        # that produced the scores the requests were served from.
        dispatch_ts = scatter_ts = None

        def attempt():
            nonlocal dispatch_ts, scatter_ts
            if (
                staged is not None
                and staged.packed is not None
                and staged.programs is self.programs
            ):
                # Packed while the previous batch was in flight — the
                # whole point of the staging pipeline. Valid because a
                # values-only reload keeps the programs object and a
                # structure reload fails the identity check above.
                feats, codes, _rung = staged.packed
            else:
                pack_t0 = time.perf_counter()
                feats, codes, _rung = self.programs.pack_requests(
                    [(r.features, r.entity_ids) for r in batch]
                )
                # Un-overlapped pack time (first batch of a burst, a
                # re-pack after a structure reload, or the serial
                # worker): counted in staging_seconds so the overlap
                # fraction's denominator is ALL pack work, not just
                # the part the pipeline managed to hide.
                pack_dt = time.perf_counter() - pack_t0
                with self._cond:
                    self._stats["staging_seconds"] += pack_dt
            # Cold lookups PER COORDINATE (codes are keyed by
            # coordinate, each resolved against its own vocabulary):
            # the aggregate hides a cold coordinate when two
            # coordinates share a re_type with different coverage.
            cold_by_coord = {
                nm: int(np.sum(vec[: len(batch)] < 0))
                for nm, vec in codes.items()
            }
            dispatch_ts = time.perf_counter()
            dp = getattr(self.programs, "dispatch_padded", None)
            with obs.span("serve/batch"):
                if self.pipeline_staging and dp is not None:
                    handle = dp(feats, codes, len(batch))
                    # Device is busy: pop + pack batch k+1 NOW. The
                    # returned pack seconds are excluded from the
                    # fetch's ledger window (satellite: overlap must
                    # not inflate vs_roofline on serve rows).
                    overlap = self._stage_next()
                    scores = self.programs.fetch_padded(
                        handle, exclude_seconds=overlap
                    )
                else:
                    # Serial fallback: pipelining off, or a programs
                    # object without the split dispatch/fetch API.
                    scores = self.programs.score_padded(
                        feats, codes, len(batch)
                    )
            scatter_ts = time.perf_counter()
            return cold_by_coord, len(codes) * len(batch), scores

        def on_retry(attempt_no, exc):
            with self._cond:
                self._stats["dispatch_retries"] += 1
            if obs.enabled():
                obs.REGISTRY.counter("serve_dispatch_retries_total").inc()

        try:
            if self.dispatch_retry is not None:
                cold_by_coord, lookups, scores = _retry.retrying_check(
                    "serve.dispatch", attempt,
                    site="serve.dispatch",
                    policy=self.dispatch_retry,
                    on_retry=on_retry,
                )
            else:
                from photon_tpu.resilience import faults

                faults.check("serve.dispatch")
                cold_by_coord, lookups, scores = attempt()
        except Exception as exc:  # noqa: BLE001 — fan out to the waiters
            drained: list[_Request] = []
            with self._cond:
                self._stats["dispatch_errors"] += 1
                self._consecutive_failures += 1
                tripped = (
                    self.breaker_threshold is not None
                    and not self._breaker_open
                    and self._consecutive_failures
                    >= self.breaker_threshold
                )
                if tripped:
                    self._breaker_open = True
                    self._stats["breaker_trips"] += 1
                    drained = list(self._pending)
                    self._pending.clear()
                    # The staged batch is popped off the deque but not
                    # yet dispatched — its futures would strand if only
                    # the deque drained.
                    if self._staged is not None:
                        drained.extend(self._staged.requests)
                        self._staged = None
                    self._cond.notify_all()
            for r in batch:
                r.future.set_exception(exc)
                _record_request(
                    r, "error", error=type(exc).__name__,
                    batch_size=len(batch),
                )
            if tripped:
                logger.error(
                    "serve dispatch circuit breaker OPEN after %d "
                    "consecutive batch failure(s) (last: %r); drained "
                    "%d queued request(s)",
                    self._consecutive_failures, exc, len(drained))
                drain_exc = CircuitOpenError(
                    "serve dispatch circuit breaker opened while this "
                    f"request was queued (last failure: {exc!r})")
                for r in drained:
                    r.future.set_exception(drain_exc)
                    _record_request(r, "breaker")
                if obs.enabled():
                    obs.REGISTRY.counter("serve_breaker_trips_total").inc()
                    obs.trace.instant(
                        "serve.breaker_open", cat="serve",
                        consecutive_failures=self._consecutive_failures,
                        drained=len(drained),
                    )
            if self.slo_tracker is not None:
                self.slo_tracker.observe_errors(len(batch) + len(drained))
            return
        # Model/data-health tap (obs/health.py; off by default): fold a
        # bounded sample of batches — raw request features + served
        # scores — into the serve-side sketch, the train/serve-skew and
        # score-distribution evidence the pilot's health gate compares
        # against the ingest sketch. Outside the queue lock (the tap
        # has its own leaf lock; obs-health CONCURRENCY_AUDIT), host
        # numpy only — the audited `health` contract pins zero impact
        # on the traced score programs.
        from photon_tpu.obs import health as _health

        if _health.enabled():
            try:
                _health.observe_serve_batch(
                    [r.features for r in batch], np.asarray(scores),
                    # Spec widths size the sparse per-feature moments
                    # to the SERVING feature space (vocabulary width),
                    # so the serve-side sketch aligns with the training
                    # sketch's moments instead of being pinned by the
                    # first sampled batch's max index.
                    widths={
                        s: self.programs.specs[s].d
                        for s in self.programs.shard_order
                    },
                )
            except Exception:  # noqa: BLE001 — telemetry must never
                # alter serving semantics: this runs on the ONE worker
                # thread with the batch already scored but its futures
                # not yet resolved; a raising tap (one malformed
                # request's feature dict) would strand the waiters AND
                # kill the worker. Same policy as validators'
                # _record_failure and the pilot's gauge export.
                logger.exception("serve health tap failed; continuing")
        cold = sum(cold_by_coord.values())
        with self._cond:
            self._consecutive_failures = 0
            self._stats["cold_lookups"] += cold
            self._stats["entity_lookups"] += lookups
            for nm, c in cold_by_coord.items():
                cs = self._coord_stats[nm]
                cs["entity_lookups"] += len(batch)
                cs["cold_lookups"] += c
            batch_no = self._stats["batches"]
            depth = len(self._pending)
        # Hotness sketches + SLO lookup budget: outside the queue lock
        # (each surface has its own lock; obs-monitor CONCURRENCY_AUDIT).
        for nm in cold_by_coord:
            sketch = self.hotness[nm]
            rt = self._re_types[nm]
            for r in batch:
                key = r.entity_ids.get(rt)
                if key is not None:
                    sketch.observe(key)
        if self.slo_tracker is not None:
            self.slo_tracker.observe_lookups(lookups, cold)
        if obs.enabled():
            obs.REGISTRY.counter("serve_requests_total").inc(len(batch))
            obs.REGISTRY.counter("serve_batches_total").inc()
            if lookups:
                obs.REGISTRY.counter("serve_cold_lookups_total").inc(cold)
            obs.REGISTRY.histogram("serve_batch_fill").observe(
                len(batch) / self.max_batch
            )
            obs.REGISTRY.histogram("serve_batch_seconds").observe(
                time.perf_counter() - t0
            )
            # Queue depth after each batch: a counter track on the
            # exported timeline (how the backlog breathes under load).
            obs.trace.counter("serve_queue_depth", depth)
        for r, s in zip(batch, scores):
            # Submit→scatter is the request's SERVICE latency — the
            # number the rolling window ring and the latency SLO judge.
            # Measured BEFORE resolution so a slow driver done-callback
            # can never inflate the served tail.
            latency = scatter_ts - r.enqueued_at
            self.latency.observe(latency)
            if self.slo_tracker is not None:
                self.slo_tracker.observe_request(latency)
            r.future.set_result(float(s))
            # done_ts lands AFTER resolution: scatter→done covers the
            # result fan-out including the driver's done-callbacks.
            _record_request(
                r, "served",
                dispatch_ts=dispatch_ts, scatter_ts=scatter_ts,
                batch=batch_no, batch_size=len(batch),
            )
