"""The serving micro-batch queue: bounded, lingering, draining.

One worker thread owns all device dispatch; producers (request handler
threads, the synchronous driver) hand ``(features, entity_ids)`` pairs
to ``submit`` and get a ``Future`` back. The flush policy is the usual
latency/throughput dial: a batch dispatches when it reaches
``max_batch`` requests (clamped to the score ladder's top rung) OR when
the OLDEST queued request has lingered ``max_linger_s`` — small linger
= low p99, large linger = fuller batches = higher QPS. The queue is
bounded (``max_queue``): producers block for space, so an overloaded
server applies backpressure instead of growing an unbounded heap.

Shutdown drains: ``close()`` wakes the worker, which keeps flushing
until the queue is empty, then exits; every in-flight future resolves.
A submit after close fails fast. Exceptions from a batch dispatch fan
out to THAT batch's futures (each waiter sees the error; the worker
keeps serving subsequent batches).
"""

from __future__ import annotations

import collections
import logging
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The threading model is single-consumer: ONE worker
# thread pops, pads, dispatches, and scatters; any number of producer
# threads push. `_cond` (a Condition, which is also the mutex) guards
# the pending deque, the closed flag, and the stats dict; the worker
# snapshots a batch UNDER the lock and dispatches OUTSIDE it, so
# producers never queue behind an XLA execution. Futures are created
# here (not executor-submitted) and every one is resolved — by the
# batch's results, by the batch's exception, or by close()'s
# drain — so no waiter can hang on a dropped future.
CONCURRENCY_AUDIT = dict(
    name="serve-queue",
    locks={
        "MicroBatchQueue._cond": (
            "MicroBatchQueue._pending",
            "MicroBatchQueue._closed",
            "MicroBatchQueue._stats",
        ),
        "_Future._lock": (
            "_Future._callbacks",
            "_Future._value",
            "_Future._exc",
            "_Future._resolved",
        ),
    },
    thread_entries=(
        "MicroBatchQueue._worker",
        "MicroBatchQueue._dispatch",
    ),
    jax_dispatch_ok={
        "_worker": "the worker loop itself only pops/waits; all device "
        "work is in _dispatch (declared below)",
        "_dispatch": "dispatches PRE-COMPILED AOT executables only "
        "(ScorePrograms.score_padded) — no tracing, no compilation can "
        "occur on this thread (the ladder is compiled at construction "
        "on the caller's thread and score_padded raises on an "
        "un-compiled rung); the single worker thread serializes every "
        "dispatch, and the np.asarray fetch is the request path's one "
        "intended host sync",
    },
)


class QueueClosed(RuntimeError):
    """submit() after close()."""


class _Request:
    __slots__ = ("features", "entity_ids", "future", "enqueued_at")

    def __init__(self, features: dict, entity_ids: dict):
        self.features = features
        self.entity_ids = entity_ids
        self.future = _Future()
        self.enqueued_at = time.perf_counter()


class _Future:
    """Minimal single-shot future (no executor): set exactly once by
    the worker, waited on by the producer. Done callbacks run on the
    worker thread at resolution — the driver uses them to timestamp
    completion without a per-request host thread. ``_lock`` closes the
    register-vs-resolve race: without it a callback added while the
    worker resolves could be dropped silently."""

    __slots__ = (
        "_lock", "_event", "_value", "_exc", "_callbacks", "_resolved"
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._value = None
        self._exc: BaseException | None = None
        self._callbacks: list = []
        self._resolved = False

    def _resolve(self, value, exc: BaseException | None) -> None:
        with self._lock:
            if self._resolved:
                raise RuntimeError("future resolved twice")
            self._resolved = True
            self._value = value
            self._exc = exc
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:  # outside the lock: callbacks are user code
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a raising callback must
                # not kill the worker thread (stranding every queued
                # future); same logged-and-continue contract as
                # concurrent.futures.
                logger.exception("serve future done-callback raised")
        # The event flips only AFTER the registered callbacks ran, so a
        # waiter that observes done() may rely on its callback's side
        # effects (the driver's latency append). Callbacks therefore
        # must never wait on this future themselves.
        self._event.set()

    def set_result(self, value) -> None:
        self._resolve(value, None)

    def set_exception(self, exc: BaseException) -> None:
        self._resolve(None, exc)

    def add_done_callback(self, cb) -> None:
        with self._lock:
            if not self._resolved:
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("score request still queued")
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("score request still queued")
        return self._exc


class MicroBatchQueue:
    """Bounded micro-batching front of a ``ScorePrograms`` ladder."""

    def __init__(
        self,
        programs,
        *,
        max_batch: int | None = None,
        max_linger_s: float = 0.002,
        max_queue: int = 4096,
    ):
        self.programs = programs
        top = programs.ladder.max_batch
        self.max_batch = min(
            top if max_batch is None else int(max_batch), top
        )
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_linger_s = float(max_linger_s)
        self.max_queue = max(int(max_queue), self.max_batch)
        self._cond = threading.Condition()
        self._pending: collections.deque[_Request] = collections.deque()
        self._closed = False
        self._stats = {
            "requests": 0,
            "batches": 0,
            "batched_requests": 0,
            "cold_lookups": 0,
            "entity_lookups": 0,
            "rejected": 0,
            "dispatch_errors": 0,
        }
        self._thread = threading.Thread(
            target=self._worker, name="photon-serve-worker"
        )
        self._thread.start()

    # -- producer side ----------------------------------------------------

    def submit(self, features: dict, entity_ids: dict | None = None):
        """Queue one request; returns its Future.

        ``features`` maps feature shard id -> the spec's request leaf
        (dense: [d] vector; sparse: ([k] indices, [k] values));
        ``entity_ids`` maps random-effect type -> entity key. Blocks
        while the queue is at ``max_queue`` (backpressure).
        """
        req = _Request(features, dict(entity_ids or {}))
        with self._cond:
            while (
                len(self._pending) >= self.max_queue and not self._closed
            ):
                self._cond.wait()
            if self._closed:
                self._stats["rejected"] += 1
                raise QueueClosed("serve queue is closed")
            self._pending.append(req)
            self._stats["requests"] += 1
            self._cond.notify_all()
        return req.future

    def close(self) -> None:
        """Stop accepting requests, drain everything queued, join the
        worker. Idempotent."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join()

    def __enter__(self) -> "MicroBatchQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        """Snapshot of the queue counters (+ derived fill/cold rates)."""
        with self._cond:
            snap = dict(self._stats)
            snap["queued_now"] = len(self._pending)
        if snap["batches"]:
            snap["batch_fill_fraction"] = round(
                snap["batched_requests"]
                / (snap["batches"] * self.max_batch),
                4,
            )
            snap["mean_batch_size"] = round(
                snap["batched_requests"] / snap["batches"], 2
            )
        else:
            snap["batch_fill_fraction"] = None
            snap["mean_batch_size"] = None
        snap["cold_entity_rate"] = (
            round(snap["cold_lookups"] / snap["entity_lookups"], 4)
            if snap["entity_lookups"]
            else None
        )
        return snap

    # -- worker side ------------------------------------------------------

    def _take_batch(self) -> list[_Request] | None:
        """Block for the next batch per the flush policy; None = exit.

        Runs on the worker thread. Returns once ``max_batch`` requests
        are pending, the oldest pending request has lingered
        ``max_linger_s``, or the queue closed (flush what remains;
        return None only when closed AND empty).
        """
        with self._cond:
            while True:
                if self._pending:
                    deadline = (
                        self._pending[0].enqueued_at + self.max_linger_s
                    )
                    while (
                        len(self._pending) < self.max_batch
                        and not self._closed
                    ):
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                    batch = [
                        self._pending.popleft()
                        for _ in range(
                            min(len(self._pending), self.max_batch)
                        )
                    ]
                    self._stats["batches"] += 1
                    self._stats["batched_requests"] += len(batch)
                    self._cond.notify_all()  # space freed: wake producers
                    return batch
                if self._closed:
                    return None
                self._cond.wait()

    def _worker(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            self._dispatch(batch)

    def _dispatch(self, batch: list[_Request]) -> None:
        """Pad, score, scatter — outside the lock (producers keep
        queuing while XLA runs). Runs on the worker thread only."""
        from photon_tpu import obs

        t0 = time.perf_counter()
        try:
            feats, codes, _rung = self.programs.pack_requests(
                [(r.features, r.entity_ids) for r in batch]
            )
            cold = sum(
                int(np.sum(vec[: len(batch)] < 0))
                for vec in codes.values()
            )
            lookups = len(codes) * len(batch)
            with obs.span("serve/batch"):
                scores = self.programs.score_padded(
                    feats, codes, len(batch)
                )
        except Exception as exc:  # noqa: BLE001 — fan out to the waiters
            with self._cond:
                self._stats["dispatch_errors"] += 1
            for r in batch:
                r.future.set_exception(exc)
            return
        with self._cond:
            self._stats["cold_lookups"] += cold
            self._stats["entity_lookups"] += lookups
        if obs.enabled():
            obs.REGISTRY.counter("serve_requests_total").inc(len(batch))
            obs.REGISTRY.counter("serve_batches_total").inc()
            if lookups:
                obs.REGISTRY.counter("serve_cold_lookups_total").inc(cold)
            obs.REGISTRY.histogram("serve_batch_fill").observe(
                len(batch) / self.max_batch
            )
            obs.REGISTRY.histogram("serve_batch_seconds").observe(
                time.perf_counter() - t0
            )
        for r, s in zip(batch, scores):
            r.future.set_result(float(s))
