"""Synchronous serving driver: feed requests, measure the tail.

No network dependency — the driver is the load generator AND the
client: it pushes requests (synthetic, or rows of a ``GameDataset``)
through a ``MicroBatchQueue`` from the calling thread, timestamps each
request's completion via a done-callback on the worker thread, and
reports the latency/throughput summary the bench and the serve CLI
emit: p50/p99 latency, QPS, batch-fill fraction, cold-entity rate.

The driver owns no threads and no locks: per-request latencies land in
a plain list appended only from the queue's single worker thread (the
done-callbacks), read only after every future resolved.
"""

from __future__ import annotations

import time

import numpy as np

from photon_tpu.serve.programs import ScorePrograms
from photon_tpu.serve.queue import MicroBatchQueue
from photon_tpu.serve.tables import CoefficientTables


def synthetic_requests(
    tables: CoefficientTables,
    programs: ScorePrograms,
    n: int,
    *,
    cold_fraction: float = 0.05,
    seed: int = 0,
) -> list[tuple[dict, dict]]:
    """``n`` synthetic ``(features, entity_ids)`` requests for a model.

    Dense feature vectors drawn N(0,1) per shard spec; entity ids drawn
    from each random table's real vocabulary, with ``cold_fraction`` of
    lookups replaced by keys the model never trained — the cold-entity
    fallback path is part of the measured workload, as it is in
    production traffic.
    """
    rng = np.random.default_rng(seed)
    reqs: list[tuple[dict, dict]] = []
    vocab = {
        rt: next(
            t.entity_keys
            for t in tables.random.values()
            if t.random_effect_type == rt
        )
        for rt in programs.retype_order
    }
    for i in range(n):
        feats = {}
        for s in programs.shard_order:
            spec = programs.specs[s]
            if spec.kind == "dense":
                feats[s] = rng.normal(size=spec.d).astype(programs.dtype)
            else:
                feats[s] = (
                    rng.integers(0, spec.d, size=spec.k).astype(np.int32),
                    rng.normal(size=spec.k).astype(programs.dtype),
                )
        ids = {}
        for rt, keys in vocab.items():
            if keys and rng.uniform() >= cold_fraction:
                ids[rt] = keys[int(rng.integers(0, len(keys)))]
            else:
                ids[rt] = f"__cold_{i}"
        reqs.append((feats, ids))
    return reqs


def dataset_requests(
    data, programs: ScorePrograms
) -> list[tuple[dict, dict]]:
    """One request per dataset row (the file-driven serve CLI path)."""
    from photon_tpu.data.dataset import DenseFeatures

    n = data.num_samples
    host: dict[str, object] = {}
    for s in programs.shard_order:
        feats = data.feature_shards[s]
        if isinstance(feats, DenseFeatures):
            host[s] = ("dense", np.asarray(feats.x))
        else:
            host[s] = (
                "sparse",
                np.asarray(feats.indices),
                np.asarray(feats.values),
            )
    tags = {
        rt: data.id_tags[rt] for rt in programs.retype_order
    }
    keys = {
        rt: [tag.inverse[c] for c in tag.host_codes()]
        for rt, tag in tags.items()
    }
    reqs: list[tuple[dict, dict]] = []
    for i in range(n):
        feats = {}
        for s, leaf in host.items():
            if leaf[0] == "dense":
                feats[s] = leaf[1][i]
            else:
                feats[s] = (leaf[1][i], leaf[2][i])
        reqs.append((feats, {rt: k[i] for rt, k in keys.items()}))
    return reqs


def traffic_loop(
    get_server,
    rate: float,
    stop,
    counts: dict,
    *,
    batch: int = 32,
    cold_fraction: float = 0.05,
    idle_sleep: float = 0.05,
    drain_timeout_s: float = 30.0,
) -> None:
    """Open-ended paced synthetic traffic against a LIVE server — the
    load generator the pilot's CLI (``--traffic-qps``) and the bench's
    pilot replay run on their own thread for a whole supervision run,
    so every promotion happens UNDER traffic.

    ``get_server()`` returns the current server-like object (anything
    with ``.programs`` and ``.submit``; ``PilotServer``) or None while
    serving is not yet up; it is re-read every ``batch`` requests so a
    hot-swapped generation is picked up. ``stop`` is a
    ``threading.Event``; ``counts`` (``served`` / ``errors`` /
    ``submit_errors`` / ``stranded`` / ``last_error``) is mutated ONLY
    from the calling thread — read it after the join. Typed queue
    rejections (shed/breaker/closed) are counted, never fatal: the
    generator outlives degraded mode. This function owns no threads and
    no locks — the CALLER spawns the thread, matching the driver's
    threading model."""
    interval = 1.0 / rate
    next_t = time.perf_counter()
    pending: list = []
    batch_no = 0
    while not stop.is_set():
        server = get_server()
        if server is None:
            time.sleep(idle_sleep)
            continue
        programs = server.programs
        try:
            reqs = synthetic_requests(
                programs.tables, programs, batch,
                cold_fraction=cold_fraction, seed=batch_no,
            )
        except Exception:  # pragma: no cover — mid-swap shapes race;
            # the next iteration reads the settled generation.
            time.sleep(0.01)
            continue
        batch_no += 1
        for feats, ids in reqs:
            if stop.is_set():
                break
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            next_t = max(
                next_t + interval, time.perf_counter() - 5 * interval
            )
            try:
                pending.append(server.submit(feats, ids))
            except Exception as exc:  # noqa: BLE001 — typed queue
                # rejections count as drops; zero-drop gates want them.
                counts["submit_errors"] += 1
                counts["last_error"] = type(exc).__name__
            while pending and pending[0].done():
                fut = pending.pop(0)
                if fut.exception() is None:
                    counts["served"] += 1
                else:
                    counts["errors"] += 1
                    counts["last_error"] = type(fut.exception()).__name__
    for fut in pending:
        try:
            exc = fut.exception(timeout=drain_timeout_s)
        except TimeoutError:
            counts["stranded"] += 1
            continue
        if exc is None:
            counts["served"] += 1
        else:
            counts["errors"] += 1
            counts["last_error"] = type(exc).__name__


def drive(
    queue: MicroBatchQueue,
    requests: list[tuple[dict, dict]],
    *,
    warmup: int | None = None,
    rate: float | None = None,
) -> dict:
    """Push ``requests`` through ``queue``; return the serving summary.

    A warmup prefix (default: one max-batch worth per ladder rung)
    exercises every compiled rung before measurement starts, so the
    p50/p99 numbers describe the steady state — and so "zero programs
    added after warmup" is checkable by the caller (compile-cache event
    deltas across the measured window).

    ``rate=None`` floods (closed-loop saturation: QPS is the ceiling and
    latency includes queueing delay behind ``max_queue``); a requests/s
    ``rate`` paces submission on a fixed schedule, making p50/p99 a
    service-latency measurement at that offered load.
    """
    ladder = queue.programs.ladder
    if warmup is None:
        warmup = min(len(requests) // 4, sum(ladder.rungs))
    warm, measured = requests[:warmup], requests[warmup:]
    if not measured:
        raise ValueError(
            f"{len(requests)} requests leave nothing to measure after "
            f"a {warmup}-request warmup"
        )

    warm_futures = [queue.submit(feats, ids) for feats, ids in warm]
    for fut in warm_futures:
        # Warmup completes (and surfaces its failures) BEFORE the
        # measured window opens — warm dispatches must not overlap it.
        fut.result()
    # Queue counters snapshot: the fill/cold numbers below are DELTAS
    # over the measured window, so they describe the same workload as
    # the latency percentiles (warmup floods in one burst and would
    # overstate steady-state batch fill).
    warm_stats = queue.stats()

    # (submit time, completion time, future) per request; appended only
    # from the queue's worker thread (the done-callback), read only
    # after every future resolved.
    completions: list[tuple[float, float, object]] = []

    def on_done(t0: float):
        def cb(fut):
            completions.append((t0, time.perf_counter(), fut))

        return cb

    futures = []
    t_start = time.perf_counter()
    for i, (feats, ids) in enumerate(measured):
        if rate:
            due = t_start + i / rate
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        t0 = time.perf_counter()
        fut = queue.submit(feats, ids)
        fut.add_done_callback(on_done(t0))
        futures.append(fut)
    errors = 0
    first_error: BaseException | None = None
    for fut in futures:
        exc = fut.exception()
        if exc is not None:
            errors += 1
            first_error = first_error or exc
    if errors == len(futures) and first_error is not None:
        raise first_error  # nothing scored: surface the real failure
    # Latency/QPS describe SERVED requests only: a failed request's
    # time-to-error is not a service latency, and counting failures as
    # throughput would let a poisoned batch IMPROVE the reported tail.
    ok = [
        (t0, td) for t0, td, f in completions if f.exception() is None
    ]
    lat = [td - t0 for t0, td in ok]
    done_at = [td for _, td in ok]
    t_end = max(done_at) if done_at else time.perf_counter()
    lat_arr = np.asarray(sorted(lat))
    wall = max(t_end - t_start, 1e-9)
    out = {
        "requests": len(measured),
        "warmup_requests": len(warm),
        "errors": errors,
        "p50_ms": round(float(np.percentile(lat_arr, 50)) * 1e3, 3),
        "p90_ms": round(float(np.percentile(lat_arr, 90)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat_arr, 99)) * 1e3, 3),
        "max_ms": round(float(lat_arr[-1]) * 1e3, 3),
        "qps": round(len(lat) / wall, 1),
        "wall_seconds": round(wall, 4),
        "offered_rate": rate,
    }
    qstats = queue.stats()
    batches = qstats["batches"] - warm_stats["batches"]
    batched = (
        qstats["batched_requests"] - warm_stats["batched_requests"]
    )
    cold = qstats["cold_lookups"] - warm_stats["cold_lookups"]
    lookups = qstats["entity_lookups"] - warm_stats["entity_lookups"]
    out["batch_fill_fraction"] = (
        round(batched / (batches * queue.max_batch), 4)
        if batches else None
    )
    out["mean_batch_size"] = (
        round(batched / batches, 2) if batches else None
    )
    out["cold_entity_rate"] = (
        round(cold / lookups, 4) if lookups else None
    )
    # Per-COORDINATE cold rates over the measured window (same delta
    # discipline as the aggregate): two coordinates sharing a re_type
    # can have very different vocabulary coverage, and the aggregate
    # hides the cold one. The old aggregate field stays for
    # compatibility.
    out["cold_entity_rate_by_coordinate"] = {}
    for nm, cs in qstats.get("per_coordinate", {}).items():
        warm_cs = warm_stats.get("per_coordinate", {}).get(
            nm, {"entity_lookups": 0, "cold_lookups": 0}
        )
        lk = cs["entity_lookups"] - warm_cs["entity_lookups"]
        cd = cs["cold_lookups"] - warm_cs["cold_lookups"]
        out["cold_entity_rate_by_coordinate"][nm] = (
            round(cd / lk, 4) if lk else None
        )
    out["batches"] = batches
    out["dispatch_errors"] = (
        qstats["dispatch_errors"] - warm_stats["dispatch_errors"]
    )
    # Staging pipeline deltas over the measured window: how much of
    # the host pad/stack time the pipelined worker hid behind in-flight
    # device dispatch (0.0 on the serial worker, None if no pack work
    # happened at all — e.g. a drive short enough to batch nothing).
    staged = qstats["staged_batches"] - warm_stats["staged_batches"]
    stage_s = (
        qstats["staging_seconds"] - warm_stats["staging_seconds"]
    )
    stage_ov = (
        qstats["staging_overlapped_seconds"]
        - warm_stats["staging_overlapped_seconds"]
    )
    out["staged_batches"] = staged
    out["staging_overlap_fraction"] = (
        round(stage_ov / stage_s, 4) if stage_s > 0 else None
    )
    # Live-monitoring surfaces (photon_tpu.obs.monitor): the sliding
    # window's p50/p99 (warmup ages out of the ring; whole-run
    # percentiles above cannot), the SLO burn report, and the
    # per-coordinate hotness top-K.
    out["window_latency"] = queue.latency.quantiles_ms()
    if queue.slo_tracker is not None:
        out["slo"] = queue.slo_tracker.report()
    out["hot_entities"] = {
        nm: [
            {"key": it["key"], "count": it["count"], "error": it["error"]}
            for it in items
        ]
        for nm, items in queue.hotness_top(5).items()
    }
    from photon_tpu import obs

    if obs.enabled():
        # Request-scoped trace rollup (outcome counts + mean segment
        # milliseconds over the ring's records — warmup included; the
        # full per-request stream is obs.trace.write_request_jsonl).
        out["request_trace"] = obs.trace.request_summary()
    if obs.health.enabled():
        # The serve-side health tap's view of this drive: sampled
        # batch/request counts + the score-distribution and request-
        # feature sketch summaries (obs/health.py serve tap).
        out["health_tap"] = obs.health.serve_snapshot()
    return out
