"""HBM-resident coefficient tables for online scoring.

A trained ``GameModel`` holds sub-models in training-time layout; serving
needs them as *lookup tables*: one dense weight vector per fixed-effect
coordinate and, per random-effect coordinate, the padded ``[E, S]``
coefficient matrix next to its ``[E, S]`` projector (original feature id
per subspace slot) on device plus a HOST map entity key -> row index.
Scoring is then pure index arithmetic against resident arrays — the same
fused kernels batch scoring uses (``models/game._score_raw_dense`` /
``_score_raw_sparse``), so online and batch scores agree by construction.

Cold entities (keys absent from the map) get code -1, which the kernels
mask to a zero random-effect contribution: the request still scores
through the fixed effect — photon-ml's left-join-with-no-match semantics.

``reload`` swaps a refreshed model into the live tables without a
recompile (coefficient arrays are traced operands, audited by the
tier-2 ``serving`` contract): the default is a reference swap that is
safe against live dispatch (in-flight batches pin the old generation),
``donate=True`` writes the new values into the OLD buffers' HBM via a
donating jitted copy for memory-constrained QUIESCED reloads; a
structure change (new entities, new coordinates) rebuilds the tables
and the caller must rebuild its programs — ``rebuild_from`` does both
in one move (new tables + new AOT ladder off-path, swap under a
caller-supplied quiesce), which is how the pilot's structure-changing
promotions and ``MicroBatchQueue.reload_model`` stay zero-downtime.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from photon_tpu.data.index_map import IndexMap
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.types import TaskType, make_feature_key

# Memory contract (audited by `python -m photon_tpu.analysis --memory`,
# machinery in analysis/memory.py): byte-exact resident formulas for the
# built tables — a fixed coordinate is its [d] weight vector at storage
# width, a random coordinate its [E,S] weights at storage width plus the
# [E,S] int32 projector (the projector never narrows under bf16) — each
# priced against the BUILT device arrays at f32 AND bf16 and against the
# admission oracle (analysis/memory.predict_resident_bytes). A
# structure-changing ``rebuild_from`` builds the next generation
# off-path while the old one serves, so its double-residency window is a
# declared transient allowance, not an accident.
MEMORY_AUDIT = dict(
    name="tables-memory",
    entry="serve.tables.CoefficientTables",
    builder="build_tables_memory",
    resident={
        "table/global": "d * wbytes",
        "table/per-user": "e * s * (wbytes + 4)",
    },
    transients={
        "rebuild_from": "2 * (d * wbytes + e * s * (wbytes + 4))",
    },
    donations={"serve.tables._swap_values": (0,)},
    tolerance=1.5,
)

_swap_cache: dict[tuple, object] = {}


def _swap_values(prev, new):
    """The donating swap body: select the new values INTO the old
    buffer. The select (rather than returning ``new`` outright) keeps
    ``prev`` in the dataflow so the donation can alias the output into
    its buffer — with an identity body jax finds no output to alias the
    donated operand to and drops the donation silently, leaving both
    generations resident (the exact failure analysis/memory.py's
    donation audit exists to catch; it probes THIS function)."""
    import jax.numpy as jnp

    return jnp.where(True, new, prev)


def _device_swap(old, new_host: np.ndarray):
    """Replace ``old``'s values with ``new_host``, donating ``old``.

    The donated input lets XLA alias the output into the old buffer's
    HBM — the reload writes the fresh coefficients into the memory the
    serving programs already read, instead of holding both generations
    resident while the transfer drains. Donation marks ``old`` deleted,
    so this path requires the serving queue QUIESCED (see
    ``CoefficientTables.reload(donate=True)``). CPU backends skip
    donation (same guard as data/pipeline._concat_chunks: the backend
    would warn on every call)."""
    import jax

    key = (tuple(old.shape), str(old.dtype))
    fn = _swap_cache.get(key)
    if fn is None:
        donate = (0,) if jax.default_backend() not in ("cpu",) else ()
        fn = jax.jit(_swap_values, donate_argnums=donate)
        _swap_cache[key] = fn
    return fn(old, new_host)


@dataclasses.dataclass
class FixedTable:
    """One fixed-effect coordinate: the dense [d] weight vector."""

    name: str
    feature_shard_id: str
    task: TaskType
    weights: object  # jax.Array [d]

    @property
    def num_features(self) -> int:
        return int(self.weights.shape[0])


@dataclasses.dataclass
class RandomTable:
    """One random-effect coordinate: padded per-entity coefficients."""

    name: str
    random_effect_type: str
    feature_shard_id: str
    task: TaskType
    weights: object  # jax.Array [E, S]
    proj: object  # jax.Array [E, S] int32, -1 pad
    entity_keys: tuple  # row i <-> entity_keys[i]
    entity_rows: dict  # str key -> row index (host map)

    @property
    def num_entities(self) -> int:
        return int(self.weights.shape[0])

    @property
    def num_features(self) -> int:
        """Original-space feature dim the projector can address. The
        model alone does not record the shard width, so this is the
        tightest bound the projector implies (features beyond it can
        never contribute — their slots do not exist)."""
        p = np.asarray(self.proj)
        return int(p.max(initial=-1)) + 1 if p.size else 1

    def code_for(self, key) -> int:
        """Row index for an entity key; -1 = cold (fixed-effect-only)."""
        row = self.entity_rows.get(str(key))
        return -1 if row is None else row


@dataclasses.dataclass
class CoefficientTables:
    """Device-resident serving state for one GameModel."""

    fixed: dict[str, FixedTable]
    random: dict[str, RandomTable]
    task: TaskType
    # Monotone model-reload counter: 0 at construction, +1 per reload
    # (in-place swap or rebuild). Surfaced by the serve queue's
    # ``health()`` so an operator can confirm which coefficient
    # generation is live without comparing arrays.
    generation: int = 0
    # Serving precision (ops/precision.py): "bfloat16" stores the
    # coefficient tables at half width — the score programs read bf16
    # and accumulate f32 (models/game.py acc_* helpers). Reloads build
    # the candidate generation at the SAME precision, so a values-only
    # refresh keeps dtypes (and with them the zero-recompile contract).
    precision: str = "float32"

    @property
    def coordinate_order(self) -> tuple[str, ...]:
        """Stable coordinate order (model iteration order) shared with
        the score-program operand layout."""
        return tuple(self.fixed) + tuple(self.random)

    @property
    def retype_order(self) -> tuple[str, ...]:
        """Distinct random-effect types in first-appearance order — one
        REQUEST entity id per type. (Row codes are per COORDINATE, not
        per type: coordinates sharing a type may hold distinct entity
        vocabularies, so each table resolves its own code.)"""
        seen: list[str] = []
        for t in self.random.values():
            if t.random_effect_type not in seen:
                seen.append(t.random_effect_type)
        return tuple(seen)

    def coordinate_stats(self) -> dict:
        """Per-coordinate shape/vocabulary facts for the monitoring and
        readiness surfaces (``cli.serve --monitor-port``'s ``/readyz``
        detail, the bench JSON): which coordinates are live, how many
        entities each random table can resolve, and the generation —
        enough to see a mis-sized vocabulary without pulling arrays."""
        return {
            "generation": self.generation,
            "fixed": {
                n: {"features": t.num_features}
                for n, t in self.fixed.items()
            },
            "random": {
                n: {
                    "entities": t.num_entities,
                    "re_type": t.random_effect_type,
                    "sub_dim": int(t.weights.shape[1]),
                }
                for n, t in self.random.items()
            },
        }

    def codes_for(self, entity_ids: dict) -> dict[str, int]:
        """Per-COORDINATE row codes for one request (-1 = cold); the
        request's entity id is keyed by the coordinate's re_type."""
        return {
            name: t.code_for(entity_ids.get(t.random_effect_type, ""))
            for name, t in self.random.items()
        }

    @staticmethod
    def from_game_model(
        model: GameModel, precision: str = "float32"
    ) -> "CoefficientTables":
        import jax
        import jax.numpy as jnp

        from photon_tpu.ops import precision as precision_mod

        resolved = precision_mod.resolve(precision)

        def put(arr):
            # bf16 table storage (serving mixed precision): half the
            # resident HBM and half the gather width per request; the
            # score kernels accumulate f32 (models/game.py).
            return jax.device_put(
                precision_mod.in_storage(jnp.asarray(arr), resolved)
            )

        fixed: dict[str, FixedTable] = {}
        random: dict[str, RandomTable] = {}
        for name, sub in model.items():
            if isinstance(sub, FixedEffectModel):
                fixed[name] = FixedTable(
                    name=name,
                    feature_shard_id=sub.feature_shard_id,
                    task=sub.task,
                    weights=put(sub.model.coefficients.means),
                )
            elif isinstance(sub, RandomEffectModel):
                keys = tuple(str(k) for k in sub.entity_keys)
                random[name] = RandomTable(
                    name=name,
                    random_effect_type=sub.random_effect_type,
                    feature_shard_id=sub.feature_shard_id,
                    task=sub.task,
                    weights=put(sub.coefficients),
                    proj=jax.device_put(
                        jnp.asarray(
                            np.asarray(sub.proj_all).astype(np.int32)
                        )
                    ),
                    entity_keys=keys,
                    entity_rows={k: i for i, k in enumerate(keys)},
                )
            else:
                raise TypeError(f"unknown sub-model type for {name!r}")
        tables = CoefficientTables(
            fixed=fixed, random=random, task=model.task,
            precision=resolved,
        )
        tables.account_resident()
        return tables

    def account_resident(self) -> None:
        """Book every table's device bytes into the cost ledger's HBM
        account (owner ``table/<coordinate>``; obs/ledger.py) — one
        flag check when the ledger is disabled. Called at build and
        after every reload, so the ledger's per-table resident bytes
        and peak watermark track the serving footprint (including the
        transient double-residency of an off-path rebuild)."""
        from photon_tpu.obs import ledger

        if not ledger.enabled():
            return
        for n, t in self.fixed.items():
            ledger.set_resident(
                f"table/{n}", ledger.tree_nbytes(t.weights)
            )
        for n, t in self.random.items():
            ledger.set_resident(
                f"table/{n}", ledger.tree_nbytes((t.weights, t.proj))
            )

    def structure_key(self) -> tuple:
        """Everything a score program specializes on: coordinate names,
        kinds, shard wiring, and array shapes/dtypes. Two models with
        equal keys serve through the SAME compiled ladder."""
        fe = tuple(
            (n, t.feature_shard_id, tuple(t.weights.shape),
             str(t.weights.dtype))
            for n, t in self.fixed.items()
        )
        re = tuple(
            (n, t.random_effect_type, t.feature_shard_id,
             tuple(t.weights.shape), str(t.weights.dtype))
            for n, t in self.random.items()
        )
        return (fe, re)

    def _values_only_delta(self, new: "CoefficientTables") -> bool:
        """True when ``new`` differs from the live tables ONLY in
        coefficient VALUES — same structure, same projectors, same
        entity vocabularies. That is the condition under which a live
        swap cannot tear: row codes stay valid across generations and
        weights are the single changing operand (each reference
        assignment is atomic)."""
        if new.structure_key() != self.structure_key():
            return False
        for name, t in self.random.items():
            src = new.random[name]
            if src.entity_keys != t.entity_keys:
                return False
            if not np.array_equal(
                np.asarray(src.proj), np.asarray(t.proj)
            ):
                return False
        return True

    def reload(self, model: GameModel, *, donate: bool = False) -> bool:
        """Swap a refreshed model's coefficients into the live tables.

        Returns True for a VALUES-ONLY refresh (same coordinates,
        shapes, dtype, projectors, and entity vocabularies — the
        daily-retrain case): each weight reference flips to the new
        generation's device array and every compiled score program
        keeps serving, since coefficients are traced operands. This
        swap is safe AGAINST LIVE DISPATCH: an in-flight batch pins the
        old buffers through its own references, row codes mean the same
        thing in both generations (vocabularies are identical), and a
        batch dispatched mid-swap at worst mixes generations ACROSS
        coordinates for that one batch.

        ``donate=True`` additionally routes each new weights array
        through a donating jitted copy so XLA may write it into the OLD
        buffer's HBM — use it for memory-constrained reloads, and ONLY
        with the queue quiesced (``close()`` or between drives):
        donation marks the old buffer deleted, which would poison a
        concurrently dispatched batch.

        Returns False for anything else — entity vocabulary or
        projector changed, coordinates added/removed, shapes/dtype
        moved: the tables are rebuilt wholesale, which is NOT safe
        under live dispatch (quiesce first), and the caller must
        rebuild its score programs if shapes changed.
        """
        return self._reload_built(
            CoefficientTables.from_game_model(model, self.precision),
            donate=donate,
        )

    def _reload_built(
        self, new: "CoefficientTables", *, donate: bool = False
    ) -> bool:
        """``reload`` against an ALREADY-BUILT new-generation tables
        object — callers that needed the structure answer before
        deciding how to swap (``MicroBatchQueue.reload_model``) avoid a
        second ``from_game_model`` device upload."""
        self.generation += 1
        if not self._values_only_delta(new):
            self.fixed = new.fixed
            self.random = new.random
            self.task = new.task
            self.account_resident()
            return False

        def swap(old, src):
            if donate:
                return _device_swap(old, np.asarray(src))
            return src

        for name, t in self.fixed.items():
            src = new.fixed[name]
            t.weights = swap(t.weights, src.weights)
            t.task = src.task
        for name, t in self.random.items():
            src = new.random[name]
            t.weights = swap(t.weights, src.weights)
            t.task = src.task
        self.task = new.task
        self.account_resident()
        return True

    def rebuild_from(
        self,
        model: GameModel,
        *,
        programs=None,
        quiesce=None,
        adopt=None,
        prebuilt: "CoefficientTables | None" = None,
    ):
        """Structure-changing reload, fully orchestrated.

        ``reload()`` returning False used to leave callers to rebuild
        the score ladder by hand; this does the whole dance: the new
        generation's tables — and, when ``programs`` (the live
        ``ScorePrograms``) is given, a freshly AOT-compiled ladder with
        the same rungs — are built OFF-PATH while the old generation
        keeps serving, then the swap happens inside ``quiesce`` (a
        context-manager factory, e.g. ``MicroBatchQueue.quiesce`` —
        None means the caller guarantees no live dispatch). ``adopt``,
        when given, is called with the new ``ScorePrograms`` INSIDE the
        quiesce window so a dispatch loop can rebind its program
        reference before traffic resumes (``reload_model`` wires it).

        A values-only delta short-circuits to the in-place ``reload``
        swap (no quiesce taken, no programs built) and returns None;
        otherwise returns the new ``ScorePrograms`` (or None when
        ``programs`` was None), rebound to THIS tables object so future
        dispatches read the live generation.
        """
        import contextlib

        new = (
            prebuilt if prebuilt is not None
            else CoefficientTables.from_game_model(model, self.precision)
        )
        if self._values_only_delta(new):
            self._reload_built(new)
            return None
        new_programs = None
        if programs is not None:
            from photon_tpu.serve.programs import ScorePrograms

            # Compile against the new generation's shapes while the old
            # ladder keeps dispatching — the expensive step stays off
            # the serving path.
            new_programs = ScorePrograms(new, ladder=programs.ladder)
        ctx = quiesce() if quiesce is not None else contextlib.nullcontext()
        with ctx:
            self.generation += 1
            self.fixed = new.fixed
            self.random = new.random
            self.task = new.task
            if new_programs is not None:
                # Rebind to the LIVE tables object: the swapped dicts
                # are the very ones the new ladder was compiled
                # against, so operand shapes cannot disagree.
                new_programs.tables = self
            if adopt is not None:
                adopt(new_programs)
        # Outside the quiesce window (host metadata only — the swap
        # pause must stay minimal): re-book the new generation's
        # footprint.
        self.account_resident()
        return new_programs


def build_index_maps_from_model(model_dir: str) -> dict[str, IndexMap]:
    """Per-shard index maps recovered from a saved model's own records.

    A standalone serving process has no training dataset to build index
    maps from; the model directory itself names every feature the model
    can use (each BayesianLinearModelAvro record keys coefficients by
    (name, term)). The union of keys per feature shard, sorted, is a
    complete and deterministic serving-side map — features the model
    never weighted are absent, which is harmless: their coefficient is
    zero either way.
    """
    from photon_tpu.io import avro
    from photon_tpu.io.model_io import COEFFICIENTS, ID_INFO

    shard_keys: dict[str, set] = {}
    for kind in ("fixed-effect", "random-effect"):
        base = os.path.join(model_dir, kind)
        if not os.path.isdir(base):
            continue
        for name in sorted(os.listdir(base)):
            info = os.path.join(base, name, ID_INFO)
            with open(info) as f:
                shard = f.read().strip().splitlines()[-1]
            keys = shard_keys.setdefault(shard, set())
            coef_dir = os.path.join(base, name, COEFFICIENTS)
            if not os.path.isdir(coef_dir):
                continue
            for rec in avro.read_container_dir(coef_dir):
                for ntv in rec["means"]:
                    keys.add(make_feature_key(ntv["name"], ntv["term"]))
                for ntv in rec.get("variances") or ():
                    keys.add(make_feature_key(ntv["name"], ntv["term"]))
    return {
        shard: IndexMap({k: i for i, k in enumerate(sorted(keys))})
        for shard, keys in shard_keys.items()
    }
