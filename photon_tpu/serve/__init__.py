"""photon_tpu.serve — AOT-compiled online scoring.

The reference ships scoring as a first-class product surface (photon-client
GameScoringDriver); this package is its low-latency twin for the TPU build:

- **Coefficient tables** (``serve/tables.py``): a trained ``GameModel``
  loaded into device-resident state — dense fixed-effect weight vectors
  plus per-coordinate random-effect tables ``[E, S]`` with their device
  projector matrices and a host entity-id -> row-index map. Unknown /
  cold entities fall back to fixed-effect-only scores (the reference's
  left-join-with-no-match semantics). ``reload`` swaps a new model in
  without a recompile — a dispatch-safe reference swap by default, or a
  donated in-place buffer update (``donate=True``) for quiesced,
  memory-constrained reloads.
- **AOT score programs** (``serve/programs.py``): ONE jitted scoring
  function per model structure, ahead-of-time compiled at server start
  for a small ladder of fixed batch shapes through
  ``utils.compile_cache.aot_compile``. Requests pad up to the nearest
  rung, so the steady-state serving loop adds ZERO programs — an audited
  contract (PROGRAM_AUDIT below), not a promise.
- **Micro-batching queue** (``serve/queue.py``): a bounded request queue
  with a latency/throughput-tunable flush policy (max batch size, max
  linger), one worker thread that pads/dispatches/scatters results back
  to per-request futures, and graceful draining shutdown — audited by
  the tier-3 concurrency gate via its declared CONCURRENCY_AUDIT.
- **Synchronous driver** (``serve/driver.py``): feeds requests from a
  dataset or a synthetic generator (no network dependency) and reports
  p50/p99 latency, QPS, batch-fill fraction, and cold-entity rate —
  the fields ``bench.py``'s ``serving`` scenario and
  ``python -m photon_tpu.cli.serve`` emit.

Architecture, tuning knobs, and the zero-recompile contract: SERVING.md.
"""

from __future__ import annotations

from photon_tpu.serve.driver import drive, synthetic_requests
from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
from photon_tpu.serve.queue import MicroBatchQueue
from photon_tpu.serve.tables import (
    CoefficientTables,
    build_index_maps_from_model,
)

# Program contract (audited by `python -m photon_tpu.analysis --semantic`;
# machinery in analysis/program.py build_serving): the serving score
# ladder must be CLOSED — every request batch size pads to one of the
# AOT-compiled rung programs (census bound = the ladder's rung count;
# a broken pad rule mints a new program and fails the census), a model
# reload with unchanged shapes re-enters the SAME executables
# (stable_under=model_reload: coefficients are traced operands, never
# baked constants), and the scoring jaxpr carries no host callback
# (hot_loop) — the request hot path never round-trips to Python.
PROGRAM_AUDIT = dict(
    name="serving",
    entry="serve.programs.ScorePrograms "
    "(AOT score ladder over serve.tables)",
    builder="build_serving",
    max_programs=3,  # == len(rungs) the builder's ladder declares
    stable_under=("request_batch", "model_reload"),
    hot_loop=True,
)

__all__ = [
    "CoefficientTables",
    "MicroBatchQueue",
    "PROGRAM_AUDIT",
    "ScorePrograms",
    "ShapeLadder",
    "build_index_maps_from_model",
    "drive",
    "synthetic_requests",
]
