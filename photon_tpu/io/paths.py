"""Date-partitioned input directory resolution.

TPU-native counterpart of photon-client util/DateRange.scala:107,
DaysRange.scala and IOUtils.getInputPathsWithinDateRange
(util/IOUtils.scala:115-150): input data laid out daily as
``baseDir/yyyy/MM/dd/<files>`` is selected by an inclusive ``yyyymmdd-
yyyymmdd`` date range, or a ``N-M`` days-ago range resolved against today.
"""

from __future__ import annotations

import dataclasses
import datetime
import os

DATE_PATTERN = "%Y%m%d"  # DateRange.DEFAULT_PATTERN "yyyyMMdd"
RANGE_DELIMITER = "-"


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] calendar range (util/DateRange.scala:107)."""

    start: datetime.date
    end: datetime.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid range: start {self.start} comes after end "
                f"{self.end}")

    @staticmethod
    def from_string(range_str: str) -> "DateRange":
        """Parse "yyyymmdd-yyyymmdd" (DateRange.fromDateString :70)."""
        parts = range_str.split(RANGE_DELIMITER)
        if len(parts) != 2:
            raise ValueError(
                f"invalid date range {range_str!r}; expected "
                "yyyymmdd-yyyymmdd")
        start = datetime.datetime.strptime(parts[0], DATE_PATTERN).date()
        end = datetime.datetime.strptime(parts[1], DATE_PATTERN).date()
        return DateRange(start, end)

    def days(self):
        d = self.start
        while d <= self.end:
            yield d
            d += datetime.timedelta(days=1)


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """Inclusive [start_days, end_days]-ago range (util/DaysRange.scala):
    "90-1" means from 90 days ago through yesterday."""

    start_days: int
    end_days: int

    def __post_init__(self):
        if self.start_days < self.end_days:
            raise ValueError(
                f"invalid days range: start {self.start_days} must be >= "
                f"end {self.end_days} (days ago)")
        if self.end_days < 0:
            raise ValueError("days ago must be non-negative")

    @staticmethod
    def from_string(range_str: str) -> "DaysRange":
        parts = range_str.split(RANGE_DELIMITER)
        if len(parts) != 2:
            raise ValueError(
                f"invalid days range {range_str!r}; expected N-M")
        return DaysRange(int(parts[0]), int(parts[1]))

    def to_date_range(
        self, today: datetime.date | None = None
    ) -> DateRange:
        today = today or datetime.date.today()
        return DateRange(
            today - datetime.timedelta(days=self.start_days),
            today - datetime.timedelta(days=self.end_days),
        )


def paths_for_date_range(
    base_dirs: list[str] | str,
    date_range: DateRange,
    *,
    error_on_missing: bool = False,
) -> list[str]:
    """Existing ``base/yyyy/MM/dd`` paths inside the range
    (IOUtils.getInputPathsWithinDateRange :115-150)."""
    if isinstance(base_dirs, str):
        base_dirs = [base_dirs]
    out: list[str] = []
    for base in base_dirs:
        found = []
        for day in date_range.days():
            p = os.path.join(
                base, f"{day.year:04d}", f"{day.month:02d}",
                f"{day.day:02d}")
            if os.path.isdir(p):
                found.append(p)
            elif error_on_missing:
                raise FileNotFoundError(
                    f"missing daily input dir {p} for {day}")
        if not found:
            raise FileNotFoundError(
                f"no daily input dirs under {base} within "
                f"{date_range.start}..{date_range.end}")
        out.extend(found)
    return out
