"""Avro training data ingest: TrainingExampleAvro -> GameDataset.

TPU-native counterpart of AvroDataReader (photon-client
data/avro/AvroDataReader.scala:54): reads TrainingExampleAvro records (uid /
label / features: [FeatureAvro name,term,value] / weight / offset /
metadataMap), merges the configured feature bags into per-shard ELL feature
matrices keyed by a feature index map (name+term joined with
Constants.DELIMITER, AvroDataReader readMerged :85-145), and surfaces
metadataMap entries as id tags (the GameDatum idTagToValueMap used for
random-effect grouping and grouped evaluation, GameConverters.scala:44).

``read_training_examples`` reads the single-bag TrainingExampleAvro layout
(one shard named "features"); ``read_merged`` is the full readMerged: each
configured shard unions one or more feature-bag record fields, with
top-level id columns and/or metadataMap entries as id tags.
"""

from __future__ import annotations

import numpy as np

from photon_tpu.data.game_data import GameDataset, make_game_dataset
from photon_tpu.data.dataset import SparseFeatures, rows_to_ell
from photon_tpu.data.index_map import IndexMap
from photon_tpu.io import avro
from photon_tpu.types import make_feature_key, split_feature_key

import jax.numpy as jnp


def build_index_map_from_records(
    records, *, add_intercept: bool = True
) -> IndexMap:
    """Scan records for distinct (name, term) keys — the DefaultIndexMap
    path (GameDriver.prepareFeatureMaps data-scan branch)."""
    keys = set()
    for rec in records:
        for f in rec["features"]:
            keys.add(make_feature_key(f["name"], f["term"]))
    return IndexMap.from_feature_names(keys, add_intercept=add_intercept)


def read_training_examples(
    path: str,
    *,
    index_map: IndexMap | None = None,
    id_tag_names: list[str] | None = None,
    add_intercept: bool = True,
    dtype=jnp.float32,
    records: list[dict] | None = None,
) -> tuple[GameDataset, IndexMap]:
    """Read a TrainingExampleAvro file/dir into a GameDataset.

    ``id_tag_names`` picks metadataMap entries to expose as id tags; when
    None all metadata keys found in the first record are used. ``records``
    supplies already-parsed Avro records for ``path`` to skip a re-parse.
    """
    if records is None:
        records = avro.read_container_dir(path)
    if not records:
        raise ValueError(f"no records in {path}")
    if id_tag_names is None:
        # Union over ALL records: any key may be absent from the first one.
        found: set[str] = set()
        for rec in records:
            found.update((rec.get("metadataMap") or {}).keys())
        id_tag_names = sorted(found)
    game, maps = read_merged(
        path,
        feature_shards={"features": ["features"]},
        index_maps=None if index_map is None else {"features": index_map},
        id_tag_names=id_tag_names,
        response_field="label",
        add_intercept=add_intercept,
        dtype=dtype,
        records=records,
    )
    return game, maps["features"]


def read_merged(
    path: str,
    *,
    feature_shards: dict[str, list[str]],
    index_maps: dict[str, IndexMap] | None = None,
    id_columns: list[str] | None = None,
    id_tag_names: list[str] | None = None,
    response_field: str | None = None,
    add_intercept: bool | dict[str, bool] = True,
    dtype=jnp.float32,
    records: list[dict] | None = None,
) -> tuple[GameDataset, dict[str, IndexMap]]:
    """Read a multi-bag Avro layout into a multi-shard GameDataset.

    The full AvroDataReader.readMerged semantics (AvroDataReader.scala
    :85-145): each feature SHARD is the union of one or more feature-bag
    record fields (FeatureShardConfiguration.featureBags) — e.g. the Yahoo!
    Music layout's ``userFeatures``/``songFeatures``/``features`` bags —
    packed into its own ELL matrix against its own index map. ``id_columns``
    exposes top-level record fields (userId, songId, ...) as id tags;
    ``id_tag_names`` additionally picks metadataMap entries. The response
    comes from ``response_field`` (auto: "response" then "label").
    ``add_intercept`` may be per-shard (FeatureShardConfiguration's
    hasIntercept flag) or one bool for all shards.
    """
    def shard_intercept(shard: str) -> bool:
        if isinstance(add_intercept, dict):
            return add_intercept.get(shard, True)
        return add_intercept
    if records is None:
        records = avro.read_container_dir(path)
    if not records:
        raise ValueError(f"no records in {path}")

    if response_field is None:
        for candidate in ("response", "label"):
            if candidate in records[0]:
                response_field = candidate
                break
        else:
            raise ValueError(
                "records carry neither 'response' nor 'label'; pass "
                "response_field explicitly")

    out_maps: dict[str, IndexMap] = {}
    for shard, bags in feature_shards.items():
        if index_maps is not None and shard in index_maps:
            out_maps[shard] = index_maps[shard]
            continue
        keys = set()
        for rec in records:
            for bag in bags:
                for f in rec.get(bag) or ():
                    keys.add(make_feature_key(f["name"], f["term"]))
        out_maps[shard] = IndexMap.from_feature_names(
            keys, add_intercept=shard_intercept(shard))

    n = len(records)
    labels = np.empty(n)
    offsets = np.zeros(n)
    weights = np.ones(n)
    uids = np.empty(n, dtype=np.int64)
    shard_rows: dict[str, list] = {shard: [] for shard in feature_shards}
    id_columns = list(id_columns or ())
    overlap = set(id_columns) & set(id_tag_names or ())
    if overlap:
        raise ValueError(
            f"id name(s) {sorted(overlap)} listed in both id_columns and "
            "id_tag_names; each id tag must come from exactly one source")
    tags: dict[str, list] = {t: [] for t in id_columns}
    for t in id_tag_names or ():
        tags.setdefault(t, [])

    for i, rec in enumerate(records):
        labels[i] = rec[response_field]
        if rec.get("offset") is not None:
            offsets[i] = rec["offset"]
        if rec.get("weight") is not None:
            weights[i] = rec["weight"]
        uids[i] = _uid_to_int(rec.get("uid"), i)
        for shard, bags in feature_shards.items():
            imap = out_maps[shard]
            row = []
            for bag in bags:
                for f in rec.get(bag) or ():
                    idx = imap.get_index(
                        make_feature_key(f["name"], f["term"]))
                    if idx is not None and f["value"] != 0.0:
                        row.append((idx, float(f["value"])))
            if imap.intercept_index is not None:
                row.append((imap.intercept_index, 1.0))
            shard_rows[shard].append(row)
        for col in id_columns:
            if col not in rec or rec[col] is None:
                raise ValueError(f"record {i} is missing id column {col!r}")
            tags[col].append(rec[col])
        meta = rec.get("metadataMap") or {}
        for t in id_tag_names or ():
            if t not in meta:
                raise ValueError(
                    f"record {i} is missing id tag {t!r} in metadataMap")
            tags[t].append(meta[t])

    shards = {}
    for shard in feature_shards:
        indices, values = rows_to_ell(
            shard_rows[shard], len(out_maps[shard]))
        # Numpy-backed: make_game_dataset keeps the host mirror (the
        # dataset-build planner reads it) and pushes the device copy once.
        shards[shard] = SparseFeatures(
            indices, values, len(out_maps[shard]))
    game = make_game_dataset(
        labels,
        shards,
        offsets=offsets,
        weights=weights,
        id_tags={t: np.asarray(v) for t, v in tags.items() if v},
        uids=uids,
        dtype=dtype,
    )
    return game, out_maps


def _uid_to_int(uid, position: int) -> int:
    """Stable int64 sample id from an Avro uid string.

    The deterministic reservoir sampling keys on these
    (build_random_effect_dataset byteswap64 hashing), so they must track the
    record's real identity — numeric uids pass through, other strings get a
    stable CRC-based hash, absent uids fall back to file position (the
    reference's GameConverters hashes the row when no uid column exists).
    """
    if uid is None:
        return position
    s = str(uid)
    try:
        return int(s)
    except ValueError:
        import zlib

        return (zlib.crc32(s.encode()) << 31) | (
            zlib.crc32(s[::-1].encode())
        )


TRAINING_EXAMPLE_SCHEMA = {
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {
            "items": {
                "name": "FeatureAvro",
                "namespace": "com.linkedin.photon.avro.generated",
                "type": "record",
                "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"},
                ],
            },
            "type": "array",
        }},
        {"name": "metadataMap", "default": None,
         "type": ["null", {"type": "map", "values": "string"}]},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}


def write_training_examples(
    path: str,
    labels,
    feature_rows,  # list of [(feature_key, value)] in name+term key form
    *,
    offsets=None,
    weights=None,
    metadata=None,  # list[dict[str, str]]
    uids=None,
) -> None:
    """TrainingExampleAvro writer (AvroDataWriter.scala:159) — used by tests
    and data-prep tooling to produce reference-format datasets."""
    labels = np.asarray(labels)

    def rec(i):
        feats = []
        for key, val in feature_rows[i]:
            name, term = split_feature_key(key)
            feats.append({"name": name, "term": term, "value": float(val)})
        return {
            "uid": None if uids is None else str(uids[i]),
            "label": float(labels[i]),
            "features": feats,
            "metadataMap": None if metadata is None else metadata[i],
            "weight": None if weights is None else float(weights[i]),
            "offset": None if offsets is None else float(offsets[i]),
        }

    avro.write_container(
        path,
        TRAINING_EXAMPLE_SCHEMA,
        (rec(i) for i in range(labels.shape[0])),
    )
