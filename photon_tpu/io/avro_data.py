"""Avro training data ingest: TrainingExampleAvro -> GameDataset.

TPU-native counterpart of AvroDataReader (photon-client
data/avro/AvroDataReader.scala:54): reads TrainingExampleAvro records (uid /
label / features: [FeatureAvro name,term,value] / weight / offset /
metadataMap), merges the configured feature bags into per-shard ELL feature
matrices keyed by a feature index map (name+term joined with
Constants.DELIMITER, AvroDataReader readMerged :85-145), and surfaces
metadataMap entries as id tags (the GameDatum idTagToValueMap used for
random-effect grouping and grouped evaluation, GameConverters.scala:44).

``read_training_examples`` reads the single-bag TrainingExampleAvro layout
(one shard named "features"); ``read_merged`` is the full readMerged: each
configured shard unions one or more feature-bag record fields, with
top-level id columns and/or metadataMap entries as id tags.
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from photon_tpu.data.game_data import GameDataset, make_game_dataset
from photon_tpu.data.dataset import SparseFeatures
from photon_tpu.data.index_map import IndexMap
from photon_tpu.io import avro
from photon_tpu.resilience.errors import CorruptShardError
from photon_tpu.types import make_feature_key, split_feature_key

import jax.numpy as jnp

# Codec-layer failure shapes a truncated or bit-rotted container
# surfaces as: varint/sync EOFs and structural ValueErrors from the
# interpreter decoder, zlib errors from a torn deflate block, struct
# errors from a cut float, KeyErrors from a half-decoded record.
_DECODE_ERRORS = (
    ValueError, EOFError, KeyError, zlib.error, struct.error,
)


def data_shard_files(path: str) -> list[str]:
    """The concrete part files a file-or-directory input resolves to
    (the HDFS part-* layout) — sorted, so iteration order is the stable
    ingest order every manifest/cursor offset is defined against."""
    if os.path.isfile(path):
        return [path]
    return [
        os.path.join(path, name)
        for name in sorted(os.listdir(path))
        if name.endswith(".avro")
    ]


def checked_iter_container_dir(path: str):
    """``avro.iter_container_dir`` with codec failures translated.

    PR 7 gave MODEL artifacts typed corruption errors; a truncated
    training DATA shard still leaked a bare ``EOFError("truncated
    varint")`` with no hint which of a directory's many part files was
    bad. Every decode failure becomes a ``CorruptShardError`` naming
    the exact FILE, so an operator (or the streaming ingest's
    quarantine policy) can act on one shard instead of rereading a
    whole day's directory.
    """
    for part in data_shard_files(path):
        try:
            yield from avro.iter_container(part)
        except _DECODE_ERRORS as exc:
            raise CorruptShardError(
                f"training data shard {part}: Avro decode failed "
                f"({type(exc).__name__}: {exc}) — the shard is "
                "truncated or not a valid container"
            ) from exc


def resolve_input_columns(
    input_columns: dict[str, str] | None,
) -> dict[str, str | None]:
    """Reserved-column name resolution, the full InputColumnsNames
    surface (InputColumnsNames.scala:80-88) — shared by ``read_merged``
    and the streaming ingest so both paths speak the same remapping."""
    cols: dict[str, str | None] = {
        "uid": "uid",
        "response": None,
        "offset": "offset",
        "weight": "weight",
        "metadataMap": "metadataMap",
    }
    if input_columns:
        unknown = sorted(set(input_columns) - set(cols))
        if unknown:
            raise ValueError(
                f"unknown input_columns key(s) {unknown}; reserved columns "
                f"are {sorted(cols)} (InputColumnsNames.scala:80-88)")
        cols.update(input_columns)
    return cols


def build_index_map_from_records(
    records, *, add_intercept: bool = True
) -> IndexMap:
    """Scan records for distinct (name, term) keys — the DefaultIndexMap
    path (GameDriver.prepareFeatureMaps data-scan branch)."""
    keys = set()
    for rec in records:
        for f in rec["features"]:
            keys.add(make_feature_key(f["name"], f["term"]))
    return IndexMap.from_feature_names(keys, add_intercept=add_intercept)


def read_training_examples(
    path: str,
    *,
    index_map: IndexMap | None = None,
    id_tag_names: list[str] | None = None,
    input_columns: dict[str, str] | None = None,
    add_intercept: bool = True,
    dtype=jnp.float32,
    records: list[dict] | None = None,
) -> tuple[GameDataset, IndexMap]:
    """Read a TrainingExampleAvro file/dir into a GameDataset.

    ``id_tag_names`` picks metadataMap entries to expose as id tags; when
    None every metadata key found in the data is used. ``input_columns``
    remaps the reserved record fields (see ``read_merged``). ``records``
    supplies already-parsed Avro records for ``path`` to skip a re-parse;
    without it the file is STREAMED block by block (peak host memory is the
    output arrays plus one decode chunk, not a list of record dicts).
    """
    response = (input_columns or {}).get("response", "label")
    game, maps = read_merged(
        path,
        feature_shards={"features": ["features"]},
        index_maps=None if index_map is None else {"features": index_map},
        id_tag_names="auto" if id_tag_names is None else id_tag_names,
        response_field=response,
        input_columns=input_columns,
        add_intercept=add_intercept,
        dtype=dtype,
        records=records,
    )
    return game, maps["features"]


_CHUNK_ROWS = 65_536


class _EllBuilder:
    """Incremental ELL assembly: rows arrive in chunks, each chunk packs at
    its own width, chunks concatenate (padded to the global max width) at
    the end. Peak memory = the final arrays + one chunk of Python rows —
    never a whole-dataset list of per-row tuples."""

    def __init__(self, num_features: int, dtype=np.float32):
        self.chunks: list[tuple[np.ndarray, np.ndarray]] = []
        self.k = 1
        self.num_features = num_features
        self.dtype = dtype

    def add_chunk(self, rows: list) -> None:
        if not rows:
            return
        k_c = max(max((len(r) for r in rows), default=0), 1)
        self.k = max(self.k, k_c)
        idx = np.zeros((len(rows), k_c), dtype=np.int32)
        val = np.zeros((len(rows), k_c), dtype=self.dtype)
        for i, row in enumerate(rows):
            for j, (fi, fv) in enumerate(row):
                idx[i, j] = fi
                val[i, j] = fv
        # Range check (rows_to_ell's guard): a non-contiguous index map
        # must raise here, not silently clamp inside the device gather.
        if idx.size and (int(idx.max()) >= self.num_features
                         or int(idx.min()) < 0):
            raise ValueError(
                f"feature index out of range [0, {self.num_features}): "
                f"min {int(idx.min())}, max {int(idx.max())}"
            )
        self.chunks.append((idx, val))

    def finish(self) -> tuple[np.ndarray, np.ndarray]:
        if not self.chunks:
            return (np.zeros((0, 1), np.int32), np.zeros((0, 1), self.dtype))
        k = self.k
        idx = np.concatenate([
            np.pad(i, ((0, 0), (0, k - i.shape[1]))) for i, _ in self.chunks
        ])
        val = np.concatenate([
            np.pad(v, ((0, 0), (0, k - v.shape[1]))) for _, v in self.chunks
        ])
        self.chunks.clear()
        return idx, val


def read_merged(
    path: str,
    *,
    feature_shards: dict[str, list[str]],
    index_maps: dict[str, IndexMap] | None = None,
    id_columns: list[str] | None = None,
    id_tag_names=None,  # list[str] | None | "auto"
    response_field: str | None = None,
    input_columns: dict[str, str] | None = None,
    add_intercept: bool | dict[str, bool] = True,
    dtype=jnp.float32,
    records: list[dict] | None = None,
) -> tuple[GameDataset, dict[str, IndexMap]]:
    """Read a multi-bag Avro layout into a multi-shard GameDataset.

    The full AvroDataReader.readMerged semantics (AvroDataReader.scala
    :85-145): each feature SHARD is the union of one or more feature-bag
    record fields (FeatureShardConfiguration.featureBags) — e.g. the Yahoo!
    Music layout's ``userFeatures``/``songFeatures``/``features`` bags —
    packed into its own ELL matrix against its own index map. ``id_columns``
    exposes top-level record fields (userId, songId, ...) as id tags;
    ``id_tag_names`` additionally picks metadataMap entries (``"auto"`` =
    every key found in the data). The response comes from ``response_field``
    (auto: "response" then "label"). ``add_intercept`` may be per-shard
    (FeatureShardConfiguration's hasIntercept flag) or one bool for all.

    STREAMING: without a pre-parsed ``records`` list the file is decoded
    block by block, twice when a scan pass is needed (vocabulary build /
    metadata-key discovery / response-field probe) — peak host memory is
    the output arrays plus one decode block, the O(batch) requirement of
    the ingest pipeline (the reference amortizes the same passes across a
    cluster, AvroDataReader.scala:85).

    ``input_columns`` remaps ALL reserved record fields, the full
    InputColumnsNames surface (InputColumnsNames.scala:80-88): keys
    "uid" / "response" / "offset" / "weight" / "metadataMap", each mapped
    to the actual field name in the data. ``response_field`` (legacy
    single-field form) takes precedence over ``input_columns["response"]``.
    """
    cols = resolve_input_columns(input_columns)
    if response_field is None:
        response_field = cols["response"]
    uid_col = cols["uid"]
    offset_col = cols["offset"]
    weight_col = cols["weight"]
    meta_col = cols["metadataMap"]

    def shard_intercept(shard: str) -> bool:
        if isinstance(add_intercept, dict):
            return add_intercept.get(shard, True)
        return add_intercept

    if records is not None and not isinstance(records, (list, tuple)):
        # The scan + build passes each iterate; a one-shot iterable would
        # be exhausted by the first.
        records = list(records)

    def stream():
        if records is not None:
            return iter(records)
        return checked_iter_container_dir(path)

    missing_maps = [
        s for s in feature_shards
        if index_maps is None or s not in index_maps
    ]
    need_scan = (
        bool(missing_maps) or id_tag_names == "auto"
        or response_field is None
    )
    # With prebuilt maps and explicit tags, the only scan need is the
    # response-field probe — one record, not a full decode pass.
    probe_only = not missing_maps and id_tag_names != "auto"
    out_maps: dict[str, IndexMap] = dict(
        (s, index_maps[s]) for s in feature_shards
        if index_maps is not None and s in index_maps
    )
    if need_scan:
        keysets: dict[str, set] = {s: set() for s in missing_maps}
        meta_keys: set[str] = set()
        first = None
        for rec in stream():
            if first is None:
                first = rec
                if probe_only:
                    break
            for shard in missing_maps:
                ks = keysets[shard]
                for bag in feature_shards[shard]:
                    for f in rec.get(bag) or ():
                        ks.add(make_feature_key(f["name"], f["term"]))
            if id_tag_names == "auto":
                meta_keys.update((rec.get(meta_col) or {}).keys())
        if first is None:
            raise ValueError(f"no records in {path}")
        if response_field is None:
            for candidate in ("response", "label"):
                if candidate in first:
                    response_field = candidate
                    break
            else:
                raise ValueError(
                    "records carry neither 'response' nor 'label'; pass "
                    "response_field explicitly")
        if id_tag_names == "auto":
            id_tag_names = sorted(meta_keys)
        for shard in missing_maps:
            out_maps[shard] = IndexMap.from_feature_names(
                keysets.pop(shard), add_intercept=shard_intercept(shard))

    id_columns = list(id_columns or ())
    overlap = set(id_columns) & set(id_tag_names or ())
    if overlap:
        raise ValueError(
            f"id name(s) {sorted(overlap)} listed in both id_columns and "
            "id_tag_names; each id tag must come from exactly one source")

    np_dtype = np.dtype(dtype)
    labels_chunks: list[np.ndarray] = []
    offsets_chunks: list[np.ndarray] = []
    weights_chunks: list[np.ndarray] = []
    uids_chunks: list[np.ndarray] = []
    builders = {
        s: _EllBuilder(len(out_maps[s]), np_dtype) for s in feature_shards
    }
    tag_names = list(id_columns)
    for t in id_tag_names or ():
        if t not in tag_names:
            tag_names.append(t)
    # Tag values flush to numpy string-array chunks like every other
    # column — a per-row Python list would break the O(batch) contract.
    tag_chunks: dict[str, list] = {t: [] for t in tag_names}

    # Chunk-local accumulators, flushed to arrays every _CHUNK_ROWS rows.
    c_labels: list = []
    c_offsets: list = []
    c_weights: list = []
    c_uids: list = []
    c_rows: dict[str, list] = {s: [] for s in feature_shards}
    c_tags: dict[str, list] = {t: [] for t in tag_names}

    def flush():
        if not c_labels:
            return
        labels_chunks.append(np.asarray(c_labels, dtype=np.float64))
        offsets_chunks.append(np.asarray(c_offsets, dtype=np.float64))
        weights_chunks.append(np.asarray(c_weights, dtype=np.float64))
        uids_chunks.append(np.asarray(c_uids, dtype=np.int64))
        for s in feature_shards:
            builders[s].add_chunk(c_rows[s])
            c_rows[s].clear()
        for t in tag_names:
            tag_chunks[t].append(np.asarray(c_tags[t]))
            c_tags[t].clear()
        c_labels.clear()
        c_offsets.clear()
        c_weights.clear()
        c_uids.clear()

    i = -1
    for i, rec in enumerate(stream()):
        c_labels.append(rec[response_field])
        c_offsets.append(
            rec[offset_col] if rec.get(offset_col) is not None else 0.0)
        c_weights.append(
            rec[weight_col] if rec.get(weight_col) is not None else 1.0)
        c_uids.append(_uid_to_int(rec.get(uid_col), i))
        for shard, bags in feature_shards.items():
            imap = out_maps[shard]
            row = []
            for bag in bags:
                for f in rec.get(bag) or ():
                    idx = imap.get_index(
                        make_feature_key(f["name"], f["term"]))
                    if idx is not None and f["value"] != 0.0:
                        row.append((idx, float(f["value"])))
            if imap.intercept_index is not None:
                row.append((imap.intercept_index, 1.0))
            c_rows[shard].append(row)
        for col in id_columns:
            if col not in rec or rec[col] is None:
                raise ValueError(f"record {i} is missing id column {col!r}")
            c_tags[col].append(rec[col])
        meta = rec.get(meta_col) or {}
        for t in id_tag_names or ():
            if t not in meta:
                raise ValueError(
                    f"record {i} is missing id tag {t!r} in metadataMap")
            c_tags[t].append(meta[t])
        if len(c_labels) >= _CHUNK_ROWS:
            flush()
    flush()
    if i < 0:
        raise ValueError(f"no records in {path}")

    shards = {}
    for shard in feature_shards:
        indices, values = builders[shard].finish()
        # Numpy-backed: make_game_dataset keeps the host mirror (the
        # dataset-build planner reads it) and pushes the device copy once.
        shards[shard] = SparseFeatures(
            indices, values, len(out_maps[shard]))
    game = make_game_dataset(
        np.concatenate(labels_chunks),
        shards,
        offsets=np.concatenate(offsets_chunks),
        weights=np.concatenate(weights_chunks),
        id_tags={
            t: np.concatenate(chunks)
            for t, chunks in tag_chunks.items() if chunks
        },
        uids=np.concatenate(uids_chunks),
        dtype=dtype,
    )
    return game, out_maps


def _uid_to_int(uid, position: int) -> int:
    """Stable int64 sample id from an Avro uid string.

    The deterministic reservoir sampling keys on these
    (build_random_effect_dataset byteswap64 hashing), so they must track the
    record's real identity — numeric uids pass through, other strings get a
    stable CRC-based hash, absent uids fall back to file position (the
    reference's GameConverters hashes the row when no uid column exists).
    """
    if uid is None:
        return position
    s = str(uid)
    try:
        return int(s)
    except ValueError:
        import zlib

        return (zlib.crc32(s.encode()) << 31) | (
            zlib.crc32(s[::-1].encode())
        )


TRAINING_EXAMPLE_SCHEMA = {
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {
            "items": {
                "name": "FeatureAvro",
                "namespace": "com.linkedin.photon.avro.generated",
                "type": "record",
                "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"},
                ],
            },
            "type": "array",
        }},
        {"name": "metadataMap", "default": None,
         "type": ["null", {"type": "map", "values": "string"}]},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}


RESPONSE_PREDICTION_SCHEMA = {
    "name": "SimplifiedResponsePrediction",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "doc": (
        "Response prediction format truncated with the only field photon "
        "is expecting"
    ),
    "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {
            "items": {
                "name": "FeatureAvro",
                "namespace": "com.linkedin.photon.avro.generated",
                "type": "record",
                "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"},
                ],
            },
            "type": "array",
        }},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ],
}


def write_response_predictions(
    path: str,
    responses,
    feature_rows,  # list of [(feature_key, value)] in name+term key form
    *,
    weights=None,
    offsets=None,
) -> None:
    """SimplifiedResponsePrediction writer
    (photon-avro-schemas ResponsePredictionAvro.avsc) — the reference's
    response-prediction data layout; readable back via ``read_merged`` with
    ``response_field="response"`` (AvroDataReader handles both layouts
    uniformly)."""
    responses = np.asarray(responses)

    def rec(i):
        feats = []
        for key, val in feature_rows[i]:
            name, term = split_feature_key(key)
            feats.append({"name": name, "term": term, "value": float(val)})
        return {
            "response": float(responses[i]),
            "features": feats,
            "weight": 1.0 if weights is None else float(weights[i]),
            "offset": 0.0 if offsets is None else float(offsets[i]),
        }

    avro.write_container(
        path,
        RESPONSE_PREDICTION_SCHEMA,
        (rec(i) for i in range(responses.shape[0])),
    )


def write_training_examples(
    path: str,
    labels,
    feature_rows,  # list of [(feature_key, value)] in name+term key form
    *,
    offsets=None,
    weights=None,
    metadata=None,  # list[dict[str, str]]
    uids=None,
) -> None:
    """TrainingExampleAvro writer (AvroDataWriter.scala:159) — used by tests
    and data-prep tooling to produce reference-format datasets."""
    labels = np.asarray(labels)

    def rec(i):
        feats = []
        for key, val in feature_rows[i]:
            name, term = split_feature_key(key)
            feats.append({"name": name, "term": term, "value": float(val)})
        return {
            "uid": None if uids is None else str(uids[i]),
            "label": float(labels[i]),
            "features": feats,
            "metadataMap": None if metadata is None else metadata[i],
            "weight": None if weights is None else float(weights[i]),
            "offset": None if offsets is None else float(offsets[i]),
        }

    avro.write_container(
        path,
        TRAINING_EXAMPLE_SCHEMA,
        (rec(i) for i in range(labels.shape[0])),
    )
