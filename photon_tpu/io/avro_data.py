"""Avro training data ingest: TrainingExampleAvro -> GameDataset.

TPU-native counterpart of AvroDataReader (photon-client
data/avro/AvroDataReader.scala:54): reads TrainingExampleAvro records (uid /
label / features: [FeatureAvro name,term,value] / weight / offset /
metadataMap), merges the configured feature bags into per-shard ELL feature
matrices keyed by a feature index map (name+term joined with
Constants.DELIMITER, AvroDataReader readMerged :85-145), and surfaces
metadataMap entries as id tags (the GameDatum idTagToValueMap used for
random-effect grouping and grouped evaluation, GameConverters.scala:44).

Here every shard reads the record's single ``features`` array (the
TrainingExampleAvro layout); multi-bag shard merging applies when records
carry bag-named metadata — the reference's multi-bag Avro layouts can be
mapped onto this via ``feature_bag_keys``.
"""

from __future__ import annotations

import numpy as np

from photon_tpu.data.game_data import GameDataset, make_game_dataset
from photon_tpu.data.dataset import SparseFeatures, rows_to_ell
from photon_tpu.data.index_map import IndexMap
from photon_tpu.io import avro
from photon_tpu.types import make_feature_key, split_feature_key

import jax.numpy as jnp


def build_index_map_from_records(
    records, *, add_intercept: bool = True
) -> IndexMap:
    """Scan records for distinct (name, term) keys — the DefaultIndexMap
    path (GameDriver.prepareFeatureMaps data-scan branch)."""
    keys = set()
    for rec in records:
        for f in rec["features"]:
            keys.add(make_feature_key(f["name"], f["term"]))
    return IndexMap.from_feature_names(keys, add_intercept=add_intercept)


def read_training_examples(
    path: str,
    *,
    index_map: IndexMap | None = None,
    id_tag_names: list[str] | None = None,
    add_intercept: bool = True,
    dtype=jnp.float32,
    records: list[dict] | None = None,
) -> tuple[GameDataset, IndexMap]:
    """Read a TrainingExampleAvro file/dir into a GameDataset.

    ``id_tag_names`` picks metadataMap entries to expose as id tags; when
    None all metadata keys found in the first record are used. ``records``
    supplies already-parsed Avro records for ``path`` to skip a re-parse.
    """
    if records is None:
        records = avro.read_container_dir(path)
    if not records:
        raise ValueError(f"no records in {path}")
    if index_map is None:
        index_map = build_index_map_from_records(
            records, add_intercept=add_intercept
        )
    intercept = index_map.intercept_index

    if id_tag_names is None:
        # Union over ALL records: any key may be absent from the first one.
        found: set[str] = set()
        for rec in records:
            found.update((rec.get("metadataMap") or {}).keys())
        id_tag_names = sorted(found)

    labels = np.empty(len(records))
    offsets = np.zeros(len(records))
    weights = np.ones(len(records))
    uids = np.empty(len(records), dtype=np.int64)
    rows = []
    tags: dict[str, list] = {t: [] for t in id_tag_names}
    for i, rec in enumerate(records):
        labels[i] = rec["label"]
        if rec.get("offset") is not None:
            offsets[i] = rec["offset"]
        if rec.get("weight") is not None:
            weights[i] = rec["weight"]
        uids[i] = _uid_to_int(rec.get("uid"), i)
        row = []
        for f in rec["features"]:
            idx = index_map.get_index(make_feature_key(f["name"], f["term"]))
            if idx is not None and f["value"] != 0.0:
                row.append((idx, float(f["value"])))
        if intercept is not None:
            row.append((intercept, 1.0))
        rows.append(row)
        meta = rec.get("metadataMap") or {}
        for t in id_tag_names:
            if t not in meta:
                # The reference fails on a missing REId (GameConverters
                # getGameDatumFromRow); silently pooling tagless rows under
                # one entity would train a spurious model.
                raise ValueError(
                    f"record {i} is missing id tag {t!r} in metadataMap"
                )
            tags[t].append(meta[t])

    indices, values = rows_to_ell(rows, len(index_map))
    game = make_game_dataset(
        labels,
        {"features": SparseFeatures(
            jnp.asarray(indices), jnp.asarray(values, dtype=dtype),
            len(index_map))},
        offsets=offsets,
        weights=weights,
        id_tags={t: np.asarray(v) for t, v in tags.items() if v},
        uids=uids,
        dtype=dtype,
    )
    return game, index_map


def _uid_to_int(uid, position: int) -> int:
    """Stable int64 sample id from an Avro uid string.

    The deterministic reservoir sampling keys on these
    (build_random_effect_dataset byteswap64 hashing), so they must track the
    record's real identity — numeric uids pass through, other strings get a
    stable CRC-based hash, absent uids fall back to file position (the
    reference's GameConverters hashes the row when no uid column exists).
    """
    if uid is None:
        return position
    s = str(uid)
    try:
        return int(s)
    except ValueError:
        import zlib

        return (zlib.crc32(s.encode()) << 31) | (
            zlib.crc32(s[::-1].encode())
        )


TRAINING_EXAMPLE_SCHEMA = {
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {
            "items": {
                "name": "FeatureAvro",
                "namespace": "com.linkedin.photon.avro.generated",
                "type": "record",
                "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"},
                ],
            },
            "type": "array",
        }},
        {"name": "metadataMap", "default": None,
         "type": ["null", {"type": "map", "values": "string"}]},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}


def write_training_examples(
    path: str,
    labels,
    feature_rows,  # list of [(feature_key, value)] in name+term key form
    *,
    offsets=None,
    weights=None,
    metadata=None,  # list[dict[str, str]]
    uids=None,
) -> None:
    """TrainingExampleAvro writer (AvroDataWriter.scala:159) — used by tests
    and data-prep tooling to produce reference-format datasets."""
    labels = np.asarray(labels)

    def rec(i):
        feats = []
        for key, val in feature_rows[i]:
            name, term = split_feature_key(key)
            feats.append({"name": name, "term": term, "value": float(val)})
        return {
            "uid": None if uids is None else str(uids[i]),
            "label": float(labels[i]),
            "features": feats,
            "metadataMap": None if metadata is None else metadata[i],
            "weight": None if weights is None else float(weights[i]),
            "offset": None if offsets is None else float(offsets[i]),
        }

    avro.write_container(
        path,
        TRAINING_EXAMPLE_SCHEMA,
        (rec(i) for i in range(labels.shape[0])),
    )
