"""GAME model save/load in the reference's Avro directory layout.

TPU-native counterpart of ModelProcessingUtils (photon-client
data/avro/ModelProcessingUtils.scala:59): ``saveGameModelToHDFS`` (:77-130)
writes

    <dir>/model-metadata.json
    <dir>/fixed-effect/<name>/id-info                  (one line: shard id)
    <dir>/fixed-effect/<name>/coefficients/part-00000.avro
    <dir>/random-effect/<name>/id-info                 (REType, shard id)
    <dir>/random-effect/<name>/coefficients/part-*.avro

with one BayesianLinearModelAvro record per GLM (per entity for random
effects), means/variances as NameTermValueAvro lists keyed by the feature
index map, and the model/loss class names of the reference JVM classes so
files round-trip with the reference loader (AvroUtils.scala
convertGLMModelToBayesianLinearModelAvro). Sparsity threshold semantics
match saveModelToHDFS: zero coefficients are dropped on save.

A fast native checkpoint (``save_checkpoint``/``load_checkpoint``) stores the
same GameModel as one .npz + JSON manifest for warm start / resume without
the name-keyed Avro round trip.
"""

from __future__ import annotations

import hashlib
import io
import json
import os

import jax.numpy as jnp
import numpy as np

from photon_tpu.data.index_map import IndexMap
from photon_tpu.io import avro
from photon_tpu.resilience.errors import CorruptModelError
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    random_effect_model_to_glms,
)
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.types import TaskType, make_feature_key, split_feature_key

ID_INFO = "id-info"
METADATA_FILE = "model-metadata.json"
FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
COEFFICIENTS = "coefficients"
DEFAULT_AVRO_FILE = "part-00000.avro"

# Reference JVM class names (the loader dispatches on them,
# ModelProcessingUtils.scala:371-391).
_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}
_LOSS_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.function.LogisticLossFunction",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.function.SquaredLossFunction",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.function.PoissonLossFunction",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.function.SmoothedHingeLossFunction",
}

NAME_TERM_VALUE_SCHEMA = {
    "name": "NameTermValueAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}
BAYESIAN_LINEAR_MODEL_SCHEMA = {
    "name": "BayesianLinearModelAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means",
         "type": {"items": NAME_TERM_VALUE_SCHEMA, "type": "array"}},
        {"name": "variances", "default": None,
         "type": ["null", {"items": "NameTermValueAvro", "type": "array"}]},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}
SCORING_RESULT_SCHEMA = {
    "name": "ScoringResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "default": None,
         "type": ["null", {"type": "map", "values": "string"}]},
    ],
}


def _resolve_index(index_map: IndexMap, name: str, term: str) -> int | None:
    """Inverse of the save-side split_feature_key: keys WITHOUT a delimiter
    serialize as (name, term="") (types.py split_feature_key), so an empty
    term must also try the bare name — identity index maps ("0", "1", ...)
    would otherwise silently drop every feature on load."""
    idx = index_map.get_index(make_feature_key(name, term))
    if idx is None and term == "":
        idx = index_map.get_index(name)
    return idx


def _ntv_list(values: np.ndarray, indices, index_map: IndexMap,
              sparsity_threshold: float) -> list[dict]:
    out = []
    for idx, v in zip(indices, values):
        if abs(float(v)) <= sparsity_threshold:
            continue
        key = index_map.get_feature_name(int(idx))
        if key is None:
            raise KeyError(f"feature index {idx} not in index map")
        name, term = split_feature_key(key)
        out.append({"name": name, "term": term, "value": float(v)})
    return out


def _glm_to_record(
    model_id: str,
    task: TaskType,
    means: np.ndarray,
    variances: np.ndarray | None,
    indices: np.ndarray,
    index_map: IndexMap,
    sparsity_threshold: float,
) -> dict:
    rec = {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS[task],
        "means": _ntv_list(means, indices, index_map, sparsity_threshold),
        "variances": None,
        "lossFunction": _LOSS_CLASS[task],
    }
    if variances is not None:
        # Variances keep the full support (threshold -1), including
        # coefficients whose mean is exactly zero (L1 solutions).
        rec["variances"] = _ntv_list(
            variances, indices, index_map, -1.0
        )
    return rec


def _record_to_coefficients(
    rec: dict, index_map: IndexMap, dim: int
) -> tuple[Coefficients, TaskType | None]:
    means = np.zeros(dim)
    for ntv in rec["means"]:
        idx = _resolve_index(index_map, ntv["name"], ntv["term"])
        if idx is not None:
            means[idx] = ntv["value"]
    variances = None
    if rec.get("variances"):
        variances = np.zeros(dim)
        for ntv in rec["variances"]:
            idx = _resolve_index(index_map, ntv["name"], ntv["term"])
            if idx is not None:
                variances[idx] = ntv["value"]
    task = _CLASS_TO_TASK.get(rec.get("modelClass") or "")
    return Coefficients(
        means=jnp.asarray(means),
        variances=None if variances is None else jnp.asarray(variances),
    ), task


def save_game_model(
    model: GameModel,
    output_dir: str,
    index_maps: dict[str, IndexMap],
    *,
    task: TaskType | None = None,
    optimization_configurations: dict | None = None,
    sparsity_threshold: float = 0.0,
) -> None:
    """saveGameModelToHDFS equivalent (ModelProcessingUtils.scala:77-130)."""
    os.makedirs(output_dir, exist_ok=True)
    task = task if task is not None else model.task
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump({
            "modelType": task.value,
            "optimizationConfigurations":
                optimization_configurations or {},
        }, f, indent=2)

    for name, sub in model.items():
        if isinstance(sub, FixedEffectModel):
            base = os.path.join(output_dir, FIXED_EFFECT, name)
            os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                f.write(sub.feature_shard_id + "\n")
            imap = index_maps[sub.feature_shard_id]
            coefs = sub.model.coefficients
            means = np.asarray(coefs.means)
            rec = _glm_to_record(
                name,
                sub.model.task,
                means,
                None if coefs.variances is None else np.asarray(coefs.variances),
                np.arange(means.shape[0]),
                imap,
                sparsity_threshold,
            )
            avro.write_container(
                os.path.join(base, COEFFICIENTS, DEFAULT_AVRO_FILE),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                [rec],
            )
        elif isinstance(sub, RandomEffectModel):
            base = os.path.join(output_dir, RANDOM_EFFECT, name)
            os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                f.write(sub.random_effect_type + "\n")
                f.write(sub.feature_shard_id + "\n")
            imap = index_maps[sub.feature_shard_id]
            records = [
                _glm_to_record(
                    entity_id,
                    sub.task,
                    coefs.means,
                    coefs.variances,
                    coefs.feature_indices,
                    imap,
                    sparsity_threshold,
                )
                for entity_id, coefs in
                random_effect_model_to_glms(sub).items()
            ]
            avro.write_container(
                os.path.join(base, COEFFICIENTS, DEFAULT_AVRO_FILE),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                records,
            )
        else:
            raise TypeError(f"unknown sub-model type for {name!r}")


def model_feature_shard_ids(model_dir: str) -> set[str]:
    """The feature shard ids a saved model directory references.

    Reads each sub-model's ``id-info`` (shard id is the LAST line —
    fixed effects write one line, random effects two). Shared by the
    scoring/serving drivers to decide which index maps a load needs.
    """
    shards: set[str] = set()
    for kind in (FIXED_EFFECT, RANDOM_EFFECT):
        base = os.path.join(model_dir, kind)
        if not os.path.isdir(base):
            continue
        for name in os.listdir(base):
            with open(os.path.join(base, name, ID_INFO)) as f:
                shards.add(f.read().strip().splitlines()[-1])
    return shards


def _read_coefficients_dir(coef_dir: str, what: str) -> list:
    """Avro coefficient read with codec failures translated.

    A truncated upload / torn copy otherwise surfaces as a bare
    ``EOFError("truncated varint")`` with no hint WHICH of the model's
    many part files is bad; every decode failure becomes a
    ``CorruptModelError`` naming the directory and the cause.
    """
    try:
        return avro.read_container_dir(coef_dir)
    except (ValueError, EOFError, KeyError) as exc:
        raise CorruptModelError(
            f"{what} coefficients under {coef_dir}: Avro decode failed "
            f"({type(exc).__name__}: {exc}) — the file is truncated or "
            "not a BayesianLinearModelAvro container"
        ) from exc


def load_game_model(
    input_dir: str,
    index_maps: dict[str, IndexMap],
) -> tuple[GameModel, dict]:
    """loadGameModelFromHDFS equivalent (ModelProcessingUtils.scala:143-240).

    Returns (model, metadata). Random-effect models are reassembled into the
    padded-matrix layout with per-entity projectors derived from each
    entity's saved support.
    """
    meta_path = os.path.join(input_dir, METADATA_FILE)
    try:
        with open(meta_path) as f:
            metadata = json.load(f)
    except json.JSONDecodeError as exc:
        raise CorruptModelError(
            f"model metadata {meta_path}: not valid JSON ({exc})"
        ) from exc
    task = TaskType(metadata["modelType"])
    models: dict[str, object] = {}

    fe_dir = os.path.join(input_dir, FIXED_EFFECT)
    if os.path.isdir(fe_dir):
        for name in sorted(os.listdir(fe_dir)):
            base = os.path.join(fe_dir, name)
            with open(os.path.join(base, ID_INFO)) as f:
                shard = f.read().strip().splitlines()[0]
            imap = index_maps[shard]
            records = _read_coefficients_dir(
                os.path.join(base, COEFFICIENTS),
                f"fixed-effect model {name!r}",
            )
            if len(records) != 1:
                raise ValueError(
                    f"fixed-effect model {name!r}: expected 1 record, "
                    f"got {len(records)}"
                )
            coefs, rec_task = _record_to_coefficients(
                records[0], imap, len(imap)
            )
            models[name] = FixedEffectModel(
                GeneralizedLinearModel(coefs, rec_task or task), shard
            )

    re_dir = os.path.join(input_dir, RANDOM_EFFECT)
    if os.path.isdir(re_dir):
        for name in sorted(os.listdir(re_dir)):
            base = os.path.join(re_dir, name)
            lines = open(os.path.join(base, ID_INFO)).read().strip().splitlines()
            re_type, shard = lines[0], lines[1]
            coef_dir = os.path.join(base, COEFFICIENTS)
            # Partial-retrain fixtures ship id-info with no coefficients
            # (reference GameIntegTest/retrainModels); an absent dir is an
            # empty model set, matching the reference's empty-RDD load (and
            # needs no index map for its shard).
            records = (
                _read_coefficients_dir(
                    coef_dir, f"random-effect model {name!r}"
                )
                if os.path.isdir(coef_dir) else []
            )
            imap = index_maps[shard] if records else None
            entity_ids = []
            supports = []
            means_list = []
            var_list = []
            any_var = False
            for rec in records:
                entity_ids.append(rec["modelId"])
                mmap: dict[int, float] = {}
                for ntv in rec["means"]:
                    idx = _resolve_index(imap, ntv["name"], ntv["term"])
                    if idx is not None:
                        mmap[idx] = ntv["value"]
                vmap: dict[int, float] = {}
                if rec.get("variances"):
                    for ntv in rec["variances"]:
                        idx = _resolve_index(imap, ntv["name"], ntv["term"])
                        if idx is not None:
                            vmap[idx] = ntv["value"]
                    any_var = True
                # Support = union of means and variances: L1 solutions carry
                # exact-zero means whose variances must survive the round
                # trip.
                idxs = np.asarray(
                    sorted(set(mmap) | set(vmap)), dtype=np.int64
                )
                supports.append(idxs)
                means_list.append(
                    np.array([mmap.get(int(i), 0.0) for i in idxs])
                )
                var_list.append(
                    np.array([vmap.get(int(i), 0.0) for i in idxs])
                    if vmap else None
                )
            e_cnt = len(records)
            s_max = max((s.size for s in supports), default=1)
            s_max = max(s_max, 1)
            w = np.zeros((e_cnt, s_max))
            v = np.zeros((e_cnt, s_max)) if any_var else None
            proj = np.full((e_cnt, s_max), -1, dtype=np.int64)
            for e in range(e_cnt):
                k = supports[e].size
                proj[e, :k] = supports[e]
                w[e, :k] = means_list[e]
                if v is not None and var_list[e] is not None:
                    v[e, :k] = var_list[e]
            rec_task = _CLASS_TO_TASK.get(
                (records[0].get("modelClass") or "") if records else ""
            )
            models[name] = RandomEffectModel(
                coefficients=jnp.asarray(w),
                random_effect_type=re_type,
                feature_shard_id=shard,
                task=rec_task or task,
                proj_all=proj,
                variances=None if v is None else jnp.asarray(v),
                entity_keys=tuple(entity_ids),
            )

    if not models:
        raise ValueError(f"no models found under {input_dir}")
    return GameModel(models), metadata


def save_scores(
    path: str,
    scores: np.ndarray,
    *,
    model_id: str = "",
    uids: np.ndarray | None = None,
    labels: np.ndarray | None = None,
    weights: np.ndarray | None = None,
) -> None:
    """ScoringResultAvro writer (ScoreProcessingUtils.scala:88)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    scores = np.asarray(scores)

    def rec(i):
        return {
            "uid": None if uids is None else str(uids[i]),
            "label": None if labels is None else float(labels[i]),
            "modelId": model_id,
            "predictionScore": float(scores[i]),
            "weight": None if weights is None else float(weights[i]),
            "metadataMap": None,
        }

    avro.write_container(
        path, SCORING_RESULT_SCHEMA, (rec(i) for i in range(scores.shape[0]))
    )


FEATURE_SUMMARIZATION_SCHEMA = {
    "name": "FeatureSummarizationResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}


def save_feature_stats(path: str, stats, index_map: IndexMap) -> None:
    """Per-feature summary artifact (one record per non-intercept feature).

    Reference: ModelProcessingUtils.writeBasicStatistics (photon-client
    data/avro/ModelProcessingUtils.scala:514-560) — the metrics map carries
    max/min/mean/normL1/normL2/numNonzeros/variance per (name, term), with
    the intercept filtered out; written under
    ``<dataSummaryDirectory>/<shardId>`` by the training driver
    (GameTrainingDriver.calculateAndSaveFeatureShardStats :616-627).
    """
    from photon_tpu.types import split_feature_key

    os.makedirs(path, exist_ok=True)
    skip = stats.intercept_index
    zeros = np.zeros(stats.dim)
    l1 = zeros if stats.norm_l1 is None else stats.norm_l1
    l2 = zeros if stats.norm_l2 is None else stats.norm_l2

    def records():
        for idx in range(stats.dim):
            if idx == skip:
                continue
            key = index_map.get_feature_name(idx)
            if key is None:
                continue
            name, term = split_feature_key(key)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "max": float(stats.max[idx]),
                    "min": float(stats.min[idx]),
                    "mean": float(stats.mean[idx]),
                    "normL1": float(l1[idx]),
                    "normL2": float(l2[idx]),
                    "numNonzeros": float(stats.num_nonzeros[idx]),
                    "variance": float(stats.variance[idx]),
                },
            }

    avro.write_container(
        os.path.join(path, "part-00000.avro"),
        FEATURE_SUMMARIZATION_SCHEMA,
        records(),
    )


def load_feature_stats(path: str) -> dict[str, dict[str, float]]:
    """Read a stats artifact back: feature key -> metrics map."""
    from photon_tpu.types import make_feature_key

    out: dict[str, dict[str, float]] = {}
    for rec in avro.read_container_dir(path):
        out[make_feature_key(rec["featureName"], rec["featureTerm"])] = {
            k: float(v) for k, v in rec["metrics"].items()
        }
    return out


# --------------------------------------------------------------------------
# native checkpoint (fast path; no Avro name-keying)
# --------------------------------------------------------------------------


def _ckpt_path(path: str) -> str:
    """np.savez appends .npz; normalize so save/load stay symmetric."""
    return path if path.endswith(".npz") else path + ".npz"


def fsync_dir(path: str) -> None:
    """Durably commit a rename: fsync the containing directory (the
    rename itself is atomic; the DIRECTORY entry still needs a sync to
    survive power loss)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover — exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(
    path: str, data: bytes | memoryview, *, fault_point: str | None = None
) -> None:
    """The one atomic-commit dance every durable artifact goes through:
    bytes land in a temp sibling that is fsynced, ``os.replace``d over
    ``path``, and the directory entry is fsynced — a crash at any step
    leaves either the previous file or the committed new one, never a
    torn write, and the rename survives power loss. ``fault_point``
    names an injection point fired in the mid-write crash window (bytes
    down, rename not yet done) so chaos tests can prove exactly that.
    Temp debris is removed on any failure."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if fault_point is not None:
            from photon_tpu.resilience import faults

            faults.check(fault_point)
        os.replace(tmp, path)
    except BaseException:
        # Never leave tmp debris for a directory listing to confuse
        # with a committed artifact.
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path) or ".")


_META_KEY = "__meta__"


def save_checkpoint(
    model: GameModel,
    path: str,
    *,
    extra_meta: dict | None = None,
    fault_point: str | None = "checkpoint.write",
) -> str:
    """One-file native GameModel checkpoint (.npz + JSON manifest).

    The write is ATOMIC: bytes land in a temp file that is fsynced and
    ``os.replace``d over ``path``, so a crash (or the injected
    ``checkpoint.write`` fault) mid-write leaves any previous file at
    ``path`` untouched and loadable. ``extra_meta`` rides inside the
    npz under a reserved key — the training checkpointer stores its
    loop state (config/iteration cursor, static key) there so the
    artifact is self-contained; read it back with
    ``load_checkpoint_meta``. ``fault_point`` names the injection point
    fired in the mid-write crash window (default the training
    checkpointer's ``checkpoint.write``; the pilot's generation ring
    passes its own ``pilot.promote`` so chaos CI can kill exactly
    between the ring commit and the serving reload).

    Returns the sha256 hex digest of the committed bytes, hashed from
    the in-memory serialization — callers recording content hashes
    (the training checkpointer's manifest) never re-read the file.
    """
    path = _ckpt_path(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    manifest: dict[str, dict] = {}
    for name, sub in model.items():
        if isinstance(sub, FixedEffectModel):
            arrays[f"{name}/means"] = np.asarray(sub.model.coefficients.means)
            if sub.model.coefficients.variances is not None:
                arrays[f"{name}/variances"] = np.asarray(
                    sub.model.coefficients.variances
                )
            manifest[name] = {
                "kind": "fixed",
                "shard": sub.feature_shard_id,
                "task": sub.model.task.value,
            }
        elif isinstance(sub, RandomEffectModel):
            arrays[f"{name}/coefficients"] = np.asarray(sub.coefficients)
            arrays[f"{name}/proj_all"] = sub.proj_all
            if sub.variances is not None:
                arrays[f"{name}/variances"] = np.asarray(sub.variances)
            manifest[name] = {
                "kind": "random",
                "re_type": sub.random_effect_type,
                "shard": sub.feature_shard_id,
                "task": sub.task.value,
                "entity_keys": [str(k) for k in sub.entity_keys],
            }
        else:
            raise TypeError(f"unknown sub-model type for {name!r}")
    if _META_KEY in manifest:
        raise ValueError(
            f"model coordinate name {_META_KEY!r} collides with the "
            "checkpoint metadata key")
    if extra_meta is not None:
        manifest[_META_KEY] = dict(extra_meta)
    arrays["__manifest__"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8
    )
    # Serialize in memory first: np.savez's zip writer seeks back to
    # patch member headers, so the only way to hash the exact committed
    # bytes in one pass is to hash the finished buffer.
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    data = buf.getbuffer()  # zero-copy view; getvalue() would double peak RSS
    digest = hashlib.sha256(data).hexdigest()
    atomic_write_bytes(path, data, fault_point=fault_point)
    return digest


def artifact_digest(path: str) -> str:
    """Stable sha256 identity of a model artifact — a checkpoint npz's
    content hash, or (for an Avro model DIRECTORY) the hash of every
    file's (relative name, content hash) pair in sorted order. The
    training checkpointer records this for the run's init model so a
    resumed day-over-day retrain can prove it is warm-starting from the
    SAME yesterday-model the interrupted run used."""
    h = hashlib.sha256()
    if os.path.isfile(path):
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
        return h.hexdigest()
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            h.update(rel.encode())
            with open(full, "rb") as f:
                for block in iter(lambda: f.read(1 << 20), b""):
                    h.update(block)
    return h.hexdigest()


def load_initial_model(
    path: str, index_maps: dict[str, IndexMap] | None = None
) -> tuple[GameModel, str]:
    """Load a warm-start model from either artifact form.

    ``path`` may be a native checkpoint (``.npz``, self-contained) or a
    reference Avro model directory (needs ``index_maps`` to key the
    name+term records). Returns ``(model, digest)`` — the digest is the
    ``artifact_digest`` identity the training checkpointer records so
    an ingest-then-descent resume can verify its warm start.
    """
    if os.path.isfile(path) or path.endswith(".npz"):
        return load_checkpoint(path), artifact_digest(_ckpt_path(path))
    if os.path.isfile(os.path.join(path, METADATA_FILE)):
        if index_maps is None:
            raise ValueError(
                f"init model {path} is an Avro model directory; loading "
                "it needs the feature index maps (name+term keyed "
                "records) — pass index_maps, or point at a native "
                ".npz checkpoint instead")
        model, _ = load_game_model(path, index_maps)
        return model, artifact_digest(path)
    raise FileNotFoundError(
        f"init model {path}: neither a checkpoint npz nor an Avro "
        f"model directory (no {METADATA_FILE})")


def load_checkpoint(path: str) -> GameModel:
    """Load a native checkpoint; see ``load_checkpoint_meta`` for the
    embedded loop-state metadata."""
    return load_checkpoint_meta(path)[0]


def load_checkpoint_meta(path: str) -> tuple[GameModel, dict | None]:
    """Load a native checkpoint plus its ``extra_meta`` (None when the
    file predates metadata). A truncated / torn npz raises
    ``CorruptModelError`` naming the file instead of leaking
    ``zipfile.BadZipFile`` from three layers down."""
    import zipfile

    path = _ckpt_path(path)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        return _load_checkpoint_impl(path)
    except (zipfile.BadZipFile, ValueError, KeyError, EOFError,
            json.JSONDecodeError) as exc:
        # Deliberately NOT OSError: EACCES / transient filesystem errors
        # mean the file may be intact — reporting them as corruption
        # would steer the operator toward deleting a good checkpoint.
        raise CorruptModelError(
            f"checkpoint {path}: failed to decode "
            f"({type(exc).__name__}: {exc}) — the npz is truncated or "
            "not a photon_tpu checkpoint"
        ) from exc


def _load_checkpoint_impl(path: str) -> tuple[GameModel, dict | None]:
    with np.load(path) as z:
        manifest = json.loads(bytes(z["__manifest__"]).decode())
        meta = manifest.pop(_META_KEY, None)
        models: dict[str, object] = {}
        for name, info in manifest.items():
            task = TaskType(info["task"])
            if info["kind"] == "fixed":
                var_key = f"{name}/variances"
                coefs = Coefficients(
                    means=jnp.asarray(z[f"{name}/means"]),
                    variances=(jnp.asarray(z[var_key])
                               if var_key in z else None),
                )
                models[name] = FixedEffectModel(
                    GeneralizedLinearModel(coefs, task), info["shard"]
                )
            else:
                var_key = f"{name}/variances"
                models[name] = RandomEffectModel(
                    coefficients=jnp.asarray(z[f"{name}/coefficients"]),
                    random_effect_type=info["re_type"],
                    feature_shard_id=info["shard"],
                    task=task,
                    proj_all=z[f"{name}/proj_all"],
                    variances=(jnp.asarray(z[var_key])
                               if var_key in z else None),
                    entity_keys=tuple(info["entity_keys"]),
                )
    return GameModel(models), meta
