"""Pure-Python Avro: binary codec + Object Container Files.

The environment ships no Avro library, and the reference's model/data
formats are Avro (photon-avro-schemas/src/main/avro/*.avsc,
AvroUtils.scala:62 readAvroFiles, ModelProcessingUtils.scala:77). This
module implements the subset of the Avro 1.x specification those schemas
need — null/boolean/int/long/float/double/string/bytes primitives, records,
arrays, maps, unions, enums, fixed, named-type references — plus the object
container file format (magic ``Obj\\x01``, metadata map with schema JSON and
codec, 16-byte sync markers, null/deflate codecs), so model files round-trip
with the reference's readers bit-compatibly.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
_PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string"
}


class Schema:
    """Parsed Avro schema with a named-type registry for references."""

    def __init__(self, schema, names: dict | None = None):
        self.names: dict[str, dict] = {} if names is None else names
        self.root = self._parse(schema)

    def _parse(self, s):
        if isinstance(s, str):
            if s in _PRIMITIVES:
                return s
            if s in self.names:
                return self.names[s]
            raise ValueError(f"unknown type name {s!r}")
        if isinstance(s, list):  # union
            return [self._parse(b) for b in s]
        if isinstance(s, dict):
            t = s.get("type")
            if t in _PRIMITIVES and len(s) == 1:
                return t
            if t in ("record", "error"):
                out = {
                    "type": "record",
                    "name": s["name"],
                    "fields": [],
                }
                self._register(s, out)
                for f in s["fields"]:
                    out["fields"].append({
                        "name": f["name"],
                        "type": self._parse(f["type"]),
                        "default": f.get("default"),
                    })
                return out
            if t == "enum":
                out = {"type": "enum", "name": s["name"],
                       "symbols": list(s["symbols"])}
                self._register(s, out)
                return out
            if t == "fixed":
                out = {"type": "fixed", "name": s["name"],
                       "size": int(s["size"])}
                self._register(s, out)
                return out
            if t == "array":
                return {"type": "array", "items": self._parse(s["items"])}
            if t == "map":
                return {"type": "map", "values": self._parse(s["values"])}
            if isinstance(t, (dict, list)):
                return self._parse(t)
            if isinstance(t, str):
                return self._parse(t)
        raise ValueError(f"cannot parse schema fragment: {s!r}")

    def _register(self, raw, parsed):
        name = raw["name"]
        ns = raw.get("namespace")
        full = f"{ns}.{name}" if ns and "." not in name else name
        parsed["fullname"] = full
        self.names[full] = parsed
        self.names[name] = parsed


# --------------------------------------------------------------------------
# binary encoding
# --------------------------------------------------------------------------


def _write_long(buf: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def _read_long(buf) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # un-zigzag


def _read_exact(buf, n: int) -> bytes:
    data = buf.read(n)
    if len(data) != n:
        raise EOFError(f"truncated input: wanted {n} bytes, got {len(data)}")
    return data


def _encode(buf: io.BytesIO, schema, datum) -> None:
    if isinstance(schema, str):
        if schema == "null":
            return
        if schema == "boolean":
            buf.write(b"\x01" if datum else b"\x00")
        elif schema in ("int", "long"):
            _write_long(buf, int(datum))
        elif schema == "float":
            buf.write(struct.pack("<f", float(datum)))
        elif schema == "double":
            buf.write(struct.pack("<d", float(datum)))
        elif schema == "string":
            raw = datum.encode("utf-8")
            _write_long(buf, len(raw))
            buf.write(raw)
        elif schema == "bytes":
            _write_long(buf, len(datum))
            buf.write(datum)
        else:
            raise ValueError(f"bad primitive {schema!r}")
        return
    if isinstance(schema, list):  # union: pick first matching branch
        idx = _union_index(schema, datum)
        _write_long(buf, idx)
        _encode(buf, schema[idx], datum)
        return
    t = schema["type"]
    if t == "record":
        for f in schema["fields"]:
            name = f["name"]
            if isinstance(datum, dict) and name in datum:
                value = datum[name]
            else:
                value = f.get("default")
            _encode(buf, f["type"], value)
    elif t == "array":
        items = list(datum or ())
        if items:
            _write_long(buf, len(items))
            for it in items:
                _encode(buf, schema["items"], it)
        _write_long(buf, 0)
    elif t == "map":
        entries = dict(datum or {})
        if entries:
            _write_long(buf, len(entries))
            for k, v in entries.items():
                _encode(buf, "string", k)
                _encode(buf, schema["values"], v)
        _write_long(buf, 0)
    elif t == "enum":
        _write_long(buf, schema["symbols"].index(datum))
    elif t == "fixed":
        if len(datum) != schema["size"]:
            raise ValueError("fixed size mismatch")
        buf.write(datum)
    else:
        raise ValueError(f"bad schema type {t!r}")


def _union_index(branches, datum) -> int:
    for i, b in enumerate(branches):
        if _matches(b, datum):
            return i
    raise ValueError(f"datum {datum!r} matches no union branch")


def _matches(schema, datum) -> bool:
    if isinstance(schema, str):
        return {
            "null": datum is None,
            "boolean": isinstance(datum, bool),
            "int": (isinstance(datum, int) and not isinstance(datum, bool)
                    and -(2 ** 31) <= datum < 2 ** 31),
            "long": (isinstance(datum, int) and not isinstance(datum, bool)
                     and -(2 ** 63) <= datum < 2 ** 63),
            "float": (isinstance(datum, (float, int))
                      and not isinstance(datum, bool)),
            "double": isinstance(datum, (float, int)) and not isinstance(datum, bool),
            "string": isinstance(datum, str),
            "bytes": isinstance(datum, (bytes, bytearray)),
        }.get(schema, False)
    if isinstance(schema, list):
        return any(_matches(b, datum) for b in schema)
    t = schema["type"]
    if t == "record":
        return isinstance(datum, dict)
    if t == "array":
        return isinstance(datum, (list, tuple))
    if t == "map":
        return isinstance(datum, dict)
    if t == "enum":
        return isinstance(datum, str) and datum in schema["symbols"]
    if t == "fixed":
        return isinstance(datum, (bytes, bytearray))
    return False


def _decode(buf, schema):
    if isinstance(schema, str):
        if schema == "null":
            return None
        if schema == "boolean":
            return _read_exact(buf, 1) == b"\x01"
        if schema in ("int", "long"):
            return _read_long(buf)
        if schema == "float":
            return struct.unpack("<f", _read_exact(buf, 4))[0]
        if schema == "double":
            return struct.unpack("<d", _read_exact(buf, 8))[0]
        if schema == "string":
            n = _read_long(buf)
            return _read_exact(buf, n).decode("utf-8")
        if schema == "bytes":
            n = _read_long(buf)
            return _read_exact(buf, n)
        raise ValueError(f"bad primitive {schema!r}")
    if isinstance(schema, list):
        return _decode(buf, schema[_read_long(buf)])
    t = schema["type"]
    if t == "record":
        return {
            f["name"]: _decode(buf, f["type"]) for f in schema["fields"]
        }
    if t == "array":
        out = []
        while True:
            count = _read_long(buf)
            if count == 0:
                return out
            if count < 0:
                count = -count
                _read_long(buf)  # block byte size, unused
            for _ in range(count):
                out.append(_decode(buf, schema["items"]))
    if t == "map":
        out = {}
        while True:
            count = _read_long(buf)
            if count == 0:
                return out
            if count < 0:
                count = -count
                _read_long(buf)
            for _ in range(count):
                k = _decode(buf, "string")
                out[k] = _decode(buf, schema["values"])
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "fixed":
        return _read_exact(buf, schema["size"])
    raise ValueError(f"bad schema type {t!r}")


# --------------------------------------------------------------------------
# object container files
# --------------------------------------------------------------------------

_META_SCHEMA = {"type": "map", "values": "bytes"}


def write_container(
    path: str,
    schema_json: dict,
    records,
    *,
    codec: str = "deflate",
    sync_interval: int = 4000,
) -> None:
    """Write records to an Avro object container file."""
    schema = Schema(schema_json)
    sync = os.urandom(SYNC_SIZE)
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = io.BytesIO()
        _encode(meta, _META_SCHEMA, {
            "avro.schema": json.dumps(schema_json).encode(),
            "avro.codec": codec.encode(),
        })
        f.write(meta.getvalue())
        f.write(sync)

        block = io.BytesIO()
        count = 0

        def flush():
            nonlocal block, count
            if count == 0:
                return
            data = block.getvalue()
            if codec == "deflate":
                co = zlib.compressobj(wbits=-15)  # raw deflate stream
                data = co.compress(data) + co.flush()
            elif codec != "null":
                raise ValueError(f"unsupported codec {codec!r}")
            head = io.BytesIO()
            _write_long(head, count)
            _write_long(head, len(data))
            f.write(head.getvalue())
            f.write(data)
            f.write(sync)
            block = io.BytesIO()
            count = 0

        for rec in records:
            _encode(block, schema.root, rec)
            count += 1
            if count >= sync_interval:
                flush()
        flush()


_PROGRAM_OPS = {
    "null": 0, "boolean": 1, "int": 2, "long": 2,
    "float": 3, "double": 4, "string": 5, "bytes": 6,
}


def schema_to_program(node, _stack=None):
    """Compile a parsed schema node into the native decoder's opcode tree
    (photon_tpu/native/avrodec.c documents the encoding). Returns None for
    shapes the native decoder does not handle (recursive types) — callers
    fall back to the interpreter codec."""
    if isinstance(node, str):
        return (_PROGRAM_OPS[node],)
    if isinstance(node, list):
        branches = tuple(
            schema_to_program(b, _stack) for b in node
        )
        if any(b is None for b in branches):
            return None
        return (10, branches)
    stack = _stack if _stack is not None else set()
    key = id(node)
    if key in stack:
        return None  # recursive type: interpreter fallback
    stack.add(key)
    try:
        t = node["type"]
        if t == "record":
            names = tuple(f["name"] for f in node["fields"])
            progs = tuple(
                schema_to_program(f["type"], stack) for f in node["fields"]
            )
            if any(p is None for p in progs):
                return None
            return (7, names, progs)
        if t == "array":
            item = schema_to_program(node["items"], stack)
            return None if item is None else (8, item)
        if t == "map":
            val = schema_to_program(node["values"], stack)
            return None if val is None else (9, val)
        if t == "enum":
            return (11, tuple(node["symbols"]))
        if t == "fixed":
            return (12, int(node["size"]))
        return None
    finally:
        stack.discard(key)


def _decode_blocks(blocks):
    """Record stream over (schema_json, count, payload_bytes) blocks —
    the shared decode dispatch of the path- and bytes-based container
    iterators (native C decoder when available, interpreter fallback)."""
    from photon_tpu.native import get_avro_decoder

    schema = program = native = None
    for schema_json, count, data in blocks:
        if schema is None:
            schema = Schema(schema_json)
            program = schema_to_program(schema.root)
            native = get_avro_decoder() if program is not None else None
        if native is not None:
            yield from native.decode_block(data, count, program)
        else:
            block = io.BytesIO(data)
            for _ in range(count):
                yield _decode(block, schema.root)


def iter_container(path: str):
    """Stream an Avro object container file block by block.

    Generator of decoded records: at any moment only ONE decompressed block
    (``sync_interval`` records, default 4000) of Python dicts is alive —
    the O(batch) decode the ingest pipeline builds its arrays from. The
    file handle closes when the generator is exhausted or dropped.

    Blocks decode through the native C decoder when it is available
    (photon_tpu/native, ~40x the interpreter codec); the interpreter path
    remains the behavioral reference and the fallback.
    """
    yield from _decode_blocks(iter_container_block_bytes(path))


def iter_container_bytes(data: bytes, *, name: str = "<bytes>"):
    """Stream records from an IN-MEMORY Avro container.

    The streaming ingest's read-once path: the shard's bytes are read
    from disk a single time (hashed for the integrity manifest), then
    decoded from the same buffer — no second disk pass, and no TOCTOU
    window between the checksum and the decode. ``name`` labels parse
    errors the way a path would.
    """
    yield from _decode_blocks(_iter_blocks(io.BytesIO(data), name))


def iter_container_block_bytes(path: str):
    """Yield (schema_json, count, payload_bytes) per container block.

    ``payload_bytes`` is the decompressed record stream of the block — the
    concatenated binary encodings of ``count`` records. Golden write-parity
    tests re-encode decoded records and compare against this byte stream.
    """
    with open(path, "rb") as f:
        yield from _iter_blocks(f, path)


def _iter_blocks(f, label: str):
    if f.read(4) != MAGIC:
        raise ValueError(f"{label}: not an Avro container file")
    meta = _decode(f, _META_SCHEMA)
    schema_json = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    sync = f.read(SYNC_SIZE)
    while True:
        try:
            count = _read_long(f)
        except EOFError:
            break
        size = _read_long(f)
        data = f.read(size)
        if codec == "deflate":
            data = zlib.decompress(data, wbits=-15)
        elif codec != "null":
            raise ValueError(f"unsupported codec {codec!r}")
        yield schema_json, count, data
        if f.read(SYNC_SIZE) != sync:
            raise ValueError(f"{label}: sync marker mismatch")


def encode_records(schema_json: dict, records) -> bytes:
    """Binary-encode ``records`` under ``schema_json`` (no container
    framing) — the record-body byte stream a container block holds."""
    schema = Schema(schema_json)
    buf = io.BytesIO()
    for rec in records:
        _encode(buf, schema.root, rec)
    return buf.getvalue()


# --- Parsing Canonical Form + CRC-64-AVRO fingerprint (Avro spec) --------

_CANONICAL_PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string",
}


def parsing_canonical_form(schema, namespace: str | None = None) -> str:
    """The Avro Parsing Canonical Form of a schema (spec section
    "Transforming into Parsing Canonical Form"): fullnames, attribute
    stripping ([STRIP] doc/aliases/defaults), fixed field order, minimal
    JSON. Two schemas with equal canonical form decode identically."""
    return _pcf(schema, namespace)


def _pcf(node, ns):
    if isinstance(node, str):
        if node in _CANONICAL_PRIMITIVES:
            return f'"{node}"'
        full = node if "." in node or not ns else f"{ns}.{node}"
        return f'"{full}"'
    if isinstance(node, list):
        return "[" + ",".join(_pcf(b, ns) for b in node) + "]"
    t = node["type"]
    if isinstance(t, (dict, list)) or (
        t not in _CANONICAL_PRIMITIVES
        and t not in ("record", "enum", "array", "map", "fixed")
    ):
        # {"type": <nested schema>} wrapper
        return _pcf(t, ns)
    if t in _CANONICAL_PRIMITIVES:
        return f'"{t}"'
    if t in ("record", "enum", "fixed"):
        name = node["name"]
        if "." in name:
            full = name
            child_ns = name.rsplit(".", 1)[0]
        else:
            child_ns = node.get("namespace", ns)
            full = f"{child_ns}.{name}" if child_ns else name
        parts = [f'"name":"{full}"', f'"type":"{t}"']
        if t == "record":
            fields = ",".join(
                "{" + f'"name":"{f["name"]}"'
                + f',"type":{_pcf(f["type"], child_ns)}' + "}"
                for f in node["fields"]
            )
            parts.append(f'"fields":[{fields}]')
        elif t == "enum":
            syms = ",".join(f'"{s}"' for s in node["symbols"])
            parts.append(f'"symbols":[{syms}]')
        else:
            parts.append(f'"size":{int(node["size"])}')
        return "{" + ",".join(parts) + "}"
    if t == "array":
        return '{"type":"array","items":' + _pcf(node["items"], ns) + "}"
    if t == "map":
        return '{"type":"map","values":' + _pcf(node["values"], ns) + "}"
    raise ValueError(f"bad schema node {node!r}")


_CRC64_EMPTY = 0xC15D213AA4D7A795
_crc64_table: list | None = None


def schema_fingerprint(schema, namespace: str | None = None) -> int:
    """CRC-64-AVRO fingerprint of the Parsing Canonical Form (Avro spec)."""
    global _crc64_table
    if _crc64_table is None:
        table = []
        for i in range(256):
            fp = i
            for _ in range(8):
                fp = (fp >> 1) ^ (_CRC64_EMPTY & -(fp & 1))
            table.append(fp & 0xFFFFFFFFFFFFFFFF)
        _crc64_table = table
    fp = _CRC64_EMPTY
    for b in parsing_canonical_form(schema, namespace).encode("utf-8"):
        fp = (fp >> 8) ^ _crc64_table[(fp ^ b) & 0xFF]
    return fp


def iter_container_dir(path: str):
    """Stream all part files of a file-or-directory of Avro containers
    (the HDFS part-* layout of AvroUtils.readAvroFiles)."""
    if os.path.isfile(path):
        yield from iter_container(path)
        return
    for name in sorted(os.listdir(path)):
        if name.endswith(".avro"):
            yield from iter_container(os.path.join(path, name))


def container_schema(path: str) -> dict:
    """Read just the schema of a container file (no record decode)."""
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta = _decode(f, _META_SCHEMA)
        return json.loads(meta["avro.schema"].decode())


def read_container(path: str) -> tuple[dict, list]:
    """Read an Avro object container file -> (schema_json, records)."""
    return container_schema(path), list(iter_container(path))


def read_container_dir(path: str) -> list:
    """Read all part files of a directory of Avro containers, materialized.
    Prefer ``iter_container_dir`` for large inputs."""
    return list(iter_container_dir(path))
