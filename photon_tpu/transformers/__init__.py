"""GameTransformer: score new data with a trained GAME model.

TPU-native counterpart of photon-api transformers/GameTransformer.scala:150:
model + dataset -> per-row scores (ModelDataScores), optionally evaluated.
The reference's scoreGameDataset (:263-275) broadcasts fixed-effect
coefficients and joins random-effect models by REId; here both are gathers
against device-resident model arrays, and sub-model scores sum elementwise
(ModelDataScores ``+`` algebra).
"""

from __future__ import annotations

import dataclasses

import jax

from photon_tpu.data.game_data import GameDataset
from photon_tpu.data.random_effect import remap_for_scoring
from photon_tpu.evaluation.evaluators import EvaluatorSpec
from photon_tpu.evaluation.suite import EvaluationResults, make_suite
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class GameTransformer:
    """Reference: transformers/GameTransformer.scala (transform :150-197)."""

    model: GameModel

    def score(self, data: GameDataset) -> Array:
        """Summed sub-model scores per row — the raw model contribution, no
        offset (GameModel.score semantics; offsets are added by evaluation
        and by downstream consumers, EvaluationSuite.scala:62-66)."""
        total = None
        for cid, m in self.model.items():
            if isinstance(m, RandomEffectModel):
                codes, idx, vals = remap_for_scoring(
                    data,
                    re_type=m.random_effect_type,
                    feature_shard_id=m.feature_shard_id,
                    entity_keys=m.entity_keys,
                    proj_all=m.proj_all,
                )
                s = m.score_table(codes, idx, vals)
            elif isinstance(m, FixedEffectModel):
                s = m.model.coefficients.compute_score(
                    data.feature_shards[m.feature_shard_id]
                )
            else:
                raise TypeError(f"unknown sub-model type for {cid!r}: {m}")
            total = s if total is None else total + s
        if total is None:
            raise ValueError("empty GAME model")
        return total

    def transform(
        self,
        data: GameDataset,
        evaluators: list[str | EvaluatorSpec] | None = None,
    ) -> tuple[Array, EvaluationResults | None]:
        """Score; optionally evaluate against the dataset's labels
        (GameTransformer validation path :186-192)."""
        scores = self.score(data)
        if not evaluators:
            return scores, None
        suite = make_suite(
            evaluators,
            data.labels,
            offsets=data.offsets,
            weights=data.weights,
            group_ids={
                name: (tag.codes, tag.num_groups)
                for name, tag in data.id_tags.items()
            },
            dtype=data.labels.dtype,
        )
        return scores, suite.evaluate(scores)
