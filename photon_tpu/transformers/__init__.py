"""GameTransformer: score new data with a trained GAME model.

TPU-native counterpart of photon-api transformers/GameTransformer.scala:150:
model + dataset -> per-row scores (ModelDataScores), optionally evaluated.
The reference's scoreGameDataset (:263-275) broadcasts fixed-effect
coefficients and joins random-effect models by REId; here both are gathers
against device-resident model arrays, and sub-model scores sum elementwise
(ModelDataScores ``+`` algebra).
"""

from __future__ import annotations

import dataclasses

import jax

from photon_tpu.data.game_data import GameDataset
from photon_tpu.data.random_effect import remap_for_scoring, scoring_codes
from photon_tpu.evaluation.evaluators import EvaluatorSpec
from photon_tpu.evaluation.suite import EvaluationResults, make_suite
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    score_entity_table_with_tail,
    score_raw_features,
)
from photon_tpu.parallel.mesh import maybe_row_shard

Array = jax.Array


def fixed_effect_scorer(data: GameDataset, feature_shard_id: str, mesh=None):
    """model -> per-row scores for a fixed-effect sub-model on ``data``."""
    from photon_tpu.data.dataset import DenseFeatures, SparseFeatures

    feats = data.feature_shards[feature_shard_id]
    if mesh is not None:
        if isinstance(feats, DenseFeatures):
            feats = DenseFeatures(*maybe_row_shard(mesh, feats.x))
        elif isinstance(feats, SparseFeatures):
            feats = SparseFeatures(
                *maybe_row_shard(mesh, feats.indices, feats.values), feats.d
            )
        # DualEll tables stay replicated: the COO tail is not row-aligned.

    def scorer(m: FixedEffectModel) -> Array:
        return m.model.coefficients.compute_score(feats)

    return scorer


def random_effect_scorer(
    data: GameDataset,
    *,
    re_type: str,
    feature_shard_id: str,
    entity_keys: tuple,
    proj_all,
    width_cap: int | None = None,
    mesh=None,
):
    """model -> per-row scores for a random-effect sub-model on ``data``.

    Dense/Sparse shards take the lazy path: only the [n] entity codes and
    the [E, S] projector matrix cross the host->device link; the subspace
    remap fuses into the jitted score against the HBM-resident raw shard
    (models/game.py score_raw_features). ``DualEllFeatures`` shards fall
    back to the materialized remap table, where ``width_cap`` bounds the
    slab width (overflow rides a COO tail). With ``mesh`` the materialized
    table is row-sharded; the COO tail stays replicated (its segment-sum
    spans rows across shards).
    """
    import numpy as np

    from photon_tpu.data.dataset import DenseFeatures, SparseFeatures
    from photon_tpu.data.random_effect import DENSE_SUB_DIM_MAX

    feats = data.feature_shards[feature_shard_id]
    # A width cap — or a very wide subspace — opts out of the lazy path:
    # its [n, S] intermediates would recreate the width hazard the cap (and
    # the build-side DENSE_SUB_DIM_MAX gate) exist to bound.
    sub_dim = np.asarray(proj_all).shape[1] if np.ndim(proj_all) == 2 else 0
    if (
        width_cap is None
        and sub_dim <= DENSE_SUB_DIM_MAX
        and isinstance(feats, (DenseFeatures, SparseFeatures))
    ):
        codes_np = scoring_codes(data, re_type, entity_keys).astype(np.int32)
        codes, proj_dev = jax.device_put(
            [codes_np, np.asarray(proj_all).astype(np.int32)]
        )
        if mesh is not None:
            # Row-shard the per-row operands (dp scoring); the projector
            # matrix and coefficients stay replicated.
            from photon_tpu.parallel.mesh import replicated

            if isinstance(feats, DenseFeatures):
                codes, x = maybe_row_shard(mesh, codes, feats.x)
                feats = DenseFeatures(x)
            else:
                codes, idx_s, val_s = maybe_row_shard(
                    mesh, codes, feats.indices, feats.values
                )
                feats = SparseFeatures(idx_s, val_s, feats.d)
            proj_dev = jax.device_put(proj_dev, replicated(mesh))

        def scorer(m: RandomEffectModel) -> Array:
            return score_raw_features(m.coefficients, codes, feats, proj_dev)

        return scorer

    codes, idx, vals, tail = remap_for_scoring(
        data,
        re_type=re_type,
        feature_shard_id=feature_shard_id,
        entity_keys=entity_keys,
        proj_all=proj_all,
        width_cap=width_cap,
    )
    codes, idx, vals = maybe_row_shard(mesh, codes, idx, vals)

    def scorer(m: RandomEffectModel) -> Array:
        return score_entity_table_with_tail(
            m.coefficients, codes, idx, vals, tail
        )

    return scorer


def make_submodel_scorer(sub_model, data: GameDataset,
                         width_cap: int | None = None, mesh=None):
    """Dispatch a scorer for one trained sub-model (GameModel.score arm)."""
    if isinstance(sub_model, RandomEffectModel):
        return random_effect_scorer(
            data,
            re_type=sub_model.random_effect_type,
            feature_shard_id=sub_model.feature_shard_id,
            entity_keys=sub_model.entity_keys,
            proj_all=sub_model.proj_all,
            width_cap=width_cap,
            mesh=mesh,
        )
    if isinstance(sub_model, FixedEffectModel):
        return fixed_effect_scorer(data, sub_model.feature_shard_id, mesh)
    raise TypeError(f"unknown sub-model type: {sub_model}")


def evaluate_scores(
    data: GameDataset,
    scores: Array,
    evaluators: list[str | EvaluatorSpec] | None,
) -> EvaluationResults | None:
    """Evaluate raw model scores against a dataset's labels — the
    GameTransformer validation path (:186-192), shared with the serving
    batch route (cli/score.py) so both scoring implementations grade
    through one suite construction."""
    if not evaluators:
        return None
    suite = make_suite(
        evaluators,
        data.labels,
        offsets=data.offsets,
        weights=data.weights,
        group_ids={
            name: (tag.codes, tag.num_groups)
            for name, tag in data.id_tags.items()
        },
        dtype=data.labels.dtype,
    )
    return suite.evaluate(scores)


@dataclasses.dataclass(frozen=True)
class GameTransformer:
    """Reference: transformers/GameTransformer.scala (transform :150-197)."""

    model: GameModel
    # Optional jax.sharding.Mesh: score tables are placed row-sharded (the
    # batch-scoring twin of the estimator's dp path; GameScoringDriver runs
    # on the cluster session like the training driver).
    mesh: object = None

    def score(self, data: GameDataset) -> Array:
        """Summed sub-model scores per row — the raw model contribution, no
        offset (GameModel.score semantics; offsets are added by evaluation
        and by downstream consumers, EvaluationSuite.scala:62-66)."""
        total = None
        for _, m in self.model.items():
            s = make_submodel_scorer(m, data, mesh=self.mesh)(m)
            total = s if total is None else total + s
        if total is None:
            raise ValueError("empty GAME model")
        return total

    def transform(
        self,
        data: GameDataset,
        evaluators: list[str | EvaluatorSpec] | None = None,
    ) -> tuple[Array, EvaluationResults | None]:
        """Score; optionally evaluate against the dataset's labels
        (GameTransformer validation path :186-192)."""
        scores = self.score(data)
        return scores, evaluate_scores(data, scores, evaluators)
