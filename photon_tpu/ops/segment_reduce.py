"""Tiled TPU segment-reduce (Pallas): scatter-add as windowed MXU work.

The GLMix hot path scatters per-entity results back to canonical rows in
three places — the bucket scorer's ``z.at[row_ids].add`` (models/game.py),
the width-capped score table's COO overflow tail (``segment_sum``), and
the wide-ELL densify ``.at[rows, slots].add`` (algorithm/random_effect.py).
XLA lowers all three to scatter-add, which serializes on duplicate
indices and reads HBM at gather granularity — the per-entity
gather/scatter is exactly where BENCH_r05's fraction-of-HBM-peak gauge
(~4.6%) says the bandwidth goes unclaimed.

This kernel reformulates scatter-add as a WINDOWED ONE-HOT CONTRACTION:

- the OUTPUT is tiled into ``_OUT_TILE``-segment blocks; the grid is
  ``(out_tiles, k_tiles)`` and each out block accumulates across its k
  steps in VMEM (init at ``k == 0``), so the result is written to HBM
  exactly once;
- for each (out tile j, step k) the kernel streams ONE ``_IN_TILE``
  block of (ids, values) and adds ``values @ onehot(ids - j*_OUT_TILE)``
  — an [IT] x [IT, OT] matmul at full MXU width; elements whose id
  falls outside the window contribute an all-zero one-hot row, so
  visiting extra tiles is always CORRECT, only ever wasteful;
- which input tiles each out tile visits comes from a SCALAR-PREFETCHED
  ``starts`` vector (``pltpu.PrefetchScalarGridSpec``): the block index
  maps resolve ``starts[j] + k`` before the body runs. The caller
  guarantees COVERAGE — every element whose id lands in window j sits
  within the K visited tiles — which is a static-shape argument: for
  sorted ids with per-segment multiplicity <= ``multiplicity``, a
  window holds at most ``_OUT_TILE * multiplicity`` elements, so
  ``K = ceil(_OUT_TILE * multiplicity / _IN_TILE) + 1`` always covers.

HBM traffic: each input element is read K times (K == 2 for the
multiplicity-1 scoring scatter) and each output written once — streaming
reads/writes, no per-element gather granularity, which is what lets the
fraction-of-HBM-peak metric actually engage on the scoring pass.

Values may be float32 or bfloat16; accumulation is ALWAYS float32 (the
mixed-precision invariant of ops/precision.py — this module is the
"segment-reduce" the ``bf16-accumulation`` tier-1 rule names).

Scope and fallback mirror ops/newton_kernel.py: Mosaic lowering is
TPU-only, so ``interpret_required()`` routes forced runs on other
backends through ``interpret=True``; unforced non-TPU backends take the
``.at[].add`` / ``segment_sum`` fallback, which doubles as the parity
oracle (tests/test_segment_reduce.py: duplicate slots, empty segments,
phantom-entity masks, out-of-bounds drop codes).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

LANES = 128
_OUT_TILE = 8 * LANES  # segments per output block
_IN_TILE = 8 * LANES  # (id, value) elements per streamed input block
# Over-visit bound: callers whose coverage argument needs more than this
# many input tiles per output tile take the XLA fallback instead of
# compiling a pathological grid.
_MAX_K_TILES = 64

# Program contract (audited by `python -m photon_tpu.analysis
# --semantic`): one segment-reduce shape is ONE program — ids, values
# and the prefetched starts are traced operands; only the static
# (elements, segments, k_tiles) shape mints a new executable. No host
# callbacks, no f64: this kernel runs inside the fused fit's sweep and
# inside score programs.
PROGRAM_AUDIT = dict(
    name="segment-reduce-kernel",
    entry="ops.segment_reduce.sorted_segment_sum",
    builder="build_segment_reduce",
    max_programs=1,
    recompiles_on=("reduce_shape",),
    hot_loop=True,
)

# Tier-5 numerics contract (`--numerics`, ANALYSIS.md): both engage
# modes are dtype-flow walked on bf16 values — the forced Pallas
# kernel (interpreted off-TPU) and the XLA segment_sum fallback. The
# kernel's windowed one-hot contraction replaces the scatter entirely
# (no nondeterministic family at all — the determinism story is
# by-construction); the fallback's scatter-add rides on the sorted-ids
# precondition. Budget: one storage rounding + up to 2 f32
# accumulation steps per element (the kernel re-reduces each streamed
# window tile once).
NUMERICS_AUDIT = dict(
    name="segment-reduce-numerics",
    entry="ops.segment_reduce.sorted_segment_sum",
    covers=("segment-reduce-kernel",),
    builder="build_segment_reduce_numerics",
    budgets={
        "segment_sum_*": "u16 + 2 * u32 * m",
    },
    deterministic={
        "segment_sum_fallback:scatter-add": (
            "ids are sorted by precondition "
            "(indices_are_sorted=True): each segment's colliding adds "
            "form one contiguous run that XLA combines in index order; "
            "the kernel path removes the scatter entirely"
        ),
    },
    tolerance=1.5,
)

# Trace-time site registry (host-side): every kernel instantiation
# records its static shape here so FusedFit._ledger_record /
# cli.profile can register a priced census row for the kernel without
# the dispatch path ever touching the ledger. Keyed by (site, shape) —
# one site (e.g. the bucket scorer) traces once PER BUCKET SHAPE, and
# ``traced_sites()`` aggregates the analytic cost per site so the
# census row prices every instance, not whichever traced last. The
# registry is process-global trace metadata (it lives as long as the
# traces do); tests clear it between cases via the conftest reset.
_TRACED_SITES: dict[tuple, dict] = {}


def interpret_required() -> bool:
    """True when pallas_call must run interpreted on this backend
    (same contract as ops/newton_kernel.interpret_required)."""
    return jax.default_backend() != "tpu"


def kernel_supported(num_values: int, num_segments: int, dtype) -> bool:
    """Whether the Pallas path serves this reduce shape on this backend.

    ``PHOTON_SEGMENT_KERNEL``: ``auto`` (default — real TPU only),
    ``force``/``on``/``1`` (every backend; non-TPU runs interpreted —
    slow, for parity tests), ``off``/``0`` (always the XLA fallback).
    """
    flag = os.environ.get("PHOTON_SEGMENT_KERNEL", "auto").lower()
    if flag in ("0", "off", "false"):  # photon: ignore[spmd-host-divergence] -- kernel-select flag is launch config, exported fleet-uniform; divergence trips the --spmd trace proof
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    if num_values < 1 or num_segments < 1:
        return False
    # int32 position/id arithmetic below: guard the flat sizes.
    if num_values >= 2**31 or num_segments >= 2**31:
        return False
    if flag in ("1", "on", "force"):  # photon: ignore[spmd-host-divergence] -- kernel-select flag is launch config, exported fleet-uniform; divergence trips the --spmd trace proof
        return True
    return jax.default_backend() == "tpu"


def _record_site(site: str, num_values: int, num_segments: int,
                 k_tiles: int, dtype) -> None:
    """Host bookkeeping at the wrapper level (runs per wrapper call on
    the eager path, per TRACE under an outer jit — never per kernel
    dispatch): the analytic cost of one instantiation, in the
    costmodel's counter vocabulary, for the ledger census."""
    esize = jnp.dtype(dtype).itemsize
    dt = str(jnp.dtype(dtype))
    _TRACED_SITES[(site, int(num_values), int(num_segments),
                   int(k_tiles), dt)] = {
        "num_values": int(num_values),
        "num_segments": int(num_segments),
        "k_tiles": int(k_tiles),
        "dtype": dt,
        # K streamed reads of (value + int32 id) per element + one f32
        # write per segment; FLOPs ~ the one-hot FMA per visited pair.
        "cost": {
            "flops": 2.0 * num_values * k_tiles,
            "hbm_bytes": float(
                num_values * k_tiles * (esize + 4) + num_segments * 4
            ),
            "transcendentals": 0.0,
        },
    }


def traced_sites() -> dict[str, dict]:
    """Per-SITE aggregate of every kernel instantiation traced so far
    (host bookkeeping for the cost ledger; see
    FusedFit._ledger_record): a site with several bucket shapes prices
    the SUM of its instances' analytic costs, not whichever traced
    last."""
    out: dict[str, dict] = {}
    for (site, *_rest), info in _TRACED_SITES.items():
        agg = out.get(site)
        if agg is None:
            agg = out[site] = {
                "instances": 0,
                "num_values": 0,
                "num_segments": 0,
                "cost": {"flops": 0.0, "hbm_bytes": 0.0,
                         "transcendentals": 0.0},
            }
        agg["instances"] += 1
        agg["num_values"] += info["num_values"]
        agg["num_segments"] += info["num_segments"]
        for key in ("flops", "hbm_bytes", "transcendentals"):
            agg["cost"][key] += info["cost"][key]
    return out


def _kernel(starts_ref, ids_ref, vals_ref, out_ref):
    del starts_ref  # consumed by the index maps (scalar prefetch)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    base = pl.program_id(0) * _OUT_TILE
    ids = ids_ref[0]  # [IT, 1] int32
    onehot = (
        ids
        == base
        + jax.lax.broadcasted_iota(jnp.int32, (_IN_TILE, _OUT_TILE), 1)
    ).astype(jnp.float32)
    vals = vals_ref[...].astype(jnp.float32)  # [1, IT]
    out_ref[...] += jnp.dot(
        vals, onehot, preferred_element_type=jnp.float32
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "k_tiles", "interpret"),
)
def _windowed_sum(
    values: Array,  # [m] f32/bf16
    ids: Array,  # [m] int32; id >= num_segments drops
    starts: Array,  # [out_tiles] int32 first input tile per out tile
    *,
    num_segments: int,
    k_tiles: int,
    interpret: bool,
):
    """The pallas_call wrapper: pads to tile multiples, clamps the
    prefetched starts into range, dispatches the windowed grid, and
    slices the flat [num_segments] f32 result back out."""
    m = values.shape[0]
    out_tiles = -(-num_segments // _OUT_TILE)
    n_pad = out_tiles * _OUT_TILE
    m_tiles = max(-(-m // _IN_TILE), k_tiles)
    pad = m_tiles * _IN_TILE - m
    if m >= 2**31 or n_pad >= 2**31:
        # ids/starts are int32 (the kernel's lane dtype): past 2^31 the
        # flat positions would silently wrap — kernel_supported refuses
        # these shapes, and the direct entry must too.
        raise ValueError(
            f"segment_reduce shapes exceed int32 range: m={m}, "
            f"segments={n_pad}")
    # Padding ids sit beyond every window (n_pad > any window base + o);
    # caller-side drop markers (id == num_segments) land either beyond
    # the windows or in the sliced-away [num_segments, n_pad) range.
    ids_p = jnp.pad(ids, (0, pad), constant_values=n_pad)
    vals_p = jnp.pad(values, (0, pad))
    starts = jnp.clip(starts, 0, m_tiles - k_tiles).astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(out_tiles, k_tiles),
        in_specs=[
            pl.BlockSpec(
                (1, _IN_TILE, 1), lambda j, k, s: (s[j] + k, 0, 0)
            ),
            pl.BlockSpec((1, _IN_TILE), lambda j, k, s: (s[j] + k, 0)),
        ],
        out_specs=pl.BlockSpec((1, _OUT_TILE), lambda j, k, s: (j, 0)),
    )
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_tiles, _OUT_TILE),
                                       jnp.float32),
        interpret=interpret,
    )(
        starts,
        ids_p.reshape(m_tiles, _IN_TILE, 1),
        vals_p.reshape(m_tiles, _IN_TILE),
    )
    return out.reshape(-1)[:num_segments]


def _k_for(per_window_elements: int) -> int:
    return -(-int(per_window_elements) // _IN_TILE) + 1


def sorted_segment_sum(
    values: Array,
    ids: Array,
    num_segments: int,
    *,
    multiplicity: int = 1,
    site: str = "segment_reduce",
    interpret: bool | None = None,
) -> Array:
    """Segment sum over SORTED int32 ids (f32 result).

    ``multiplicity`` is a STATIC bound on how many elements share one
    segment id — the coverage argument that sizes the visited-tile
    window (callers derive it from plan structure: 1 for the bucket
    scorer, the host-computed tail bound for score tables). ids equal
    to ``num_segments`` (or beyond) are dropped — the phantom-row /
    padding convention of the ``.at[].add`` paths this replaces.

    Falls back to ``segment_sum`` when the kernel is unsupported here.
    """
    n = int(num_segments)
    m = int(values.shape[0])
    k_tiles = _k_for(_OUT_TILE * max(int(multiplicity), 1))
    if (
        not kernel_supported(m, n, values.dtype)
        or k_tiles > _MAX_K_TILES
    ):
        return jax.ops.segment_sum(
            values.astype(jnp.float32),
            jnp.minimum(ids, n),
            num_segments=n + 1,
            indices_are_sorted=True,
        )[:n]
    bases = jnp.arange(-(-n // _OUT_TILE), dtype=jnp.int32) * _OUT_TILE
    starts = (
        jnp.searchsorted(ids, bases).astype(jnp.int32)
        // _IN_TILE
    )
    # Site bookkeeping lives HERE, not in the jitted wrapper: the site
    # label is census metadata, and making it a static argument would
    # mint one executable per label for identical reduce shapes —
    # contradicting the contract that shape is the only recompile key.
    _record_site(site, m, n, k_tiles, values.dtype)
    return _windowed_sum(
        values, ids.astype(jnp.int32), starts,
        num_segments=n, k_tiles=k_tiles,
        interpret=(
            interpret_required() if interpret is None else interpret
        ),
    )


def scatter_add_rows(
    z: Array,  # [n]
    row_ids: Array,  # [B, R] int32 canonical rows
    zb: Array,  # [B, R] per-slot scores (f32 or bf16)
    valid: Array,  # [B, R] bool — False lanes drop
    *,
    site: str = "segment_reduce/score",
) -> Array:
    """``z.at[row_ids].add(where(valid, zb, 0))`` as sort + tiled
    reduce — the bucket scorer's scatter (models/game.py:_bucket_
    score_add). Valid row ids are DISTINCT within one bucket (each kept
    row belongs to exactly one entity), so multiplicity is 1 and the
    sort is a cheap int32 radix whose cost XLA hoists out of the fused
    sweep loop (the ids are loop-invariant operands).
    """
    n = z.shape[0]
    ids = jnp.where(valid, row_ids, n).reshape(-1).astype(jnp.int32)
    vals = zb.reshape(-1)
    order = jnp.argsort(ids)
    out = sorted_segment_sum(
        jnp.take(vals, order),
        jnp.take(ids, order),
        n,
        multiplicity=1,
        site=site,
    )
    return z + out.astype(z.dtype)


# Pair-product transient cap for the gram route: ``ell_gram_blocks``
# materializes the [B, R, k, k] f32 pair products before the flat
# reduce; past this many elements the transient (plus the argsort over
# it) outweighs what skipping the dense [B, R, S] slab saves, and the
# plan-time host pass that bounds window coverage stops being free.
GRAM_ELEMENT_BUDGET = 1 << 26


def window_counts_np(ids: np.ndarray, num_segments: int) -> np.ndarray:
    """HOST: per-``_OUT_TILE``-window element counts for flat segment
    ids — plan-time numpy bookkeeping for the gram route. The planner
    (data/random_effect) accumulates these over entity chunks and feeds
    the max through ``window_bound_from_counts`` to get the bound
    ``ell_gram_supported`` consumes."""
    return np.bincount(
        ids // _OUT_TILE, minlength=-(-int(num_segments) // _OUT_TILE)
    )


def window_bound_from_counts(max_count) -> int:
    """Convert a max per-window element count to the ``multiplicity``
    currency of ``sorted_segment_sum`` (elements per window divided by
    ``_OUT_TILE``, ceil, floored at 1): the kernel visits
    ``_k_for(_OUT_TILE * bound)`` input tiles per window, which covers
    exactly when no window holds more than ``_OUT_TILE * bound``
    elements."""
    return max(-(-int(max_count) // _OUT_TILE), 1)


def ell_gram_supported(
    b: int, r: int, k: int, sub_dim: int, *,
    grad_mult: int, hess_mult: int,
) -> bool:
    """Whether the gram-route reduces (``ell_gram_blocks`` +
    ``ell_segment_slots``) serve this ELL block shape on this backend.

    ``grad_mult`` / ``hess_mult`` are HOST-computed WINDOW bounds
    (data/random_effect.py ``block_gram_mults``): the max nonzero
    elements landing in one ``_OUT_TILE``-segment output window,
    divided (ceil) by ``_OUT_TILE`` — the same coverage currency
    ``sorted_segment_sum`` sizes its visited-tile window with. A
    uniform per-segment bound would be useless here: the intercept
    slot co-occurs with every row, so per-SEGMENT multiplicity is the
    row count, while whole windows stay cheap.
    """
    s = int(sub_dim)
    m_pair = b * r * k * k
    if m_pair > GRAM_ELEMENT_BUDGET:
        return False
    if _k_for(_OUT_TILE * max(int(grad_mult), 1)) > _MAX_K_TILES:
        return False
    if _k_for(_OUT_TILE * max(int(hess_mult), 1)) > _MAX_K_TILES:
        return False
    # Products are formed f32 regardless of the storage dtype.
    return (
        kernel_supported(m_pair, b * s * s, jnp.float32)
        and kernel_supported(b * r * k, b * s, jnp.float32)
    )


def ell_segment_slots(
    x_indices: Array,  # [B, R, k] int32 subspace slots
    x_values: Array,  # [B, R, k] (f32 or bf16 storage)
    row_weights: Array,  # [B, R] per-row scale (e.g. weighted targets)
    sub_dim: int,
    *,
    multiplicity: int,
    site: str = "segment_reduce/gram",
) -> Array | None:
    """Per-entity weighted slot totals straight from the ELL layout:
    ``out[b, s] = sum_{r, j: idx[b,r,j] == s} row_weights[b,r] * v[b,r,j]``
    as ONE flat sorted tiled reduce — the ``X^T (w*y)`` half of the
    normal equations with no [B, R, S] densified slab in between.

    Products are formed in f32 (the ELL payload is read once at storage
    width, then upcast), and ZERO products are remapped to the drop
    segment: the host-computed window bound counts only nonzero entries,
    so padding lanes must not land in real segments. ``multiplicity`` is
    the window bound described at ``ell_gram_supported``. Returns None
    when the kernel does not serve this shape.
    """
    b, r, k = x_indices.shape
    s = int(sub_dim)
    n = b * s
    m = b * r * k
    if (
        not kernel_supported(m, n, jnp.float32)
        or _k_for(_OUT_TILE * max(int(multiplicity), 1)) > _MAX_K_TILES
    ):
        return None
    vals = (
        x_values.astype(jnp.float32)
        * row_weights.astype(jnp.float32)[:, :, None]
    ).reshape(-1)
    ent = jnp.arange(b, dtype=jnp.int32)[:, None, None] * s
    ids = (x_indices.astype(jnp.int32) + ent).reshape(-1)
    ids = jnp.where(vals != 0.0, ids, n)
    order = jnp.argsort(ids)
    flat = sorted_segment_sum(
        jnp.take(vals, order), jnp.take(ids, order), n,
        multiplicity=multiplicity, site=site,
    )
    return flat.reshape(b, s)


def ell_gram_blocks(
    x_indices: Array,  # [B, R, k] int32 subspace slots
    x_values: Array,  # [B, R, k] (f32 or bf16 storage)
    weights: Array,  # [B, R] row weights (curvature)
    sub_dim: int,
    *,
    multiplicity: int,
    site: str = "segment_reduce/gram",
) -> Array | None:
    """Per-entity weighted gram matrices ``X^T diag(w) X`` straight from
    the ELL layout, [B, S, S] f32: every pair product
    ``w[b,r] * v[b,r,j] * v[b,r,l]`` lands in flat segment
    ``b*S^2 + idx[b,r,j]*S + idx[b,r,l]`` and ONE sorted tiled reduce
    aggregates the whole bucket's Hessians — the dense [B, R, S] slab
    the direct solver previously needed never exists.

    Same f32-product / zero-drop / window-bound conventions as
    ``ell_segment_slots`` (the bound here is ``hess_mult``). Returns
    None when the kernel does not serve this shape.
    """
    b, r, k = x_indices.shape
    s = int(sub_dim)
    n = b * s * s
    m = b * r * k * k
    if (
        m > GRAM_ELEMENT_BUDGET
        or not kernel_supported(m, n, jnp.float32)
        or _k_for(_OUT_TILE * max(int(multiplicity), 1)) > _MAX_K_TILES
    ):
        return None
    xf = x_values.astype(jnp.float32)
    vals = (
        weights.astype(jnp.float32)[:, :, None, None]
        * xf[:, :, :, None]
        * xf[:, :, None, :]
    ).reshape(-1)
    idx = x_indices.astype(jnp.int32)
    ent = (
        jnp.arange(b, dtype=jnp.int32)[:, None, None, None] * (s * s)
    )
    ids = (ent + idx[:, :, :, None] * s + idx[:, :, None, :]).reshape(-1)
    ids = jnp.where(vals != 0.0, ids, n)
    order = jnp.argsort(ids)
    flat = sorted_segment_sum(
        jnp.take(vals, order), jnp.take(ids, order), n,
        multiplicity=multiplicity, site=site,
    )
    return flat.reshape(b, s, s)


def densify_ell_blocks(
    x_indices: Array,  # [B, R, k] int32 subspace slots (dups sum)
    x_values: Array,  # [B, R, k]
    sub_dim: int,
    *,
    site: str = "segment_reduce/densify",
) -> Array | None:
    """[B, R, k] slot-ELL -> [B, R, S] dense via ONE flat tiled reduce
    (the wide-subspace ``.at[rows, slots].add`` scatter of
    algorithm/random_effect.py, batched over the whole bucket instead
    of per entity under vmap). Returns None when the kernel does not
    serve this shape — the caller keeps the ELL layout.

    Coverage here uses blockedness, not sortedness: flat ids are
    ``row * S + slot`` with rows ascending in flatten order, so the
    elements touching output window j span at most ``_OUT_TILE/S + 2``
    rows — a static position range the ``starts`` vector encodes.
    """
    b, r, k = x_indices.shape
    s = int(sub_dim)
    rows = b * r
    n = rows * s
    m = rows * k
    rows_per_window = _OUT_TILE // s + 3
    k_tiles = _k_for(rows_per_window * k)
    if (
        s > _OUT_TILE
        or k_tiles > _MAX_K_TILES
        or not kernel_supported(m, n, x_values.dtype)
    ):
        return None
    row_base = (
        jnp.arange(rows, dtype=jnp.int32)[:, None] * s
    )  # [BR, 1]
    ids = (
        x_indices.reshape(rows, k).astype(jnp.int32) + row_base
    ).reshape(-1)
    out_tiles = -(-n // _OUT_TILE)
    # Exact row containing each window's base id (j*_OUT_TILE)//s — an
    # approximation here would drift by j*(_OUT_TILE % s)/s rows and
    # outrun the k_tiles coverage window at large j.
    first_row = (
        jnp.arange(out_tiles, dtype=jnp.int32) * _OUT_TILE
    ) // s
    starts = (first_row * k) // _IN_TILE
    _record_site(site, m, n, k_tiles, x_values.dtype)
    flat = _windowed_sum(
        x_values.reshape(-1), ids, starts,
        num_segments=n,
        k_tiles=k_tiles,
        interpret=interpret_required(),
    )
    return flat.reshape(b, r, s).astype(x_values.dtype)
