"""Fused per-entity Newton-step TPU kernel (Pallas): H never leaves VMEM.

One damped-Newton/IRLS step for a whole bucket of per-entity GLM
subproblems — margins, curvature, the [S, S] Hessian build, an S-step CG
direction solve, the vectorized Armijo line search, and the objective/
gradient refresh at the accepted point — in a single Pallas kernel.

Why: under XLA the batched [B, S, S] Hessian must round-trip through HBM
between its MXU build and the CG re-reads, and TPU (8, 128) tiling
physically inflates that layout ~7-10x at S ~ 17. The round-4 probe
(experiments/README.md) identified fusing the build THROUGH the solve as
the remaining ~3-6x of per-iteration headroom; this kernel implements it:

- ENTITIES LIVE IN LANES: each grid step owns 128 entities. The slab
  arrives pre-transposed as [S, R, B] so every access is a contiguous
  leading-dim block slice; all math is elementwise / single-axis reduces
  over [sublane, 128] tiles at full VPU width (per-entity dot_generals —
  the round-4 probe's layout — serialize and ran 7x SLOWER than XLA).
- H lives in a [S, S, 128] VMEM scratch; the CG matvec is S broadcast
  FMAs over [S, 128] tiles.
- The line search runs its T trials sequentially per 128-lane block,
  tracking the largest passing step per lane (argmax on bools does not
  lower in Mosaic).

Measured (bench user bucket, [~100k, 64, 17] logistic): 9.9ms per Newton
step vs 30.9ms for the batch-minor XLA step — 3.1x.

Scope: float32, dense slabs, logistic/Poisson losses (the two losses the
damped-Newton path serves), R * S bounded so a block fits VMEM. The
batch-minor XLA path remains as fallback and parity oracle
(tests/test_newton_kernel.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_tpu.types import TaskType

Array = jax.Array

LANES = 128

# Program contract (audited by `python -m photon_tpu.analysis --semantic`;
# machinery in analysis/program.py): one Newton step for a bucket shape is
# ONE program — damping/λ/weights are traced operands; only the bucket
# shape (r, s) and the line-search trial count are static and may mint a
# new executable. No host callbacks, no f64, ever: this kernel sits inside
# the fused fit's per-iteration loop.
PROGRAM_AUDIT = dict(
    name="newton-kernel",
    entry="ops.newton_kernel.newton_step_lanes",
    builder="build_newton_kernel",
    max_programs=1,
    recompiles_on=("bucket_shape", "line_search_trials"),
    hot_loop=True,
)
# x block is [S, R, LANES] f32 in VMEM; stay well under the ~16MB budget
# (double buffering + scratch + vectors).
_MAX_RS = 16_384
_LINE_SEARCH_TRIALS = 16


def interpret_required() -> bool:
    """True when pallas_call must run interpreted on this backend.

    Mosaic lowering is TPU-only: a force-flagged run on any other
    backend (CPU, GPU) routes through ``interpret=True`` (slow, but
    correct and traceable) instead of crashing in lowering.
    """
    return jax.default_backend() != "tpu"


def kernel_supported(task: TaskType, dtype, r: int, s: int) -> bool:
    flag = os.environ.get("PHOTON_NEWTON_KERNEL", "auto").lower()
    if flag in ("0", "off", "false"):  # photon: ignore[spmd-host-divergence] -- kernel-select flag is launch config, exported fleet-uniform; divergence trips the --spmd trace proof
        return False
    if jnp.dtype(dtype) != jnp.float32:
        return False
    if task not in (TaskType.LOGISTIC_REGRESSION,
                    TaskType.POISSON_REGRESSION):
        return False
    if r * s > _MAX_RS:
        return False
    if flag in ("1", "on", "force"):  # photon: ignore[spmd-host-divergence] -- kernel-select flag is launch config, exported fleet-uniform; divergence trips the --spmd trace proof
        # Callers pass interpret=interpret_required() so a forced run on
        # a non-TPU backend executes the interpreter path rather than
        # failing in Mosaic.
        return True
    # Auto: only a real TPU runs the kernel. Other accelerators must take
    # the batch-minor XLA fallback — the interpreter path is orders of
    # magnitude slower and is reserved for the explicit force flag.
    return jax.default_backend() == "tpu"


def _loss_terms(task: TaskType, z, y):
    """(loss, dz, dzz) elementwise — mirrors ops/losses.py for the two
    strictly convex smooth losses the Newton path serves."""
    if task == TaskType.LOGISTIC_REGRESSION:
        # Labels may arrive as {0,1} OR {-1,1}: anything above the
        # positive-response threshold counts as positive, exactly as
        # ops/losses.py (MathConst.POSITIVE_RESPONSE_THRESHOLD = 0.5).
        ind = jnp.where(y > 0.5, 1.0, 0.0)
        p = 1.0 / (1.0 + jnp.exp(-z))
        loss = jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(z, 0.0) \
            - z * ind
        return loss, p - ind, p * (1 - p)
    # Poisson: loss = exp(z) - y z (raw counts; PoissonLossFunction.scala)
    ez = jnp.exp(z)
    return ez - y * z, ez - y, ez


def _make_kernel(r: int, s: int, task: TaskType, trials: int):
    def kernel(x_ref, w_ref, y_ref, wt_ref, off_ref, l2_ref, mt_ref,
               vm_ref, f_ref, w_out, f_out, g_out, imp_out, h_ref):
        w = w_ref[...]           # [S, BL]
        l2 = l2_ref[...]
        mt = mt_ref[...]
        vm = vm_ref[...]
        y = y_ref[...]           # [R, BL]
        wt = wt_ref[...]
        off = off_ref[...]
        f_prev = f_ref[...]      # [1, BL]

        z = off
        for i in range(s):
            z = z + x_ref[i] * w[i:i + 1, :]
        loss0, dz0, dzz0 = _loss_terms(task, z, y)
        c = wt * dzz0
        d1 = wt * dz0

        g_rows = []
        for i in range(s):
            xs = x_ref[i]
            xc = xs * c
            for t in range(i + 1):
                row = jnp.sum(xc * x_ref[t], axis=0, keepdims=True)
                if t == i:
                    row = row + l2[i:i + 1, :] + (1.0 - vm[i:i + 1, :])
                h_ref[i, t, :] = row[0]
                if t != i:
                    h_ref[t, i, :] = row[0]
            g_rows.append(jnp.sum(xs * d1, axis=0, keepdims=True))
        g = (jnp.concatenate(g_rows, axis=0) + l2 * (w - mt)) * vm

        def matvec(pp):
            acc = h_ref[:, 0, :] * pp[0:1, :]
            for t in range(1, s):
                acc = acc + h_ref[:, t, :] * pp[t:t + 1, :]
            return acc

        b0 = -g

        def cg_step(_, st):
            xx, rr, pp, rs = st
            hp = matvec(pp)
            denom = jnp.sum(pp * hp, axis=0, keepdims=True)
            alpha = rs / jnp.maximum(denom, 1e-30)
            xx = xx + alpha * pp
            rr = rr - alpha * hp
            rs2 = jnp.sum(rr * rr, axis=0, keepdims=True)
            pp = rr + (rs2 / jnp.maximum(rs, 1e-30)) * pp
            return xx, rr, pp, rs2

        d, _, _, _ = lax.fori_loop(
            0, s, cg_step,
            (jnp.zeros_like(b0), b0, b0,
             jnp.sum(b0 * b0, axis=0, keepdims=True)),
        )
        d = d * vm
        gd = jnp.sum(g * d, axis=0, keepdims=True)
        bad = gd >= 0.0
        d = jnp.where(bad, -g, d)
        gd = jnp.where(bad, -jnp.sum(g * g, axis=0, keepdims=True), gd)

        zd = jnp.zeros_like(z)
        for i in range(s):
            zd = zd + x_ref[i] * d[i:i + 1, :]

        t_sel = jnp.zeros_like(gd)
        f_sel = f_prev
        for k in range(trials):
            tk = 0.5 ** k
            loss_k, _, _ = _loss_terms(task, z + tk * zd, y)
            f_k = jnp.sum(wt * loss_k, axis=0, keepdims=True) + 0.5 * \
                jnp.sum(l2 * (w + tk * d - mt) ** 2, axis=0, keepdims=True)
            ok = (f_k <= f_prev + 1e-4 * tk * gd) & (t_sel == 0.0)
            t_sel = jnp.where(ok, tk, t_sel)
            f_sel = jnp.where(ok, f_k, f_sel)
        improved = (t_sel > 0.0) & (f_sel < f_prev)
        w_new = jnp.where(improved, w + t_sel * d, w)

        z2 = off
        for i in range(s):
            z2 = z2 + x_ref[i] * w_new[i:i + 1, :]
        loss2, dz2, _ = _loss_terms(task, z2, y)
        f_new = jnp.sum(wt * loss2, axis=0, keepdims=True) + 0.5 * \
            jnp.sum(l2 * (w_new - mt) ** 2, axis=0, keepdims=True)
        g2_rows = []
        for i in range(s):
            g2_rows.append(jnp.sum(x_ref[i] * (wt * dz2), axis=0,
                                   keepdims=True))
        g_new = (jnp.concatenate(g2_rows, axis=0) + l2 * (w_new - mt)) * vm

        w_out[...] = w_new
        f_out[...] = f_new
        g_out[...] = g_new
        imp_out[...] = improved.astype(jnp.float32)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("r", "s", "task", "trials", "interpret"),
)
def newton_step_lanes(
    x_t: Array,   # [S, R, Bp] transformed slab, entities in lanes
    w: Array,     # [S, Bp]
    y: Array,     # [R, Bp]
    wt: Array,    # [R, Bp]
    off: Array,   # [R, Bp]
    l2: Array,    # [S, Bp]
    mt: Array,    # [S, Bp]
    vm: Array,    # [S, Bp]
    f: Array,     # [1, Bp]
    *,
    r: int,
    s: int,
    task: TaskType,
    trials: int = _LINE_SEARCH_TRIALS,
    interpret: bool = False,
):
    """One fused Newton step for Bp (lane-padded) entities.

    Returns (w_new [S, Bp], f_new [1, Bp], g_new [S, Bp],
    improved [1, Bp] float)."""
    bp = x_t.shape[-1]
    nb = bp // LANES
    vec = lambda: pl.BlockSpec((s, LANES), lambda i: (0, i))  # noqa: E731
    row = lambda: pl.BlockSpec((r, LANES), lambda i: (0, i))  # noqa: E731
    one = lambda: pl.BlockSpec((1, LANES), lambda i: (0, i))  # noqa: E731
    return pl.pallas_call(
        _make_kernel(r, s, task, trials),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((s, r, LANES), lambda i: (0, 0, i)),
            vec(), row(), row(), row(), vec(), vec(), vec(), one(),
        ],
        out_specs=[vec(), one(), vec(), one()],
        out_shape=[
            jax.ShapeDtypeStruct((s, bp), jnp.float32),
            jax.ShapeDtypeStruct((1, bp), jnp.float32),
            jax.ShapeDtypeStruct((s, bp), jnp.float32),
            jax.ShapeDtypeStruct((1, bp), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((s, s, LANES), jnp.float32)],
        interpret=interpret,
    )(x_t, w, y, wt, off, l2, mt, vm, f)


def pad_lanes(n: int) -> int:
    return -(-n // LANES) * LANES


def to_lanes(a: Array, bp: int) -> Array:
    """[B, ...] -> [..., Bp] with zero padding on the entity axis."""
    pad = bp - a.shape[0]
    if pad:
        a = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
    axes = tuple(range(1, a.ndim)) + (0,)
    return jnp.transpose(a, axes)
