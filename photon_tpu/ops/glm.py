"""The GLM objective: value / gradient / HVP / Hessian as fused matvecs.

TPU-native replacement for the reference's entire aggregator layer:
``ValueAndGradientAggregator`` (photon-lib function/glm/
ValueAndGradientAggregator.scala:33-348), ``HessianVectorAggregator``
(HessianVectorAggregator.scala:33-290), ``HessianMatrixAggregator`` and
``HessianDiagonalAggregator`` (HessianMatrixAggregator.scala,
HessianDiagonalAggregator.scala), and the objective-function plumbing above
them (``DistributedGLMLossFunction``, ``SingleNodeGLMLossFunction``).

Where the reference streams per-row add() calls and merges partial
accumulators via treeAggregate, every quantity here is one or two matvecs
plus an elementwise kernel, fused by XLA:

    z      = X @ ew - es + offset                    (margins)
    value  = sum(weight * l(z, y))
    grad   = f * (X^T c - shift * sum(c)),  c = weight * dl/dz
    Hv     = f * (X^T h - shift * sum(h)),  h = weight * d2l/dz2 * (X @ ev - es_v)

with (ew, es) the normalization effective-coefficient rewrite
(ValueAndGradientAggregator.scala:62-88) so the raw — possibly sparse — data
is never transformed in memory. Under jit with the batch row-sharded over a
mesh data axis and ``w`` replicated, XLA lowers the ``X^T c`` reductions to
psum over ICI: the treeAggregate of the reference with zero host round trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import GLMBatch
from photon_tpu.ops.losses import PointwiseLoss
from photon_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_tpu.optim.base import HessianVectorProduct, ValueAndGrad

Array = jax.Array


def margins(batch: GLMBatch, coef: Array, norm: NormalizationContext) -> Array:
    """z_i = x'_i . w + offset_i in transformed feature space, computed on raw
    features via the effective-coefficient rewrite."""
    ew, es = norm.effective_coefficients(coef)
    return batch.features.matvec(ew) - es + batch.offsets


def make_value_and_grad(
    batch: GLMBatch,
    loss: PointwiseLoss,
    norm: NormalizationContext | None = None,
) -> ValueAndGrad:
    """Build fun(w) -> (value, grad) over the batch in transformed space.

    Replaces ValueAndGradientAggregator.calculateValueAndGradient
    (distributed, :299-320) and its local variant (:331): sharding the batch
    rows over the mesh turns the reductions into collectives automatically.
    """
    norm = norm or no_normalization()

    def fun(w: Array):
        z = margins(batch, w, norm)
        value = jnp.sum(batch.weights * loss.loss(z, batch.labels))
        c = batch.weights * loss.dz(z, batch.labels)
        raw_grad = batch.features.rmatvec(c)
        grad = norm.effective_gradient(raw_grad, jnp.sum(c))
        return value, grad

    return fun


def make_hvp(
    batch: GLMBatch,
    loss: PointwiseLoss,
    norm: NormalizationContext | None = None,
) -> HessianVectorProduct:
    """Build hvp(w, v) -> H(w) @ v (Gauss-Newton Hessian of the GLM loss).

    Replaces HessianVectorAggregator.calcHessianVector (:235): two matvecs
    and one reduction per CG step.
    """
    norm = norm or no_normalization()

    def hvp(w: Array, v: Array):
        z = margins(batch, w, norm)
        ev, es_v = norm.effective_coefficients(v)
        zv = batch.features.matvec(ev) - es_v  # directional margins (no offset)
        h = batch.weights * loss.dzz(z, batch.labels) * zv
        raw = batch.features.rmatvec(h)
        return norm.effective_gradient(raw, jnp.sum(h))

    return hvp


def hessian_diagonal(
    batch: GLMBatch,
    loss: PointwiseLoss,
    coef: Array,
    norm: NormalizationContext | None = None,
) -> Array:
    """diag(H) in transformed space; SIMPLE variance computation.

    Replaces HessianDiagonalAggregator. With x' = (x - s) * f:
      diag_j = f_j^2 * (sum_i c_i x_ij^2 - 2 s_j sum_i c_i x_ij + s_j^2 sum_i c_i),
      c_i = weight_i * dzz_i.
    """
    norm = norm or no_normalization()
    z = margins(batch, coef, norm)
    c = batch.weights * loss.dzz(z, batch.labels)
    d_sq = batch.features.rmatvec_sq(c)
    if norm.shifts is None and norm.factors is None:
        return d_sq
    d1 = batch.features.rmatvec(c)
    total = jnp.sum(c)
    s = norm.shifts if norm.shifts is not None else jnp.zeros_like(d_sq)
    f = norm.factors if norm.factors is not None else jnp.ones_like(d_sq)
    return f * f * (d_sq - 2.0 * s * d1 + s * s * total)


def hessian_matrix(
    batch: GLMBatch,
    loss: PointwiseLoss,
    coef: Array,
    norm: NormalizationContext | None = None,
) -> Array:
    """Full [d, d] Hessian in transformed space; FULL variance computation.

    Replaces HessianMatrixAggregator (X^T diag(c) X einsum). Materializes
    d^2 — only call for small-d coordinates, exactly like the reference's
    FULL variance option. With normalization:
      H = F (H_raw - s a^T - a s^T + (sum c) s s^T) F,  a = X^T c.
    Dense path only; sparse features are densified via their matvec
    structure using an identity sweep (d matvecs) — acceptable for the small
    d this option targets.
    """
    norm = norm or no_normalization()
    z = margins(batch, coef, norm)
    c = batch.weights * loss.dzz(z, batch.labels)

    from photon_tpu.data.dataset import DenseFeatures

    if isinstance(batch.features, DenseFeatures):
        x = batch.features.x
        h_raw = x.T @ (c[:, None] * x)
    else:
        d = batch.num_features
        eye = jnp.eye(d, dtype=c.dtype)
        cols = jax.vmap(lambda e: batch.features.rmatvec(c * batch.features.matvec(e)))(eye)
        h_raw = cols.T

    if norm.shifts is None and norm.factors is None:
        return h_raw
    dtype = h_raw.dtype
    dsize = h_raw.shape[0]
    s = norm.shifts if norm.shifts is not None else jnp.zeros(dsize, dtype)
    f = norm.factors if norm.factors is not None else jnp.ones(dsize, dtype)
    a = batch.features.rmatvec(c)
    total = jnp.sum(c)
    h = h_raw - jnp.outer(s, a) - jnp.outer(a, s) + total * jnp.outer(s, s)
    return f[:, None] * h * f[None, :]
