"""Pointwise GLM loss kernels: l(z, y) with first and second derivatives in z.

TPU-native counterpart of the reference's ``PointwiseLossFunction`` hierarchy
(photon-lib function/glm/PointwiseLossFunction.scala:38-57 and the concrete
losses in photon-api function/glm/*.scala, function/svm/SmoothedHingeLossFunction.scala).

Each loss is a set of pure elementwise jnp functions over a margin array
``z = offset + X @ w`` and a label array ``y`` — they vmap/fuse trivially into
the surrounding matvec, so there is no per-sample streaming aggregator here:
the whole "aggregator" layer of the reference collapses into
``sum(weight * loss(z, y))`` under jit.

Semantics match the reference exactly:

- logistic  (LogisticLossFunction.scala:84): labels in {0,1} (or {-1,1}, where
  anything <= 0.5 is negative); l = log(1+exp(z)) - 1[y>0.5] * z.
- squared   (SquaredLossFunction.scala:43): l = (z-y)^2 / 2.
- poisson   (PoissonLossFunction.scala): l = exp(z) - y*z.
- smoothed hinge (SmoothedHingeLossFunction.scala:34, Rennie's smooth hinge):
  labels mapped to {-1,1}; piecewise quadratic; no true second derivative —
  the reference substitutes an identity-Hessian approximation (dzz = 1).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from photon_tpu.types import TaskType

Array = jax.Array

# Threshold above which a label counts as a positive response
# (reference: MathConst.POSITIVE_RESPONSE_THRESHOLD = 0.5).
POSITIVE_RESPONSE_THRESHOLD = 0.5


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss l(z, y) with derivatives in the margin z.

    Attributes:
      name: stable identifier.
      loss: elementwise l(z, y).
      dz: elementwise dl/dz.
      dzz: elementwise d2l/dz2 (Gauss-Newton weight). For the smoothed hinge
        this is the reference's identity approximation.
      mean: the inverse link function mapping margin -> E[y] for prediction.
    """

    name: str
    loss: Callable[[Array, Array], Array]
    dz: Callable[[Array, Array], Array]
    dzz: Callable[[Array, Array], Array]
    mean: Callable[[Array], Array]

    def loss_and_dz(self, z: Array, y: Array) -> tuple[Array, Array]:
        return self.loss(z, y), self.dz(z, y)


def _is_positive(y: Array) -> Array:
    return (y > POSITIVE_RESPONSE_THRESHOLD).astype(jnp.result_type(y, jnp.float32))


def _logistic_loss(z: Array, y: Array) -> Array:
    # log(1+exp(z)) - y01*z, stable for large |z| via softplus.
    return jax.nn.softplus(z) - _is_positive(y) * z


def _logistic_dz(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - _is_positive(y)


def _logistic_dzz(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


def _squared_loss(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


# exp() overflows f32 (and bf16 — same exponent range) at z ~= 88.7, and
# a single inf poisons every reduction it feeds. Mirror the
# softplus-stable logistic path: treat any margin beyond
# POISSON_MAX_MARGIN as the threshold itself. e^30 ~= 1.1e13 keeps the
# loss, gradient, and Hessian finite in f32 with ~1e25 of row-sum
# headroom, and a margin of 30 already means the fit has diverged by 13
# decades — the clamped gradient still points the solver back down.
# Clamping the margin (not just exp's argument) keeps loss/dz/dzz the
# exact derivatives of one shared 1-D function, so the autodiff-oracle
# tests hold on the whole clamped region.
POISSON_MAX_MARGIN = 30.0


def _poisson_margin(z: Array) -> Array:
    return jnp.minimum(z, POISSON_MAX_MARGIN)


def _poisson_loss(z: Array, y: Array) -> Array:
    zc = _poisson_margin(z)
    return jnp.exp(zc) - y * zc


def _poisson_dz(z: Array, y: Array) -> Array:
    return jnp.exp(_poisson_margin(z)) - y


def _poisson_dzz(z: Array, y: Array) -> Array:
    return jnp.exp(_poisson_margin(z))


def _poisson_mean(z: Array) -> Array:
    return jnp.exp(_poisson_margin(z))


def _sign_label(y: Array) -> Array:
    """Map {0,1}-style labels to {-1,+1} (reference SmoothedHingeLossFunction:46)."""
    dt = jnp.result_type(y, jnp.float32)
    return jnp.where(y < POSITIVE_RESPONSE_THRESHOLD, -1.0, 1.0).astype(dt)


def _smoothed_hinge_loss(z: Array, y: Array) -> Array:
    t = _sign_label(y) * z
    # t <= 0: 0.5 - t ; 0 < t < 1: 0.5*(1-t)^2 ; t >= 1: 0
    return jnp.where(t <= 0.0, 0.5 - t, jnp.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))


def _smoothed_hinge_dz(z: Array, y: Array) -> Array:
    s = _sign_label(y)
    t = s * z
    dt = jnp.where(t < 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return dt * s


LOGISTIC = PointwiseLoss(
    name="logistic",
    loss=_logistic_loss,
    dz=_logistic_dz,
    dzz=_logistic_dzz,
    mean=jax.nn.sigmoid,
)

SQUARED = PointwiseLoss(
    name="squared",
    loss=_squared_loss,
    dz=lambda z, y: z - y,
    dzz=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)

POISSON = PointwiseLoss(
    name="poisson",
    loss=_poisson_loss,
    dz=_poisson_dz,
    dzz=_poisson_dzz,
    mean=_poisson_mean,
)

SMOOTHED_HINGE = PointwiseLoss(
    name="smoothed_hinge",
    loss=_smoothed_hinge_loss,
    dz=_smoothed_hinge_dz,
    # Reference uses an identity Hessian approximation for the smoothed hinge
    # (no DzzLoss; SingleNode/DistributedSmoothedHingeLossFunction are
    # DiffFunction-only). dzz=1 keeps TRON usable with the same caveat.
    dzz=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)

_BY_NAME = {
    loss.name: loss for loss in (LOGISTIC, SQUARED, POISSON, SMOOTHED_HINGE)
}

_BY_TASK = {
    TaskType.LOGISTIC_REGRESSION: LOGISTIC,
    TaskType.LINEAR_REGRESSION: SQUARED,
    TaskType.POISSON_REGRESSION: POISSON,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SMOOTHED_HINGE,
}


def get_loss(name_or_task: str | TaskType) -> PointwiseLoss:
    """Look up a pointwise loss by name or by training task."""
    if isinstance(name_or_task, TaskType):
        return _BY_TASK[name_or_task]
    try:
        return _BY_NAME[name_or_task]
    except KeyError:
        raise ValueError(
            f"Unknown loss {name_or_task!r}; known: {sorted(_BY_NAME)}"
        ) from None
