"""Fused serve-score TPU kernel (Pallas): one dispatch per rung.

The AOT score ladder (serve/programs.py) lowers each coordinate's
gather -> contract -> add as its own fusion chain inside the jitted
program: per random coordinate, XLA materializes the gathered [B, S]
coefficient rows and the [B, k, S] / [S, d] one-hot operands in HBM
between chains, and the per-coordinate adds round-trip the [B] partial
scores. This kernel scores an entire padded rung in ONE pallas_call:

- the grid is ``(rung,)`` — each step owns one request row;
- the per-request entity codes ride as a SCALAR-PREFETCHED [C, rung]
  int32 array (``pltpu.PrefetchScalarGridSpec``), so each random
  coordinate's [1, S] weight row and projector row are DMA'd straight
  from the HBM-resident tables by the BlockSpec index maps
  (``codes[c, i]``, clamped at 0) before the body runs — the gather
  never materializes a [B, S] intermediate;
- inside the body every contraction is a one-hot multiply-reduce in
  VMEM with float32 accumulators; coordinate partials add in registers
  and the [1, 1] score is written once. Cold rows (code -1) multiply
  their random contribution by 0 — fixed-effect-only, the same
  semantics as ``models/game._score_raw_dense`` / ``_score_raw_sparse``.

Storage dtypes: f32 or bf16 tables (the serving precision policy);
feature payloads are cast to the table dtype at the contraction and
every reduction accumulates f32 — the ops/precision.py invariant, and
the parity contract with the jit fallback (tests/test_serve_kernel.py).

Scope and fallback mirror ops/segment_reduce.py: Mosaic lowering is
TPU-only, so ``interpret_required()`` routes forced runs on other
backends through ``interpret=True``; unforced non-TPU backends keep the
jitted per-coordinate chain, which doubles as the parity oracle. The
``PHOTON_SERVE_KERNEL`` flag (auto/force/off) picks the path ONCE at
``ScorePrograms`` construction — tables stay traced operands either
way, so values-only reloads re-enter the same executables.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

# Program contract (audited by `python -m photon_tpu.analysis
# --semantic`): one ladder rung through the fused kernel is ONE program
# — tables, features and the prefetched codes are traced operands; only
# the rung batch and the model structure (shard widths, coordinate
# count, sub_dims) are static and may mint a new executable. No host
# callbacks, no f64: this kernel IS the steady-state request loop.
PROGRAM_AUDIT = dict(
    name="serve-kernel",
    entry="ops.serve_kernel.fused_score",
    builder="build_serve_kernel",
    max_programs=1,
    recompiles_on=("rung", "model_structure"),
    hot_loop=True,
)

# Memory contract (`--memory`, ANALYSIS.md): the fused rung's live set
# is the resident tables (weights at storage width + int32 projector +
# fixed weights) plus the padded request payloads and the [rung] f32
# output — NO gathered [rung, s] coefficient intermediate and no
# [rung, k, s] one-hot operand, which is the kernel's memory story vs
# the jit chain. Scaffold constant mirrors the serving audit.
MEMORY_AUDIT = dict(
    name="serve-kernel-memory",
    entry="ops.serve_kernel.fused_score",
    covers=("serve-kernel",),
    builder="build_serve_kernel_memory",
    budgets={
        # Resident: [e, s] weights at storage width + [e, s] int32
        # projector + [d] fixed weights (+ a fixed scaffold constant);
        # per request row: the padded feature payloads (d dense + du
        # shard columns), the prefetched code, and the f32 score. NO
        # rung * s gathered-coefficient term — the kernel's gathers
        # live in VMEM blocks, which is the whole point.
        "serve_kernel_b*": (
            "e * s * (wbytes + 4) + d * wbytes + 52 * wbytes"
            " + rung * (d + du + s) * wbytes"
        ),
    },
    tolerance=1.5,
)

# Tier-5 numerics contract (`--numerics`): the kernel traced over bf16
# tables next to the jit fallback on the same fixture. One table
# storage rounding per gathered coefficient + f32 accumulation per
# reduced column; the one-hot contraction is a static single-axis VMEM
# reduce per coordinate — no scatter family, so the determinism census
# has nothing to declare.
NUMERICS_AUDIT = dict(
    name="serve-kernel-numerics",
    entry="ops.serve_kernel.fused_score",
    covers=("serve-kernel",),
    builder="build_serve_kernel_numerics",
    budgets={
        # One bf16 storage rounding on the deepest path (feature cast
        # at the contraction; the table sides are already storage
        # width) + f32 accumulator rounding over the summed one-hot
        # reduce lengths: the [s, d] random gather + the [s] row
        # contraction + the [d] fixed contraction, plus the per-rung
        # output accumulation.
        "serve_kernel_b*": (
            "u16 + u32 * (s * (d + du) + d + du + 2 * s + 4 * rung)"
        ),
    },
    tolerance=1.5,
)

# Trace-time site registry (host-side), same shape as
# ops/segment_reduce._TRACED_SITES: every kernel instantiation records
# its static shape + analytic cost so cli.profile can register a priced
# census row without the dispatch path touching the ledger. Keyed by
# (site, rung, structure digest); ``traced_sites()`` aggregates per
# site. Cleared between tests by the conftest reset.
_TRACED_SITES: dict[tuple, dict] = {}


def interpret_required() -> bool:
    """True when pallas_call must run interpreted on this backend
    (same contract as ops/segment_reduce.interpret_required)."""
    return jax.default_backend() != "tpu"


def kernel_supported(dtype) -> bool:
    """Whether the fused kernel serves score dispatches on this backend.

    ``PHOTON_SERVE_KERNEL``: ``auto`` (default — real TPU only),
    ``force``/``on``/``1`` (every backend; non-TPU runs interpreted —
    slow, for parity tests and the profile probe), ``off``/``0``
    (always the jitted per-coordinate chain).
    """
    flag = os.environ.get("PHOTON_SERVE_KERNEL", "auto").lower()
    if flag in ("0", "off", "false"):  # photon: ignore[spmd-host-divergence] -- kernel-select flag is launch config, exported fleet-uniform; divergence trips the --spmd trace proof
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    if flag in ("1", "on", "force"):  # photon: ignore[spmd-host-divergence] -- kernel-select flag is launch config, exported fleet-uniform; divergence trips the --spmd trace proof
        return True
    return jax.default_backend() == "tpu"


def _record_site(site: str, rung: int, fe_dims, re_dims, dtype) -> None:
    """Host bookkeeping at trace time (once per rung trace, never per
    dispatch): analytic cost of one fused dispatch in the costmodel's
    counter vocabulary. ``fe_dims`` is [(kind, width, k)] per fixed
    coordinate; ``re_dims`` [(kind, width, k, s)] per random one."""
    esize = jnp.dtype(dtype).itemsize
    flops = 0.0
    hbm = float(rung) * 4.0  # the [rung] f32 output
    for kind, d, k in fe_dims:
        hbm += d * esize  # the resident weight vector, read once
        if kind == "dense":
            flops += 2.0 * rung * d
            hbm += rung * d * 4.0
        else:
            flops += 2.0 * rung * k * d
            hbm += rung * k * 8.0
    for kind, d, k, s in re_dims:
        # One [1, s] weight + projector row gathered per request.
        hbm += rung * s * (esize + 4.0)
        if kind == "dense":
            flops += 2.0 * rung * (s * d + s)
            hbm += rung * d * 4.0
        else:
            flops += 2.0 * rung * (k * s + s)
            hbm += rung * k * 8.0
    _TRACED_SITES[(site, int(rung), tuple(fe_dims), tuple(re_dims),
                   str(jnp.dtype(dtype)))] = {
        "rung": int(rung),
        "dtype": str(jnp.dtype(dtype)),
        "cost": {
            "flops": flops,
            "hbm_bytes": hbm,
            "transcendentals": 0.0,
        },
    }


def traced_sites() -> dict[str, dict]:
    """Per-SITE aggregate of every fused-score instantiation traced so
    far (host bookkeeping for the cost ledger / cli.profile census): a
    site traced at several rungs prices the SUM of its instances'
    analytic costs."""
    out: dict[str, dict] = {}
    for (site, *_rest), info in _TRACED_SITES.items():
        agg = out.get(site)
        if agg is None:
            agg = out[site] = {
                "instances": 0,
                "rungs": 0,
                "cost": {"flops": 0.0, "hbm_bytes": 0.0,
                         "transcendentals": 0.0},
            }
        agg["instances"] += 1
        agg["rungs"] += info["rung"]
        for key in ("flops", "hbm_bytes", "transcendentals"):
            agg["cost"][key] += info["cost"][key]
    return out


def _make_kernel(fe_ops, re_ops):
    """Kernel body closure over the STATIC coordinate walk.

    ``fe_ops``: [(kind, shard_ref_slots, w_slot, wdtype)] per fixed
    coordinate; ``re_ops``: [(kind, shard_ref_slots, w_slot, code_row,
    wdtype)] per random one. Slot numbers index the positional operand
    refs; the scalar-prefetched codes ref comes first.
    """

    def kernel(codes_ref, *refs):
        out_ref = refs[-1]
        i = pl.program_id(0)
        acc = jnp.zeros((1, 1), jnp.float32)
        for kind, shard, w_slot, wdtype in fe_ops:
            w = refs[w_slot][...]  # [1, d]
            if kind == "dense":
                x = refs[shard[0]][...].astype(wdtype)  # [1, d]
                acc += jnp.sum(
                    (x * w).astype(jnp.float32), axis=1, keepdims=True
                )
            else:
                idx = refs[shard[0]][...]  # [1, k] int32
                val = refs[shard[1]][...]  # [1, k]
                k = idx.shape[1]
                d = w.shape[1]
                onehot = (
                    idx[0][:, None]
                    == jax.lax.broadcasted_iota(jnp.int32, (k, d), 1)
                ).astype(jnp.float32)
                # One-hot gather is exact: f32 sum of one bf16 value.
                gathered = jnp.sum(
                    onehot * w.astype(jnp.float32), axis=1
                ).astype(wdtype)
                acc += jnp.sum(
                    (val[0].astype(wdtype) * gathered).astype(
                        jnp.float32
                    ),
                )[None, None]
        for kind, shard, w_slot, code_row, wdtype in re_ops:
            w = refs[w_slot][...]       # [1, s] gathered table row
            proj = refs[w_slot + 1][...]  # [1, s] int32 projector row
            s = w.shape[1]
            # Cold / padding rows (code -1) contribute zero — the
            # fixed-effect-only fallback of the jit chain.
            known = (codes_ref[code_row, i] >= 0).astype(jnp.float32)
            if kind == "dense":
                x = refs[shard[0]][...]  # [1, d] f32 payload
                d = x.shape[1]
                # proj -1 pads match no feature id: the spill-drop of
                # _score_raw_dense's scatter.
                onehot = (
                    proj[0][:, None]
                    == jax.lax.broadcasted_iota(jnp.int32, (s, d), 1)
                ).astype(jnp.float32)
                # One-hot gather is exact (distinct projector slots:
                # one term per row), so rounding AFTER it equals the
                # jit chain's x.astype(w.dtype) — one storage rounding,
                # no f32->bf16->f32 round-trip in the cast graph.
                xg = jnp.sum(
                    onehot * x.astype(jnp.float32)[0][None, :], axis=1
                ).astype(wdtype)
                z = jnp.sum(
                    (w[0] * xg).astype(jnp.float32)
                )
            else:
                idx = refs[shard[0]][...]  # [1, k] int32
                val = refs[shard[1]][...]  # [1, k]
                k = idx.shape[1]
                onehot = (
                    idx[0][:, None] == proj[0][None, :]
                ).astype(jnp.float32)  # [k, s]; duplicates sum
                contrib = jnp.sum(
                    val[0].astype(jnp.float32)[:, None] * onehot, axis=0
                ).astype(wdtype)  # storage rounding, like_storage
                z = jnp.sum(
                    (contrib.astype(jnp.float32))
                    * w[0].astype(jnp.float32)
                )
            acc += (known * z)[None, None]
        out_ref[...] = acc

    return kernel


def fused_score(
    fe_ws,
    re_ws,
    re_projs,
    feats,
    codes,
    *,
    spec_kinds: tuple[str, ...],
    fe_feat: tuple[int, ...],
    re_feat: tuple[int, ...],
    interpret: bool | None = None,
    site: str = "serve_kernel/score",
) -> Array:
    """Score one padded rung in a single fused kernel dispatch.

    Operand layout is EXACTLY ``ScorePrograms.score_fn``'s: per-shard
    feature leaves in ``shard_order`` position (``spec_kinds``), fixed
    weight vectors + random (weights, projector) tables, and one [rung]
    int32 code vector per random coordinate. Returns [rung] float32.
    Call under an outer jit — the pallas_call is built at trace time
    from the static model structure.
    """
    if not feats:
        raise ValueError("fused_score needs at least one feature shard")
    leaf = feats[0]
    rung = int(
        (leaf if isinstance(leaf, jax.Array) or hasattr(leaf, "shape")
         else leaf[0]).shape[0]
    )
    n_codes = len(re_ws)
    codes_arr = (
        jnp.stack([c.astype(jnp.int32) for c in codes])
        if n_codes
        else jnp.zeros((1, rung), jnp.int32)
    )

    operands: list = []
    in_specs: list = []
    shard_slots: dict[int, tuple[int, ...]] = {}

    def row_spec(width: int):
        return pl.BlockSpec((1, width), lambda i, s: (i, 0))

    for si, kind in enumerate(spec_kinds):
        if kind == "dense":
            x = feats[si]
            shard_slots[si] = (len(operands),)
            operands.append(x)
            in_specs.append(row_spec(x.shape[1]))
        else:
            idx, val = feats[si]
            shard_slots[si] = (len(operands), len(operands) + 1)
            operands += [idx.astype(jnp.int32), val]
            in_specs += [row_spec(idx.shape[1]), row_spec(val.shape[1])]

    fe_ops = []
    fe_dims = []
    for w, fi in zip(fe_ws, fe_feat):
        fe_ops.append(
            (spec_kinds[fi], shard_slots[fi], len(operands),
             jnp.dtype(w.dtype))
        )
        d = int(w.shape[0])
        kk = 0 if spec_kinds[fi] == "dense" else int(
            feats[fi][0].shape[1]
        )
        fe_dims.append((spec_kinds[fi], d, kk))
        operands.append(w.reshape(1, d))
        in_specs.append(pl.BlockSpec((1, d), lambda i, s: (0, 0)))

    re_ops = []
    re_dims = []
    wdtype = jnp.dtype(fe_ws[0].dtype) if fe_ws else None
    for ci, (w, proj, fi) in enumerate(zip(re_ws, re_projs, re_feat)):
        sdim = int(w.shape[1])
        re_ops.append(
            (spec_kinds[fi], shard_slots[fi], len(operands), ci,
             jnp.dtype(w.dtype))
        )
        wdtype = jnp.dtype(w.dtype)
        if spec_kinds[fi] == "dense":
            re_dims.append(("dense", int(feats[fi].shape[1]), 0, sdim))
        else:
            re_dims.append(
                ("sparse", 0, int(feats[fi][0].shape[1]), sdim)
            )

        def table_row(i, s, c=ci):
            # Codes are scalar-prefetched: the DMA for this request's
            # table row is issued from the index map, before the body.
            return (jnp.maximum(s[c, i], 0), 0)

        operands.append(w)
        in_specs.append(pl.BlockSpec((1, sdim), table_row))
        operands.append(proj.astype(jnp.int32))
        in_specs.append(pl.BlockSpec((1, sdim), table_row))

    _record_site(site, rung, fe_dims, re_dims, wdtype or jnp.float32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rung,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1), lambda i, s: (i, 0)),
    )
    out = pl.pallas_call(
        _make_kernel(tuple(fe_ops), tuple(re_ops)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rung, 1), jnp.float32),
        interpret=(
            interpret_required() if interpret is None else interpret
        ),
    )(codes_arr, *operands)
    return out[:, 0]
