"""Mixed-precision policy for the fused GLMix hot path.

The fused fit is dispatch/layout-bound at f32 (BENCH_r05: ~0.03% of
bf16 peak, ~4.6% of HBM peak); the biggest per-sweep HBM reads are the
materialized bucket slabs and the per-coordinate score/residual
vectors. ``precision="bfloat16"`` stores those in bf16 — halving the
slab and score traffic — while every sum that crosses a row axis
(losses, gradients, Hessians, margins, score reductions, convergence
diagnostics) accumulates in float32.

The policy is a STRING plumbed explicitly (GameEstimator(precision=)
-> FusedFit -> _solve_block statics), never ambient state: the traced
program depends only on operand dtypes and the static precision key,
so the tier-2 contracts can pin that "float32" (the default) traces
byte-identical programs to the pre-policy code and that "bfloat16" is
a DECLARED recompile key (``recompiles_on=("precision",)``).

The accumulate helpers below are dtype-driven: on f32 operands they
are literally the plain ``jnp`` call (identical jaxpr — the default
path cannot drift), on bf16 operands they force an f32 accumulator via
``preferred_element_type`` / ``dtype=``. The tier-1
``bf16-accumulation`` rule (analysis/rules.py) flags raw
``jnp.sum``/``einsum``/segment-reduce calls on bf16-marked operands
across every audited module — the fused-fit path, ``serve/`` (bf16
coefficient tables), and the segment-reduce fallback alike — and these
helpers are the sanctioned spelling. The tier-5 numerics auditor
(``--numerics``, ``NUMERICS_AUDIT`` below) is the semantic check
behind it: the dtype-provenance walk over the traced jaxprs proves the
accumulators really are f32, so a tier-1 suppression can cite it.

Precision policy table, per-family tolerances, and the donation map
live in PERFORMANCE.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

FLOAT32 = "float32"
BFLOAT16 = "bfloat16"

_ALIASES = {
    "float32": FLOAT32,
    "f32": FLOAT32,
    "fp32": FLOAT32,
    "bfloat16": BFLOAT16,
    "bf16": BFLOAT16,
    "mixed_bf16": BFLOAT16,
}

# Tier-5 numerics contract (verified by `python -m photon_tpu.analysis
# --numerics`, see ANALYSIS.md): the policy helpers and all four GLM
# loss families are traced over bf16-STORED margins and dtype-flow
# checked — acc_sum/acc_einsum must accumulate f32, no family's exp()
# may reach a reduction without a dominating clamp (the ops/losses.py
# POISSON_MAX_MARGIN fix), and each probe's worst-case relative error
# must price inside its declared budget: one storage rounding (u16)
# plus one f32 accumulation step (u32) per reduced row.
NUMERICS_AUDIT = dict(
    name="precision-policy-numerics",
    entry="ops.precision.acc_sum/acc_einsum + ops.losses families",
    builder="build_precision_numerics",
    budgets={
        "acc_sum": "u16 + u32 * m",
        "acc_einsum": "u16 + u32 * k",
        # three acc_sum reductions per family probe (loss + curvature
        # + link); families whose dzz is constant price below this
        "loss_*": "u16 + 3 * u32 * m",
    },
    tolerance=1.5,
)


def resolve(name: str | None) -> str:
    """Normalize a precision name; the default is the f32 path."""
    if name is None:
        return FLOAT32
    key = str(name).lower()
    if key not in _ALIASES:
        raise ValueError(
            f"unknown precision {name!r}: expected one of "
            f"{sorted(set(_ALIASES))}")
    return _ALIASES[key]


def is_mixed(name: str | None) -> bool:
    return resolve(name) == BFLOAT16


def storage_dtype(name: str | None):
    """The dtype large reused operands (slabs, score vectors, serving
    coefficient tables) are STORED in under this policy."""
    return jnp.bfloat16 if is_mixed(name) else jnp.float32


def in_storage(x: Array, name: str | None) -> Array:
    """Cast a float operand to the policy's storage dtype (identity on
    the default path and for non-float operands)."""
    if is_mixed(name) and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(jnp.bfloat16)
    return x


def _any_bf16(ops) -> bool:
    return any(
        getattr(o, "dtype", None) == jnp.bfloat16 for o in ops
    )


def acc_einsum(spec: str, *ops: Array) -> Array:
    """einsum whose accumulator is f32 whenever any operand is bf16.

    On all-f32 operands this is EXACTLY ``jnp.einsum(spec, *ops)`` —
    same jaxpr, so the default path is untouched by construction. On
    bf16 operands the contraction reads bf16 (the bandwidth win) and
    accumulates f32 (the correctness invariant); the result is f32.
    """
    if _any_bf16(ops):
        return jnp.einsum(
            spec, *ops, preferred_element_type=jnp.float32
        )
    return jnp.einsum(spec, *ops)


def acc_sum(x: Array, axis=None, keepdims: bool = False) -> Array:
    """sum with an f32 accumulator whenever the operand is bf16."""
    if getattr(x, "dtype", None) == jnp.bfloat16:
        return jnp.sum(  # photon: ignore[bf16-accumulation] -- this IS the sanctioned f32-accumulator spelling (dtype=float32)
            x, axis=axis, keepdims=keepdims, dtype=jnp.float32
        )
    return jnp.sum(x, axis=axis, keepdims=keepdims)


def like_storage(x: Array, ref: Array) -> Array:
    """Cast ``x`` to ``ref``'s dtype when ``ref`` is a bf16-stored
    operand (the contraction-partner cast: einsum on mixed dtypes would
    otherwise PROMOTE the stored operand back to f32 and re-read the
    full-width slab)."""
    if getattr(ref, "dtype", None) == jnp.bfloat16:
        return x.astype(jnp.bfloat16)
    return x
