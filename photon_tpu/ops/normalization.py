"""Feature normalization as pure affine algebra on coefficient vectors.

TPU-native counterpart of the reference's ``NormalizationContext``
(photon-lib normalization/NormalizationContext.scala:37-176) and
``NormalizationType`` (normalization/NormalizationType.scala:42).

The transform is x' = (x - shift) * factor elementwise, with the intercept
column never shifted (shift[intercept] == 0) nor scaled (factor[intercept] == 1).
Optimization runs in the transformed space; coefficients round-trip to the
original space keeping margins identical:

    w  = w' * factor;          b  = b' - (w . shift)   (all shift mass -> intercept)
    w' = w / factor;           b' = b + (w . shift)

Rather than materializing transformed copies of the data, the GLM objective
uses the *effective coefficients* rewrite from the reference's aggregators
(ValueAndGradientAggregator.scala:62-88): for margins over transformed
features,

    x' . w' = (x - shift) * factor . w' = x . (factor * w') - shift . (factor * w')
            = x . ew - es,   ew = factor * w',   es = shift . ew

so the hot matvec always runs on the raw (sparse) data with rewritten
coefficients — one extra scalar per batch, zero data movement.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

Array = jax.Array


class NormalizationType(enum.Enum):
    """Reference: NormalizationType.scala:42."""

    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """Affine feature transform x' = (x - shift) * factor.

    ``factors is None`` means all-ones; ``shifts is None`` means all-zeros
    (and then no intercept is required). A default-constructed instance is
    no-normalization. This is a pytree so it can ride through jit boundaries.
    """

    factors: Array | None = None
    shifts: Array | None = None
    intercept_index: int | None = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    def __post_init__(self):
        if self.shifts is not None and self.intercept_index is None:
            raise ValueError(
                "Normalization with shifts requires an intercept "
                "(reference NormalizationContext.scala:49)"
            )

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    # --- effective-coefficient rewrite (the hot path) -----------------------

    def effective_coefficients(self, coef: Array) -> tuple[Array, Array]:
        """Return (ew, es) such that margin = x . ew - es for raw features x.

        Reference: ValueAndGradientAggregator.scala:62-88 (effectiveCoefficients
        and totalShift).
        """
        ew = coef if self.factors is None else coef * self.factors
        if self.shifts is None:
            es = jnp.zeros((), dtype=coef.dtype)
        else:
            es = jnp.dot(self.shifts.astype(coef.dtype), ew)
        return ew, es

    def effective_gradient(self, raw_grad: Array, grad_dot_total: Array) -> Array:
        """Map a gradient aggregated against *raw* features into transformed space.

        d margin / d w'_j = factor_j * (x_j - shift_j), so
        grad'_j = factor_j * (raw_grad_j - shift_j * sum_i g_i)
        where ``raw_grad = X^T g`` and ``grad_dot_total = sum_i g_i``.
        Reference folds this into the aggregator's vectorShiftPrefactorSum.
        """
        g = raw_grad
        if self.shifts is not None:
            g = g - self.shifts.astype(g.dtype) * grad_dot_total
        if self.factors is not None:
            g = g * self.factors.astype(g.dtype)
        return g

    # --- coefficient space round-trips --------------------------------------

    def coef_to_original_space(self, coef: Array) -> Array:
        """Transformed-space coefficients -> original space, margin-preserving.

        Reference: NormalizationContext.coefToOriginalSpace (scala:77-95):
        w = w' * factor, then intercept -= w . shift.
        """
        out = coef if self.factors is None else coef * self.factors
        if self.shifts is not None:
            adj = jnp.dot(out, self.shifts.astype(out.dtype))
            out = out.at[self.intercept_index].add(-adj)
        return out

    def coef_to_transformed_space(self, coef: Array) -> Array:
        """Original-space coefficients -> transformed space (scala:111-129):
        intercept += w . shift, then w' = w / factor.
        """
        out = coef
        if self.shifts is not None:
            adj = jnp.dot(out, self.shifts.astype(out.dtype))
            out = out.at[self.intercept_index].add(adj)
        if self.factors is not None:
            out = out / self.factors
        return out

    def var_to_transformed_space(self, variances: Array) -> Array:
        """Coefficient variances original -> transformed: Var(w') = Var(w)/factor^2.

        Reference: NormalizationContext.varToTransformedSpace (scala:145-160).
        Used when converting a prior model for incremental training.
        """
        if self.factors is None:
            return variances
        return variances / (self.factors * self.factors)


def no_normalization() -> NormalizationContext:
    """Reference: NoNormalization()."""
    return NormalizationContext()


def build_normalization_context(
    normalization_type: NormalizationType,
    *,
    mean: Array | None = None,
    variance: Array | None = None,
    min_: Array | None = None,
    max_: Array | None = None,
    intercept_index: int | None = None,
) -> NormalizationContext:
    """Build a NormalizationContext from per-feature statistics.

    Mirrors NormalizationContext.apply(normalizationType, summary)
    (scala:162-220): zero std / zero magnitude features get factor 1 so that
    constant columns pass through untouched.
    """
    if normalization_type == NormalizationType.NONE:
        return no_normalization()

    if normalization_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        if min_ is None or max_ is None:
            raise ValueError("max-magnitude scaling needs min/max statistics")
        magnitude = jnp.maximum(jnp.abs(max_), jnp.abs(min_))
        factors = jnp.where(magnitude == 0.0, 1.0, 1.0 / jnp.where(magnitude == 0, 1.0, magnitude))
        if intercept_index is not None:
            factors = factors.at[intercept_index].set(1.0)
        return NormalizationContext(factors=factors)

    if normalization_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        if variance is None:
            raise ValueError("std scaling needs variance statistics")
        std = jnp.sqrt(variance)
        factors = jnp.where(std == 0.0, 1.0, 1.0 / jnp.where(std == 0, 1.0, std))
        if intercept_index is not None:
            factors = factors.at[intercept_index].set(1.0)
        return NormalizationContext(factors=factors)

    if normalization_type == NormalizationType.STANDARDIZATION:
        if variance is None or mean is None:
            raise ValueError("standardization needs mean/variance statistics")
        if intercept_index is None:
            raise ValueError(
                "standardization (shifting) requires an intercept column "
                "(reference GameTrainingDriver normalization validation)"
            )
        std = jnp.sqrt(variance)
        factors = jnp.where(std == 0.0, 1.0, 1.0 / jnp.where(std == 0, 1.0, std))
        factors = factors.at[intercept_index].set(1.0)
        shifts = mean.at[intercept_index].set(0.0)
        return NormalizationContext(
            factors=factors, shifts=shifts, intercept_index=intercept_index
        )

    raise ValueError(f"Unknown normalization type: {normalization_type}")
