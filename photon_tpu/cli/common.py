"""Shared CLI plumbing: logging setup with an optional persistent sink."""

from __future__ import annotations

import contextlib
import logging


@contextlib.contextmanager
def cli_logging(verbose: bool, log_file: str | None,
                fmt: str = "%(asctime)s %(name)s %(levelname)s %(message)s"):
    """Console logging at WARNING (INFO with ``verbose``) plus an optional
    INFO-level file sink (the PhotonLogger equivalent,
    util/PhotonLogger.scala:34). Gating happens at the HANDLER level so the
    file sink can capture INFO without flooding the console, and the file
    handler is detached and closed on exit — repeated ``main()`` calls in
    one process (tests, notebooks) don't leak handlers or level state.
    """
    root = logging.getLogger()
    console = logging.StreamHandler()
    console.setLevel(logging.INFO if verbose else logging.WARNING)
    console.setFormatter(logging.Formatter(fmt))
    handlers = [console]
    if log_file:
        sink = logging.FileHandler(log_file)
        sink.setLevel(logging.INFO)
        sink.setFormatter(logging.Formatter(fmt))
        handlers.append(sink)
    prev_level = root.level
    # INFO records are only materialized when something consumes them.
    root.setLevel(
        logging.INFO if (verbose or log_file) else logging.WARNING)
    for h in handlers:
        root.addHandler(h)
    try:
        yield
    finally:
        for h in handlers:
            root.removeHandler(h)
            h.close()
        root.setLevel(prev_level)
