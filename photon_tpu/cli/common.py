"""Shared CLI plumbing: logging setup, multi-host runtime initialization."""

from __future__ import annotations

import contextlib
import logging


def maybe_init_distributed() -> bool:
    """Initialize the JAX multi-host runtime when launched under a
    coordinator (the cluster-session bring-up the reference does in
    SparkSessionConfiguration.scala:109; here controller-less multi-host:
    every process calls jax.distributed.initialize and jax.devices() then
    spans all hosts, so the estimator's auto mesh rides ICI/DCN).

    Uses JAX's own cluster auto-detection (GCE/GKE TPU pods, SLURM, K8s,
    or the JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID env
    vars); a plain single-host launch is a no-op. Returns True when
    initialization ran.
    """
    import jax

    # jax.distributed.is_initialized only exists from jax 0.5; on older
    # versions read the global client state the accessor wraps.
    initialized = getattr(jax.distributed, "is_initialized", None)
    if initialized is None:
        def initialized() -> bool:
            try:
                from jax._src.distributed import global_state
            except ImportError:  # pragma: no cover — layout moved again
                return False
            return getattr(global_state, "client", None) is not None
    def _mark_fleet_clock() -> None:
        # The init half of the fleet clock-alignment handshake
        # (obs/fleet.py): sample the monotonic↔epoch offset and probe
        # the post-init rank identity, so a later bundle commit can
        # bound how far this host's clock mapping drifted over the run.
        # Sampled on EVERY path out of here — single-host runs ship
        # 1-rank bundles too.
        from photon_tpu.obs import fleet

        fleet.mark_init()

    if initialized():
        _mark_fleet_clock()
        return False  # idempotent CLI re-entry in one process
    try:
        jax.distributed.initialize()
    except ValueError as e:
        # Auto-detection found no cluster environment — the normal
        # single-host case. Any OTHER ValueError (e.g. coordinator set but
        # num_processes missing) is real misconfiguration: half-configured
        # pods silently training independent models would be far worse
        # than failing fast.
        if "coordinator_address" in str(e):
            _mark_fleet_clock()
            return False
        raise
    except RuntimeError as e:
        # Programmatic re-entry after the XLA backend is already up (tests,
        # notebooks calling main() mid-session): multi-host init is a
        # process-start decision, so treat as single-host. The wording has
        # moved across jax versions ("before any JAX calls" / "before any
        # JAX computations"); match both. Anything else (real cluster
        # misconfiguration) propagates.
        msg = str(e)
        if ("before any JAX" in msg or "called once" in msg):
            _mark_fleet_clock()
            return False
        raise
    _mark_fleet_clock()
    logging.getLogger("photon.cli").info(
        "multi-host runtime up: process %d/%d, %d global device(s)",
        jax.process_index(), jax.process_count(), len(jax.devices()),
    )
    return True


def fetch_global(x):
    """Materialize a (possibly host-spanning) device array on this host.

    Multi-host meshes shard rows across processes; fetching such an array
    with ``np.asarray`` raises (non-addressable shards). Single-host is a
    plain fetch.
    """
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return np.asarray(x)
    # Process-local (fully addressable) or replicated arrays already carry
    # the complete value on this host: allgathering them would concatenate
    # one full copy per process (duplicated rows in the written output).
    # Only arrays genuinely sharded across hosts need the gather.
    if getattr(x, "is_fully_addressable", True) or getattr(
        x, "is_fully_replicated", False
    ):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def is_coordinator() -> bool:
    """True on the process that owns artifact writes (process 0).

    Multi-host SPMD runs execute the same driver on every process; model /
    score / summary files must be written once (the reference writes from
    the Spark driver only). Single-host is trivially the coordinator.
    """
    import jax

    return jax.process_index() == 0


@contextlib.contextmanager
def cli_logging(verbose: bool, log_file: str | None,
                fmt: str = "%(asctime)s %(name)s %(levelname)s %(message)s"):
    """Console logging at WARNING (INFO with ``verbose``) plus an optional
    INFO-level file sink (the PhotonLogger equivalent,
    util/PhotonLogger.scala:34). Gating happens at the HANDLER level so the
    file sink can capture INFO without flooding the console, and the file
    handler is detached and closed on exit — repeated ``main()`` calls in
    one process (tests, notebooks) don't leak handlers or level state.
    """
    root = logging.getLogger()
    console = logging.StreamHandler()
    console.setLevel(logging.INFO if verbose else logging.WARNING)
    console.setFormatter(logging.Formatter(fmt))
    handlers = [console]
    if log_file:
        sink = logging.FileHandler(log_file)
        sink.setLevel(logging.INFO)
        sink.setFormatter(logging.Formatter(fmt))
        handlers.append(sink)
    prev_level = root.level
    # INFO records are only materialized when something consumes them.
    root.setLevel(
        logging.INFO if (verbose or log_file) else logging.WARNING)
    for h in handlers:
        root.addHandler(h)
    try:
        yield
    finally:
        for h in handlers:
            root.removeHandler(h)
            h.close()
        root.setLevel(prev_level)
