"""``photon pilot``: the always-on train→validate→promote→rollback daemon.

One process supervises the whole production loop (PILOT.md): watch a
shard directory, stream-ingest new data, warm-start retrain, gate
promotion against the serving model, hot-reload the live scorer with
zero recompiles, observe post-promotion SLO burn, auto-roll back from
the bounded generation ring — committing every state-machine transition
atomically so a killed pilot resumes exactly where it died
(``--work-dir`` is the only memory it needs).

Usage:
    python -m photon_tpu.cli.pilot --config pilot.yaml \
        [--poll-interval 5] [--max-cycles N] [--idle-timeout S] \
        [--traffic-qps R] [--monitor-port P] [--json PATH]

The config file carries the training surface (task / coordinates /
num_iterations / evaluators — the ``photon train`` vocabulary) plus the
pilot blocks::

    stream_dir: out/shards          # watched directory
    work_dir: out/pilot             # durable state + ring + cycles
    keep_generations: 3             # rollback ring bound
    promotion: {min_delta: {AUC: -0.005}}
    observe: {window_s: 2.0, max_dispatch_errors: 0}
    serve: {rungs: [1, 8, 64], max_linger_ms: 2.0}
    health:                         # model/data health gates (PILOT.md)
      max_drift_psi: 0.25           # this cycle vs last promoted cycle
      max_skew_psi: 0.5             # training data vs sampled traffic
      max_ece: 0.1                  # candidate calibration (binary)
      max_coefficient_rel_l2: 5.0   # warm-start lurch ceiling
      forbid_nonfinite: true        # numerics sentinels refuse
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The optional synthetic-traffic generator is the one
# extra thread: it only calls ``queue.submit`` (internally locked) and
# appends to ITS OWN counters dict, which the main thread reads only
# after the join — no shared-state locking needed. No JAX runs on it
# (request assembly is pure numpy; dispatch stays on the queue worker).
CONCURRENCY_AUDIT = dict(
    name="cli-pilot",
    locks={},
    thread_entries=("_traffic_loop",),
    jax_dispatch_ok={},
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon pilot", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--config", required=True,
                        help="pilot configuration (YAML/JSON; see "
                             "PILOT.md)")
    parser.add_argument("--stream-dir", default=None,
                        help="override the config's stream_dir")
    parser.add_argument("--work-dir", default=None,
                        help="override the config's work_dir")
    parser.add_argument("--poll-interval", type=float, default=5.0,
                        metavar="S",
                        help="seconds between shard-directory polls")
    parser.add_argument("--max-cycles", type=int, default=None,
                        help="stop after N completed cycles "
                             "(promotions + refusals) — the CI mode")
    parser.add_argument("--idle-timeout", type=float, default=None,
                        metavar="S",
                        help="stop after S seconds with no new shards")
    parser.add_argument("--traffic-qps", type=float, default=None,
                        metavar="R",
                        help="drive R synthetic requests/s against the "
                             "live scorer for the whole run (served/"
                             "error counts ride the exit JSON — the "
                             "zero-dropped-requests evidence)")
    parser.add_argument("--monitor-port", type=int, default=None,
                        metavar="PORT",
                        help="serve /metrics + /healthz + /readyz "
                             "(pilot_* gauges + the queue collector; "
                             "0 = ephemeral)")
    parser.add_argument("--reset-serve-only", action="store_true",
                        help="re-arm a pilot that degraded to "
                             "serve-only mode, then continue")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write the exit summary JSON to PATH")
    parser.add_argument("--flight-dir", default=".", metavar="DIR",
                        help="crash flight recorder destination "
                             "(refusals and rollbacks dump here too)")
    parser.add_argument("--no-flight", action="store_true")
    parser.add_argument("--backend", default=None)
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--log-file", default=None)
    args = parser.parse_args(argv)

    if args.backend:
        os.environ["JAX_PLATFORMS"] = args.backend
    from photon_tpu.cli.common import cli_logging

    with cli_logging(args.verbose, args.log_file):
        from photon_tpu.resilience import faults
        from photon_tpu.utils import enable_compilation_cache

        faults.arm_from_env()
        enable_compilation_cache()
        return _run(args)


def _load_config(args) -> dict:
    from photon_tpu.cli.config import _read_config_file

    raw = _read_config_file(args.config)
    if args.stream_dir:
        raw["stream_dir"] = args.stream_dir
    if args.work_dir:
        raw["work_dir"] = args.work_dir
    for key in ("stream_dir", "work_dir", "task", "coordinates"):
        if not raw.get(key):
            raise SystemExit(
                f"pilot config {args.config}: missing {key!r}")
    return raw


def _build_pilot_config(raw: dict):
    from photon_tpu.cli.config import parse_coordinate
    from photon_tpu.estimators.game_estimator import GameEstimator
    from photon_tpu.pilot import ObservePolicy, PilotConfig, PromotionGate
    from photon_tpu.types import TaskType

    task = TaskType(raw["task"].upper())
    coords = {
        cid: parse_coordinate(cid, c)
        for cid, c in raw["coordinates"].items()
    }
    update_sequence = list(raw.get("update_sequence", list(coords)))
    num_iterations = int(raw.get("num_iterations", 1))
    evaluators = list(raw.get("evaluators", []))
    mesh = raw.get("mesh", "off")

    def estimator_factory():
        return GameEstimator(
            task,
            {cid: spec.config for cid, spec in coords.items()},
            update_sequence=update_sequence,
            num_iterations=num_iterations,
            evaluators=evaluators or None,
            mesh=mesh,
        )

    promo = raw.get("promotion", {})
    observe = raw.get("observe", {})
    health_cfg = raw.get("health")
    health_gate = None
    if health_cfg is not None:
        import dataclasses as _dc

        from photon_tpu.obs.health import HealthGatePolicy

        _defaults = {
            f.name: f.default for f in _dc.fields(HealthGatePolicy)
        }

        def _opt(key):
            # An ABSENT key keeps the policy's documented default
            # (max_drift_psi=0.25); only an explicit `null` disables
            # the individual gate — `health: {forbid_nonfinite: true}`
            # must not silently drop the drift gate.
            if key not in health_cfg:
                return _defaults[key]
            v = health_cfg[key]
            return None if v is None else float(v)

        health_gate = HealthGatePolicy(
            max_drift_psi=_opt("max_drift_psi"),
            max_skew_psi=_opt("max_skew_psi"),
            max_ece=_opt("max_ece"),
            max_coefficient_rel_l2=_opt("max_coefficient_rel_l2"),
            forbid_nonfinite=bool(
                health_cfg.get("forbid_nonfinite", True)),
            min_skew_requests=int(
                health_cfg.get("min_skew_requests", 64)),
        )
    ingest = dict(raw.get("ingest", {}))
    if "feature_shards" in ingest:
        ingest["feature_shards"] = {
            s: list(b) for s, b in ingest["feature_shards"].items()
        }
    return PilotConfig(
        stream_dir=raw["stream_dir"],
        work_dir=raw["work_dir"],
        estimator_factory=estimator_factory,
        validation_dir=raw.get("validation_dir"),
        window_shards=int(raw.get("window_shards", 1)),
        keep_generations=int(raw.get("keep_generations", 3)),
        keep_cycle_dirs=int(raw.get("keep_cycle_dirs", 2)),
        gate=PromotionGate(
            min_delta={
                k: float(v)
                for k, v in (promo.get("min_delta") or {}).items()
            },
            require_primary=bool(promo.get("require_primary", True)),
        ),
        observe=ObservePolicy(
            window_s=float(observe.get("window_s", 2.0)),
            poll_s=float(observe.get("poll_s", 0.25)),
            max_dispatch_errors=int(
                observe.get("max_dispatch_errors", 0)),
            max_error_burn=float(observe.get("max_error_burn", 0.0)),
            rollback_on_breaker=bool(
                observe.get("rollback_on_breaker", True)),
        ),
        stage_deadline_s={
            str(k).lower(): float(v)
            for k, v in (raw.get("stage_deadline_s") or {}).items()
        },
        max_consecutive_failures=int(
            raw.get("max_consecutive_failures", 3)),
        pin_vocabulary=bool(raw.get("pin_vocabulary", True)),
        ingest_kwargs=ingest,
        health=health_gate,
    )


def _make_server_factory(raw: dict):
    from photon_tpu.obs.monitor import SloPolicy
    from photon_tpu.pilot import PilotServer

    serve = raw.get("serve", {})
    slo_cfg = serve.get("slo", {})

    def make_server(model):
        return PilotServer(
            model,
            rungs=tuple(serve.get("rungs", (1, 8, 64))),
            max_linger_s=float(serve.get("max_linger_ms", 2.0)) / 1e3,
            breaker_threshold=serve.get("breaker_threshold", 8) or None,
            slo=SloPolicy(
                p99_ms=float(slo_cfg.get("p99_ms", 250.0)),
                error_rate=float(slo_cfg.get("error_rate", 0.001)),
                cold_entity_rate=float(
                    slo_cfg.get("cold_entity_rate", 0.2)),
                short_window_s=float(slo_cfg.get("window_s", 5.0)),
                long_window_s=12 * float(slo_cfg.get("window_s", 5.0)),
            ),
        )

    return make_server


def _traffic_loop(pilot, rate: float, stop, counts: dict) -> None:
    """Synthetic load against whatever generation is live — runs on its
    own thread for the daemon's whole life so every promotion happens
    UNDER traffic. The loop itself is the shared
    ``serve.driver.traffic_loop`` (the bench's pilot replay drives the
    same one); counters are this thread's, read after the join."""
    from photon_tpu.serve.driver import traffic_loop

    traffic_loop(
        lambda: pilot.server, rate, stop, counts,
        batch=max(int(rate / 4), 8),
    )


def _run(args) -> int:
    from photon_tpu import obs
    from photon_tpu.obs import flight, monitor
    from photon_tpu.pilot import MODE_SERVE_ONLY, Pilot

    raw = _load_config(args)
    cfg = _build_pilot_config(raw)
    make_server = _make_server_factory(raw)

    was_enabled = obs.enabled()
    obs.reset()
    obs.enable()
    rec = None
    prior_rec = flight.installed()
    if not args.no_flight:
        rec = flight.install(args.flight_dir, signals=True)

    pilot = Pilot(cfg, server_factory=make_server)
    if args.reset_serve_only:
        pilot.reset_serve_only()
    # A restarted pilot serves the ring's LIVE generation from the
    # first second — a staged-but-never-committed candidate stays
    # un-served until PROMOTE resumes and commits it.
    if pilot.server is None and pilot.ring.live is not None:
        pilot.server = make_server(pilot.ring.load(pilot.ring.live))

    mon = None
    if args.monitor_port is not None:
        def _readiness():
            server_up = pilot.server is not None
            breaker = bool(
                server_up and pilot.server.health()["breaker_open"]
            )
            return (server_up and not breaker), {
                "server_up": server_up,
                "breaker_open": breaker,
                "mode": pilot.state.mode,
                "stage": pilot.state.stage,
            }

        mon = monitor.MonitorServer(
            args.monitor_port, readiness=_readiness
        ).start()
        mon.add_collector(pilot.metrics_families)
        mon.add_collector(
            lambda: pilot.server.queue.metrics_families()
            if pilot.server is not None else []
        )

    stop = threading.Event()
    counts = {
        "served": 0, "errors": 0, "submit_errors": 0, "stranded": 0,
        "last_error": None,
    }
    traffic = None
    if args.traffic_qps:
        traffic = threading.Thread(
            target=_traffic_loop,
            args=(pilot, args.traffic_qps, stop, counts),
            name="pilot-traffic", daemon=True,
        )
        traffic.start()

    try:
        summary = pilot.run_forever(
            poll_interval_s=args.poll_interval,
            max_cycles=args.max_cycles,
            idle_timeout_s=args.idle_timeout,
        )
    finally:
        stop.set()
        if traffic is not None:
            traffic.join(timeout=60.0)
        server_health = (
            pilot.server.health() if pilot.server is not None else None
        )
        if pilot.server is not None:
            pilot.server.close(timeout=30.0)
        if rec is not None:
            flight.uninstall()
            if prior_rec is not None:
                flight.reinstall(prior_rec)
        obs.TRACER.enabled = was_enabled

    state = pilot.state
    out = {
        "metric": "pilot",
        "stopped": summary.get("stopped"),
        "cycles": summary.get("cycles"),
        "mode": state.mode,
        "stage": state.stage,
        "promotions": state.promotions,
        "rollbacks": state.rollbacks,
        "refusals": state.refusals,
        "failures": state.failures,
        "deadline_overruns": state.deadline_overruns,
        "staleness_seconds": state.staleness_seconds,
        "last_promotion": state.last_promotion,
        "last_refusal": state.last_refusal,
        "last_rollback": state.last_rollback,
        "last_health": state.last_health,
        "generation_live": pilot.ring.live,
        "generations": [
            {k: e[k] for k in ("gen", "cycle", "created_at")}
            | {"rolled_back": bool(e.get("rolled_back"))}
            for e in pilot.ring.entries()
        ],
        "serving_reload_compile_events": (
            pilot.server.reload_compile_events
            if pilot.server is not None else None
        ),
        "health": server_health,
    }
    if args.traffic_qps:
        out["traffic"] = {"offered_qps": args.traffic_qps, **counts}
    if mon is not None:
        out["monitor"] = {"port": mon.port, **mon.scrape_stats()}
        mon.stop()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))
    # Exit-code contract for supervisors: serve-only degradation or
    # errored traffic must be visible to exit-code-only consumers.
    degraded = state.mode == MODE_SERVE_ONLY
    traffic_bad = counts["errors"] or counts["submit_errors"] \
        or counts["stranded"]
    return 1 if (degraded or traffic_bad) else 0


if __name__ == "__main__":
    sys.exit(main())
