"""``photon health``: compare model/data-health sketches, render drift.

The offline half of ``photon_tpu.obs.health``: take two persisted
:class:`DataSketch` artifacts — a streaming-ingest run's
``ingest-sketch.json`` (written beside the cursor when the health layer
is armed), a pilot work dir's ``pilot-health-sketch.json`` (the last
promoted cycle's reference), or a serve run's ``--health-sketch``
artifact (the sampled-traffic sketch) — and render the PSI/KS/mean-shift
comparison per column, per feature shard, and per top-moved feature.
With ``--max-psi`` the comparison GATES: exit 1 when any compared
distribution's PSI crosses the ceiling — the same number the pilot's
``health:drift`` promotion gate thresholds.

Usage:
    python -m photon_tpu.cli.health --a DAY1_WORK_DIR --b DAY2_WORK_DIR
    python -m photon_tpu.cli.health --a ingest-sketch.json \
        --b serve-sketch.json --max-psi 0.25 [--json PATH]
    python -m photon_tpu.cli.health --url http://127.0.0.1:9100

``--a``/``--b`` accept a sketch FILE or a DIRECTORY (a training work
dir / manifest dir: ``ingest-sketch.json`` is resolved inside, falling
back to ``pilot-health-sketch.json``). ``--url`` scrapes a live
monitor's ``/metrics`` and prints the ``health_*`` families — the
live-server view next to (or instead of) the offline comparison.

No jax import, no device: this is host JSON + numpy arithmetic.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_SKETCH_BASENAMES = ("ingest-sketch.json", "pilot-health-sketch.json")


def resolve_sketch_path(path: str) -> str:
    """A sketch file, or a directory holding one of the well-known
    sketch artifacts (training work dir / pilot work dir)."""
    if os.path.isdir(path):
        for base in _SKETCH_BASENAMES:
            cand = os.path.join(path, base)
            if os.path.exists(cand):
                return cand
        raise SystemExit(
            f"photon health: no sketch artifact under {path} "
            f"(looked for {', '.join(_SKETCH_BASENAMES)}); was the "
            "ingest run health-armed (obs.health.enable / a pilot "
            "`health:` config block)?")
    if not os.path.exists(path):
        raise SystemExit(f"photon health: no such sketch {path}")
    return path


def scrape_health_families(url: str, timeout_s: float = 5.0) -> list[str]:
    """The ``health_*`` exposition lines of a live monitor."""
    from urllib.request import urlopen

    target = url.rstrip("/") + "/metrics"
    with urlopen(target, timeout=timeout_s) as resp:
        text = resp.read().decode("utf-8")
    return [
        line for line in text.splitlines()
        if "health_" in line.split(" ")[0].lstrip("#")
        or (line.startswith("# ") and " health_" in line)
    ]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon health", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--a", dest="a", default=None, metavar="PATH",
                        help="baseline sketch (file or work dir)")
    parser.add_argument("--b", dest="b", default=None, metavar="PATH",
                        help="comparison sketch (file or work dir)")
    parser.add_argument("--max-psi", type=float, default=None,
                        help="gate: exit 1 when the comparison's max "
                             "PSI exceeds this ceiling (the pilot's "
                             "health:drift threshold semantics)")
    parser.add_argument("--top-k", type=int, default=10,
                        help="top moved features per shard (default 10)")
    parser.add_argument("--url", default=None, metavar="URL",
                        help="also scrape a live monitor and print its "
                             "health_* metric families")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write the machine-readable report")
    args = parser.parse_args(argv)

    if args.a is None and args.b is None and args.url is None:
        parser.error("nothing to do: pass --a/--b and/or --url")
    if (args.a is None) != (args.b is None):
        parser.error("--a and --b come together (two sketches compare)")

    from photon_tpu.obs import health

    out: dict = {"metric": "health"}
    rc = 0
    if args.a is not None:
        path_a = resolve_sketch_path(args.a)
        path_b = resolve_sketch_path(args.b)
        sketch_a = health.DataSketch.load(path_a)
        sketch_b = health.DataSketch.load(path_b)
        report = health.compare(sketch_a, sketch_b, top_k=args.top_k)
        out["a"] = path_a
        out["b"] = path_b
        out["comparison"] = report
        print(health.render_comparison(report))
        if args.max_psi is not None:
            out["max_psi_ceiling"] = args.max_psi
            out["gate_fired"] = report["max_psi"] > args.max_psi
            if out["gate_fired"]:
                print(
                    f"GATE: max PSI {report['max_psi']} > ceiling "
                    f"{args.max_psi:g} ({report['max_psi_surface']})")
                rc = 1
            else:
                print(
                    f"gate OK: max PSI {report['max_psi']} <= "
                    f"{args.max_psi:g}")
    if args.url is not None:
        lines = scrape_health_families(args.url)
        out["url"] = args.url
        out["live_families"] = lines
        print(f"== live health families ({args.url}) ==")
        if lines:
            print("\n".join(lines))
        else:
            print("(no health_* families — the layer is disarmed on "
                  "that server)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    return rc


if __name__ == "__main__":
    sys.exit(main())
