"""Training/scoring configuration: YAML/JSON -> typed configs.

TPU-native counterpart of the reference's three-tier config system (SURVEY
§5.6): scopt CLI flags -> Spark-ML ParamMap -> typed case classes
(io/scopt/ScoptGameTrainingParametersParser.scala:42,
io/CoordinateConfiguration.scala:25-70). The nested ``name=...|...`` scopt
map syntax becomes one YAML/JSON document with the same vocabulary:
optimizer type/tolerance/iterations, regularization type/alpha/weights
(the per-coordinate lambda grid), active data bounds, down-sampling,
update sequence, normalization, evaluators, output modes.
"""

from __future__ import annotations

import dataclasses
import itertools
import json

from photon_tpu import optim
from photon_tpu.algorithm.problems import (
    GLMOptimizationConfiguration,
    VarianceComputationType,
)
from photon_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_tpu.estimators.game_estimator import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_tpu.ops.normalization import NormalizationType
from photon_tpu.types import TaskType


@dataclasses.dataclass(frozen=True)
class CoordinateSpec:
    """One coordinate's parsed config + its lambda grid.

    Reference: io/CoordinateConfiguration.scala:25-70 — data config + opt
    config + regularization weight set, expanded per lambda sorted
    descending (:62).
    """

    config: object  # FixedEffect/RandomEffectCoordinateConfiguration
    lambdas: tuple[float, ...]

    def expanded(self) -> list[GLMOptimizationConfiguration]:
        base = self.config.optimization
        if not self.lambdas:
            return [base]
        return [
            base.with_regularization_weight(lam)
            for lam in sorted(self.lambdas, reverse=True)
        ]


def _parse_optimizer(d: dict) -> optim.OptimizerConfig:
    kind = optim.OptimizerType(d.get("type", "LBFGS").upper())
    kw = {}
    for key in ("tolerance", "max_iterations", "num_corrections",
                "max_improvement_failures", "max_cg_iterations",
                "max_line_search_iterations"):
        if key in d:
            kw[key] = d[key]
    if kind == optim.OptimizerType.TRON:
        return optim.OptimizerConfig.tron(**kw)
    return optim.OptimizerConfig.lbfgs(**kw)


def _parse_regularization(d: dict) -> tuple[optim.RegularizationContext, tuple[float, ...]]:
    kind = optim.RegularizationType(d.get("type", "NONE").upper())
    ctx = optim.RegularizationContext(
        kind,
        alpha=d.get("alpha") if kind == optim.RegularizationType.ELASTIC_NET
        else None,
    )
    weights = d.get("weights", d.get("weight", ()))
    if isinstance(weights, (int, float)):
        weights = (float(weights),)
    return ctx, tuple(float(w) for w in weights)


def parse_coordinate(cid: str, d: dict) -> CoordinateSpec:
    opt_cfg = GLMOptimizationConfiguration(
        optimizer=_parse_optimizer(d.get("optimizer", {})),
        down_sampling_rate=float(d.get("down_sampling_rate", 1.0)),
        variance_computation=VarianceComputationType(
            d.get("variance_computation", "NONE").upper()
        ),
        incremental_weight=float(d.get("incremental_weight", 1.0)),
    )
    reg_dict = d.get("regularization", {})
    reg, lambdas = _parse_regularization(reg_dict)
    opt_cfg = dataclasses.replace(
        opt_cfg,
        regularization=reg,
        regularization_weight=lambdas[0] if lambdas else 0.0,
        regularization_weight_range=(
            tuple(reg_dict["weight_range"])
            if "weight_range" in reg_dict else None
        ),
        elastic_net_param_range=(
            tuple(reg_dict["alpha_range"])
            if "alpha_range" in reg_dict else None
        ),
    )
    shard = d.get("feature_shard", "features")
    kind = d.get("type", "fixed").lower()
    if kind in ("fixed", "fixed_effect", "fixed-effect"):
        cfg = FixedEffectCoordinateConfiguration(
            shard, opt_cfg,
            feature_sharding=str(
                d.get("feature_sharding", "replicated")).lower(),
        )
    elif kind in ("random", "random_effect", "random-effect"):
        cfg = RandomEffectCoordinateConfiguration(
            RandomEffectDataConfiguration(
                random_effect_type=d["random_effect_type"],
                feature_shard_id=shard,
                active_data_upper_bound=d.get("active_data_upper_bound"),
                active_data_lower_bound=d.get("active_data_lower_bound"),
                features_to_samples_ratio=d.get("features_to_samples_ratio"),
            ),
            opt_cfg,
        )
    else:
        raise ValueError(f"coordinate {cid!r}: unknown type {kind!r}")
    return CoordinateSpec(cfg, lambdas)


@dataclasses.dataclass
class TrainingConfig:
    """Parsed `photon train` configuration (GameTrainingDriver params)."""

    task: TaskType
    coordinates: dict[str, CoordinateSpec]
    update_sequence: list[str]
    num_iterations: int
    input_format: str  # "avro" | "libsvm"
    train_path: str
    validation_path: str | None
    output_dir: str
    id_tags: list[str] | None
    normalization: NormalizationType
    evaluators: list[str]
    model_output_mode: str  # NONE | BEST | EXPLICIT | TUNED | ALL
    warm_start_model_dir: str | None
    locked_coordinates: set[str]
    hyperparameter_tuning: dict | None
    incremental_training: bool
    data_validation: str
    feature_index_dir: str | None
    profile_dir: str | None
    # Multi-bag shard specs (AvroDataReader.readMerged): shard -> record
    # feature-bag fields, or shard -> {bags: [...], intercept: bool}
    # (FeatureShardConfiguration featureBags + hasIntercept); None means the
    # single TrainingExampleAvro 'features' bag. id_columns exposes
    # top-level record fields as id tags.
    feature_shards: dict[str, list[str] | dict] | None
    id_columns: list[str] | None

    def shard_bags(self) -> dict[str, list[str]] | None:
        if self.feature_shards is None:
            return None
        out = {}
        for shard, spec in self.feature_shards.items():
            if isinstance(spec, dict):
                if "bags" not in spec:
                    raise ValueError(
                        f"feature shard {shard!r}: dict spec needs a "
                        "'bags' list (and optional 'intercept' bool)")
                bags = spec["bags"]
            else:
                bags = spec
            if isinstance(bags, str) or not all(
                isinstance(b, str) for b in bags
            ):
                raise ValueError(
                    f"feature shard {shard!r}: bags must be a list of "
                    f"record field names, got {bags!r}")
            out[shard] = list(bags)
        return out

    def shard_intercepts(self) -> dict[str, bool]:
        if self.feature_shards is None:
            return {}
        return {
            shard: bool(spec.get("intercept", True))
            for shard, spec in self.feature_shards.items()
            if isinstance(spec, dict)
        }
    # Daily-format input selection (trainDir/yyyy/MM/dd, GameDriver
    # inputDataDateRange / inputDataDaysRange): "yyyymmdd-yyyymmdd" / "N-M".
    date_range: str | None
    days_range: str | None
    # Multi-device execution: "auto" (all devices; the reference's
    # cluster-session default, SparkSessionConfiguration.scala:109), "off",
    # or a device count.
    mesh: str | int = "auto"
    # Per-feature summary artifact directory (GameTrainingDriver
    # dataSummaryDirectory): when set, each shard's stats are written as
    # FeatureSummarizationResultAvro under <dir>/<shardId>/.
    data_summary_dir: str | None = None
    # Reserved-column remapping (InputColumnsNames.scala:80-88): keys
    # uid/response/offset/weight/metadataMap -> actual field names.
    input_columns: dict[str, str] | None = None

    @staticmethod
    def load(path: str) -> "TrainingConfig":
        raw = _read_config_file(path)
        coords = {
            cid: parse_coordinate(cid, c)
            for cid, c in raw["coordinates"].items()
        }
        return TrainingConfig(
            task=TaskType(raw["task"].upper()),
            coordinates=coords,
            update_sequence=list(
                raw.get("update_sequence", list(coords))
            ),
            num_iterations=int(raw.get("num_iterations", 1)),
            input_format=raw.get("input", {}).get("format", "avro"),
            train_path=raw["input"]["train_path"],
            validation_path=raw.get("input", {}).get("validation_path"),
            output_dir=raw["output_dir"],
            id_tags=raw.get("input", {}).get("id_tags"),
            normalization=NormalizationType(
                raw.get("normalization", "NONE").upper()
            ),
            evaluators=list(raw.get("evaluators", [])),
            model_output_mode=raw.get("model_output_mode", "BEST").upper(),
            warm_start_model_dir=raw.get("warm_start_model_dir"),
            locked_coordinates=set(raw.get("locked_coordinates", ())),
            hyperparameter_tuning=raw.get("hyperparameter_tuning"),
            incremental_training=bool(raw.get("incremental_training", False)),
            data_validation=str(
                raw.get("data_validation", "DISABLED")).upper(),
            feature_index_dir=raw.get("input", {}).get("feature_index_dir"),
            profile_dir=raw.get("profile_dir"),
            feature_shards=raw.get("input", {}).get("feature_shards"),
            id_columns=raw.get("input", {}).get("id_columns"),
            date_range=raw.get("input", {}).get("date_range"),
            days_range=raw.get("input", {}).get("days_range"),
            mesh=raw.get("mesh", "auto"),
            data_summary_dir=raw.get("data_summary_dir"),
            input_columns=raw.get("input", {}).get("input_columns"),
        )

    def opt_config_sequence(self) -> list[dict[str, GLMOptimizationConfiguration]]:
        """Cartesian product of per-coordinate lambda grids, each entry one
        full GAME optimization configuration
        (GameTrainingDriver.prepareGameOptConfigs :658-667)."""
        ids = list(self.coordinates)
        grids = [self.coordinates[cid].expanded() for cid in ids]
        return [
            dict(zip(ids, combo)) for combo in itertools.product(*grids)
        ]

    def build_estimator(
        self, normalization_contexts=None, intercept_indices=None
    ) -> GameEstimator:
        return GameEstimator(
            self.task,
            {cid: spec.config for cid, spec in self.coordinates.items()},
            update_sequence=self.update_sequence,
            num_iterations=self.num_iterations,
            normalization=normalization_contexts or {},
            intercept_indices=intercept_indices or {},
            evaluators=self.evaluators or None,
            locked_coordinates=self.locked_coordinates,
            incremental_training=self.incremental_training,
            mesh=self.mesh,
        )


def _read_config_file(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    import yaml

    return yaml.safe_load(text)
