"""``photon profile``: the cost ledger's top-k report — who burns the time.

Drives a tiny-but-real workload (a fused GLMix fit plus a serve-ladder
scoring pass) under the cost ledger (``photon_tpu.obs.ledger``) and
prints the top-k ``(coordinate, phase, program)`` rows ranked by
wasted-seconds-vs-roofline, each with its blocking reason — dispatch
gap vs bandwidth vs compute — plus the attribution fraction of the
measured fit wall. This is the instrument the roofline push steers by:
``measured_vs_roofline`` says the gap exists; this names it.

Three gates ride along (the profile-smoke CI job's contract):

- **off-census**: the same fit runs FIRST with the ledger disabled and
  the census must stay EMPTY — a disabled ledger adds zero programs
  (and, conveniently, the warm-up makes the overhead A/B honest);
- **engagement**: the top-k table must be non-empty and the fused-fit
  wall must attribute to named rows (exit 1 otherwise — a dead
  instrument must not report "clean");
- **overhead** (``--overhead-check``): warm per-fit wall, ledger off vs
  on, best-of-N in-process A/B (interleaved arms — the only honest
  protocol on a noisy shared box); the on/off ratio must stay under
  ``--overhead-budget`` (default 5%).

Usage:
    python -m photon_tpu.cli.profile [--top N] [--json PATH]
        [--rows N] [--entities N] [--iterations N]
        [--overhead-check] [--overhead-samples N] [--overhead-budget F]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _tiny_workload(rows: int, entities: int, iterations: int):
    """A miniature single-device GLMix estimator + dataset (one dense
    fixed effect, one random effect, logistic task) — the smallest
    structure that exercises the fused materialize/fit programs and a
    servable model. Mirrors the analysis tier's audit fixture; kept
    local so the CLI never imports audit machinery."""
    import numpy as np

    from photon_tpu.data.dataset import DenseFeatures
    from photon_tpu.data.game_data import make_game_dataset
    from photon_tpu.data.random_effect import RandomEffectDataConfiguration
    from photon_tpu.estimators.game_estimator import (
        FixedEffectCoordinateConfiguration,
        GameEstimator,
        RandomEffectCoordinateConfiguration,
    )
    from photon_tpu.optim import RegularizationContext, RegularizationType
    from photon_tpu.algorithm.problems import GLMOptimizationConfiguration
    from photon_tpu.types import TaskType

    def l2(w):
        return GLMOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=w,
        )

    d, du = 6, 4
    rng = np.random.default_rng(20260804)
    x = rng.normal(size=(rows, d)).astype(np.float32)
    x[:, -1] = 1.0
    xu = rng.normal(size=(rows, du)).astype(np.float32)
    xu[:, -1] = 1.0
    users = rng.integers(0, entities, size=rows)
    y = (rng.uniform(size=rows) < 0.5).astype(np.float32)
    data = make_game_dataset(
        y,
        {"global": DenseFeatures(x), "userShard": DenseFeatures(xu)},
        id_tags={"userId": users},
    )
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "global": FixedEffectCoordinateConfiguration(
                "global", l2(0.01)
            ),
            "per-user": RandomEffectCoordinateConfiguration(
                RandomEffectDataConfiguration("userId", "userShard"),
                l2(0.5),
            ),
        },
        intercept_indices={"global": d - 1, "userShard": du - 1},
        num_iterations=iterations,
        mesh="off",
    )
    return est, data


def _fit_once(est, data):
    """One blocking fit (checksum-forced completion — enqueue times
    are not measurements; same idiom as bench.py)."""
    import jax.numpy as jnp
    import numpy as np

    r = est.fit(data)[0]
    for m in r.model.models.values():
        c = (m.coefficients if hasattr(m, "coefficients")
             else m.model.coefficients.means)
        float(np.asarray(jnp.sum(c)))
    return r


def _serve_pass(result, data):
    """Score the training rows through the REAL serve ladder (tables →
    AOT rungs → padded dispatch), so serve-phase rows and the compile
    ledger engage."""
    from photon_tpu.serve.programs import ScorePrograms, specs_from_dataset
    from photon_tpu.serve.tables import CoefficientTables

    tables = CoefficientTables.from_game_model(result.model)
    programs = ScorePrograms(
        tables, specs=specs_from_dataset(data), compile_now=False
    )
    return programs.score_dataset(data)


def _overhead_ab(
    est, data, samples: int, fits_per_sample: int = 3
) -> dict:
    """Warm fit wall, ledger off vs on: interleaved arms, best-of-N
    each (the 2-core CI box is noisy; the BEST of an interleaved series
    is the only stable estimator of the true floor in-process). Each
    sample times a small BATCH of fits — a single warm fit is
    milliseconds, where one scheduler hiccup masquerades as overhead."""
    from photon_tpu.obs import ledger

    k = max(fits_per_sample, 1)
    off: list[float] = []
    on: list[float] = []
    for _ in range(max(samples, 1)):
        ledger.disable()
        t0 = time.perf_counter()
        for _ in range(k):
            _fit_once(est, data)
        off.append(time.perf_counter() - t0)
        ledger.enable()
        t0 = time.perf_counter()
        for _ in range(k):
            _fit_once(est, data)
        on.append(time.perf_counter() - t0)
    best_off, best_on = min(off), min(on)
    return {
        "samples": len(off),
        "fits_per_sample": k,
        "off_best_seconds": round(best_off, 6),
        "on_best_seconds": round(best_on, 6),
        "overhead_fraction": (
            round(best_on / best_off - 1.0, 4) if best_off > 0 else None
        ),
    }


def _kernel_probe() -> dict | None:
    """One REAL dispatch of the tiled segment-reduce kernel under the
    armed ledger (ops/segment_reduce): registers its census row with the
    analytic cost and records the measured dispatch->fetch window, so
    the priced report carries the kernel's own roofline row. Returns
    None where the kernel does not serve this backend (auto mode off
    TPU) — the profile-smoke job forces it with
    ``PHOTON_SEGMENT_KERNEL=force`` to exercise the interpreter path.
    """
    import numpy as np

    from photon_tpu.obs import ledger
    from photon_tpu.ops import segment_reduce as sr

    m = n = 8_192
    if not sr.kernel_supported(m, n, np.float32):
        return None
    import jax
    import jax.numpy as jnp

    ids = jnp.asarray(np.arange(m, dtype=np.int32))
    vals = jnp.asarray(
        np.random.default_rng(0).normal(size=m).astype(np.float32))
    site = "segment_reduce/probe"
    # warm (compile outside the measured window)
    jax.block_until_ready(sr.sorted_segment_sum(
        vals, ids, n, multiplicity=1, site=site))
    t0 = time.perf_counter()
    out = np.asarray(sr.sorted_segment_sum(
        vals, ids, n, multiplicity=1, site=site))
    t1 = time.perf_counter()
    info = sr.traced_sites()[site]
    ledger.register_program(site, phase="score", cost=info["cost"])
    ledger.record_dispatch(
        site, t1 - t0, phase="score", start=t0, end=t1)
    return {
        "program": site,
        "elements": m,
        "segments": n,
        "seconds": round(t1 - t0, 6),
        "checksum": float(out.sum()),
    }


def _serve_kernel_probe() -> dict | None:
    """One REAL dispatch of the fused serve-score kernel under the
    armed ledger (ops/serve_kernel): a tiny model's tables are loaded
    at serving precision, one padded rung is scored through the fused
    pallas path, and the kernel's trace-time census entry prices its
    roofline row next to the jit-chain serve rows. Returns None where
    the kernel does not serve this backend (auto mode off TPU) — the
    profile-smoke job forces it with ``PHOTON_SERVE_KERNEL=force`` to
    exercise the interpreter path."""
    import numpy as np

    from photon_tpu.obs import ledger
    from photon_tpu.ops import serve_kernel as sk

    if not sk.kernel_supported(np.float32):
        return None
    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import (
        Coefficients,
        GeneralizedLinearModel,
    )
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.types import TaskType

    d, e, s, du, rung = 6, 16, 3, 4, 64
    rng = np.random.default_rng(20260806)
    proj = np.stack([
        np.sort(rng.choice(du, size=s, replace=False))
        for _ in range(e)
    ]).astype(np.int64)
    model = GameModel({
        "global": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(means=jnp.asarray(
                    rng.normal(size=d).astype(np.float32)
                )),
                TaskType.LOGISTIC_REGRESSION,
            ),
            "features",
        ),
        "per-user": RandomEffectModel(
            coefficients=jnp.asarray(
                rng.normal(size=(e, s)).astype(np.float32)
            ),
            random_effect_type="userId",
            feature_shard_id="userShard",
            task=TaskType.LOGISTIC_REGRESSION,
            proj_all=proj,
            entity_keys=tuple(str(i) for i in range(e)),
        ),
    })
    tables = CoefficientTables.from_game_model(model)
    programs = ScorePrograms(tables, ladder=ShapeLadder((rung,)))
    if not programs.use_kernel:
        return None
    reqs = [
        (
            {
                "features": rng.normal(size=d).astype(np.float32),
                "userShard": rng.normal(size=du).astype(np.float32),
            },
            {"userId": str(i % e)},
        )
        for i in range(rung)
    ]
    feats, codes, _ = programs.pack_requests(reqs)
    # warm (the AOT ladder compiled at construction; this pays the
    # first-dispatch transfer outside the measured window)
    programs.score_padded(feats, codes, rung)
    site = "serve_kernel/score"
    t0 = time.perf_counter()
    out = programs.score_padded(feats, codes, rung)
    t1 = time.perf_counter()
    info = sk.traced_sites().get(site)
    if info is None:
        return None
    probe_site = "serve_kernel/probe"
    ledger.register_program(probe_site, phase="serve", cost=info["cost"])
    ledger.record_dispatch(
        probe_site, t1 - t0, phase="serve", start=t0, end=t1)
    return {
        "program": probe_site,
        "rung": rung,
        "seconds": round(t1 - t0, 6),
        "checksum": float(np.asarray(out).sum()),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon profile", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--top", type=int, default=5,
                        help="rows in the top-k table")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write the full priced report to PATH")
    parser.add_argument("--rows", type=int, default=512,
                        help="workload rows")
    parser.add_argument("--entities", type=int, default=16,
                        help="random-effect entities")
    parser.add_argument("--iterations", type=int, default=2,
                        help="coordinate-descent iterations")
    parser.add_argument("--fits", type=int, default=3,
                        help="warm fits inside the measured window")
    parser.add_argument("--overhead-check", action="store_true",
                        help="A/B the warm fit ledger-off vs ledger-on "
                        "and gate the overhead fraction")
    parser.add_argument("--overhead-samples", type=int, default=25,
                        help="best-of-N samples per A/B arm (the fits "
                        "are milliseconds warm; a deep N is what makes "
                        "the best-of estimator stable on a loaded box)")
    parser.add_argument("--overhead-budget", type=float, default=0.05,
                        help="max tolerated on/off overhead fraction")
    args = parser.parse_args(argv)

    from photon_tpu import obs
    from photon_tpu.obs import ledger

    failures: list[str] = []
    obs.enable()
    ledger.disable()
    ledger.reset()

    est, data = _tiny_workload(args.rows, args.entities, args.iterations)
    # Gate 1 — off-census: the ledger-disabled run must register NOTHING
    # (zero added programs in the dispatch census). Doubles as warm-up:
    # this pays the compiles, so the A/B and the attribution window
    # below measure dispatch, not tracing.
    result = _fit_once(est, data)
    _serve_pass(result, data)
    off_snap = ledger.snapshot()
    if off_snap["programs"] or off_snap["rows"] or off_snap["compiles"]:
        failures.append(
            "ledger-disabled run polluted the census: "
            f"{len(off_snap['programs'])} program(s), "
            f"{len(off_snap['rows'])} row(s), "
            f"{len(off_snap['compiles'])} compile key(s)"
        )

    overhead = None
    if args.overhead_check:
        overhead = _overhead_ab(est, data, args.overhead_samples)
        ledger.reset()  # the A/B's on-arm rows are not the profile
        if (
            overhead["overhead_fraction"] is not None
            and overhead["overhead_fraction"] > args.overhead_budget
        ):
            failures.append(
                f"ledger-on overhead {overhead['overhead_fraction']:.2%}"
                f" > budget {args.overhead_budget:.2%} "
                f"(best-of-{overhead['samples']} per arm)"
            )

    # The profiled window: warm fits + a serve pass, ledger armed.
    ledger.enable()
    mark = ledger.mark()
    t0 = time.perf_counter()
    for _ in range(max(args.fits, 1)):
        result = _fit_once(est, data)
    fit_wall = time.perf_counter() - t0
    # The fit-window attribution closes BEFORE the serve pass: serve
    # rows recorded after the fit wall must not count as attributed
    # fit seconds, or a dead fused-fit feed would hide behind them.
    fit_attr = ledger.attribution_since(mark, wall_seconds=fit_wall)
    _serve_pass(result, data)
    # Kernel probes: where the segment-reduce / fused serve kernels
    # serve this backend, one real dispatch each prices its census/
    # roofline row into the report (the profile-smoke job forces the
    # kernels and asserts the rows).
    kernel_probe = _kernel_probe()
    serve_kernel_probe = _serve_kernel_probe()
    attribution = ledger.attribution_since(mark, wall_seconds=None)

    table = ledger.render_top_k(args.top)
    rows = ledger.top_k(args.top)
    print(table)
    if rows:
        worst = rows[0]
        print(
            f"worst program: {worst['program']} "
            f"(coordinate={worst['coordinate']}, phase={worst['phase']}) "
            f"— wasted {worst['wasted_seconds']:.4f}s vs its roofline, "
            f"blocking: {worst['blocking']}"
        )
    print(
        "fit-window attribution: "
        f"{fit_attr['attributed_fraction']} of {fit_wall:.4f}s named "
        f"({fit_attr['unattributed_seconds']:.4f}s unattributed)"
    )
    if overhead is not None:
        print(
            f"ledger overhead: {overhead['overhead_fraction']} "
            f"(off {overhead['off_best_seconds']:.4f}s / on "
            f"{overhead['on_best_seconds']:.4f}s, "
            f"best-of-{overhead['samples']})"
        )

    # Gate 2 — engagement: an empty table or a dead attribution means
    # the instrument is broken, and a broken instrument exiting 0 is
    # how tracked metrics rot.
    if not rows:
        failures.append("top-k table is empty (no dispatches recorded)")
    if not fit_attr["attributed_fraction"]:
        failures.append(
            "fused-fit wall attributed nothing (ledger feed dead)")
    if kernel_probe is not None:
        probe_rows = [
            r for r in ledger.report()["rows"]
            if r.get("program") == kernel_probe["program"]
        ]
        if not probe_rows:
            failures.append(
                "segment-reduce kernel dispatched but its census row is "
                "missing from the priced report")
        elif probe_rows[0].get("vs_roofline") is None:
            failures.append(
                "segment-reduce census row carries no priced roofline "
                "(vs_roofline is None — analytic cost missing)")
    if serve_kernel_probe is not None:
        probe_rows = [
            r for r in ledger.report()["rows"]
            if r.get("program") == serve_kernel_probe["program"]
        ]
        if not probe_rows:
            failures.append(
                "serve kernel dispatched but its census row is missing "
                "from the priced report")
        elif probe_rows[0].get("vs_roofline") is None:
            failures.append(
                "serve-kernel census row carries no priced roofline "
                "(vs_roofline is None — analytic cost missing)")

    if args.json:
        doc = {
            "report": ledger.report(),
            "attribution": attribution,
            "fit_window": {
                "wall_seconds": round(fit_wall, 6),
                "fits": max(args.fits, 1),
                **fit_attr,
            },
            "overhead": overhead,
            "kernel_probe": kernel_probe,
            "serve_kernel_probe": serve_kernel_probe,
            "failures": failures,
        }
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2)
    for msg in failures:
        print(f"FAIL: {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
