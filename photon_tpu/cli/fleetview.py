"""``python -m photon_tpu.cli.fleetview`` — merge per-rank obs bundles.

The read side of the fleet layer (``obs/fleet.py``): point it at the
shared run directory the ranks shipped their ``obs-host-<k>/`` bundles
into and it produces

- ONE Perfetto-loadable timeline (``--trace``; pid per rank, every
  host's events shifted onto the shared epoch clock through its own
  clock-alignment handshake, ``validate_chrome_trace``-clean),
- the fleet ledger rollup + straggler report (printed; ``--json`` writes
  the full report): per-rank attributed dispatch seconds, per-program
  max−min window skew, the slowest rank, the collective-vs-compute
  split of barrier wait, and the clock skew bound the cross-host
  ordering is trusted to.

Degradation is visible, never fatal: a crashed rank's torn spans.jsonl,
an uncommitted bundle, or a missing rank land in the report's ``gaps``
and the merge proceeds over what exists. Exit codes: 0 merged clean,
1 merged with gaps or a ``--expect-ranks`` mismatch, 2 nothing to merge.
"""

from __future__ import annotations

import argparse
import json
import logging
import os

from photon_tpu.cli.common import cli_logging

logger = logging.getLogger("photon.cli.fleetview")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_tpu.cli.fleetview",
        description=(
            "Merge per-rank obs bundles (obs-host-<k>/) into one "
            "Perfetto timeline + a fleet straggler report."
        ),
    )
    p.add_argument(
        "--run-dir", required=True,
        help="shared run directory the ranks shipped bundles into",
    )
    p.add_argument(
        "--trace", default=None, metavar="PATH",
        help="write the merged chrome-trace timeline here "
        "(default: <run-dir>/fleet-trace.json)",
    )
    p.add_argument(
        "--json", default=None, metavar="PATH",
        help="write the full straggler report as JSON",
    )
    p.add_argument(
        "--expect-ranks", type=int, default=None, metavar="N",
        help="fail (exit 1) unless exactly N rank bundles merged",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def render_report(report: dict) -> str:
    """The human view of a straggler report."""
    rows = [
        "== fleet straggler report ==",
        f"bundles {report['bundles']}/{report['process_count']} "
        f"rank(s) {report['ranks']}"
        + (
            f"  MISSING {report['missing_ranks']}"
            if report["missing_ranks"] else ""
        ),
        f"wall {report['wall_seconds']:.4f}s  "
        f"straggler skew {report['straggler_skew_seconds']:.4f}s  "
        f"collective fraction {report['collective_fraction']:.4f}  "
        f"clock bound {report['clock_skew_bound_seconds']:.2e}s",
    ]
    if report.get("straggler"):
        s = report["straggler"]
        rows.append(
            f"slowest rank: {s['process_index']} "
            f"({s['attributed_seconds']:.4f}s attributed)"
        )
    rows.append(
        "-- per rank (attributed s / collective wait s / dispatches) --"
    )
    for r in report["per_rank"]:
        rows.append(
            f"  rank {r['process_index']:<3} {r['hostname'] or '?':<20} "
            f"{r['attributed_seconds']:>10.4f} "
            f"{r['collective_wait_seconds']:>10.4f} "
            f"{r['dispatches']:>6}"
        )
    progs = report.get("programs") or {}
    shared = {
        name: e for name, e in progs.items() if e.get("on_all_ranks")
    }
    if shared:
        rows.append("-- programs on all ranks (window skew s) --")
        for name, e in sorted(shared.items()):
            skew = e.get("window_skew_seconds", e.get("seconds_skew"))
            rows.append(
                f"  {name:<28} "
                f"{'-' if skew is None else f'{skew:.4f}':>10}"
                + (
                    f"  slowest rank {e['slowest_rank']}"
                    if "slowest_rank" in e else ""
                )
            )
    for gap in report.get("gaps", ()):
        rows.append(f"GAP: {gap}")
    return "\n".join(rows)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from photon_tpu.obs import fleet

    with cli_logging(args.verbose, None):
        trace_path = args.trace or os.path.join(
            args.run_dir, "fleet-trace.json"
        )
        report, _trace_doc = fleet.merge_run(
            args.run_dir, trace_path=trace_path
        )
        if not report["bundles"]:
            print(render_report(report))
            print(f"fleetview: no bundles under {args.run_dir}")
            return 2
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1)
        print(render_report(report))
        print(f"merged timeline: {trace_path}")
        if (
            args.expect_ranks is not None
            and report["bundles"] != args.expect_ranks
        ):
            print(
                f"fleetview: expected {args.expect_ranks} rank "
                f"bundle(s), merged {report['bundles']}"
            )
            return 1
        return 1 if report["gaps"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
