"""``photon score``: batch scoring driver.

TPU-native counterpart of GameScoringDriver (photon-client
cli/game/scoring/GameScoringDriver.scala:39, run :136-197): feature maps ->
read data -> load GAME model -> GameTransformer -> save ScoringResultAvro
(+ optional evaluation).

Usage:
    python -m photon_tpu.cli.score --model-dir out/models/best \
        --input data.avro --output scores/ [--evaluators AUC RMSE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon score", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--model-dir", required=True,
                        help="GAME model directory (Avro layout)")
    parser.add_argument("--input", required=True,
                        help="TrainingExampleAvro data file/dir")
    parser.add_argument("--output", required=True,
                        help="output directory for scores")
    parser.add_argument("--model-id", default="")
    parser.add_argument("--evaluators", nargs="*", default=None,
                        help="optional metrics, e.g. AUC RMSE AUC:userId")
    parser.add_argument("--id-tags", nargs="*", default=None)
    parser.add_argument("--feature-shards", nargs="*", default=None,
                        help="shard=bag[,bag...] specs for multi-bag avro "
                             "layouts (must match the model's shards)")
    parser.add_argument("--id-columns", nargs="*", default=None,
                        help="top-level record fields to expose as id tags")
    parser.add_argument("--data-validation", default="DISABLED",
                        help="FULL | SAMPLE | DISABLED")
    parser.add_argument("--input-columns", nargs="*", default=None,
                        metavar="COL=FIELD",
                        help="remap reserved record fields "
                             "(uid/response/offset/weight/metadataMap), "
                             "e.g. weight=sampleWeight "
                             "(InputColumnsNames.scala:80-88)")
    parser.add_argument("--mesh", default="auto",
                        help="multi-device scoring: auto (all devices), "
                             "off, or a device count")
    parser.add_argument("--backend", default=None)
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--log-file", default=None,
                        help="also write logs to this file (PhotonLogger "
                             "equivalent, util/PhotonLogger.scala:34)")
    args = parser.parse_args(argv)

    if args.backend:
        os.environ["JAX_PLATFORMS"] = args.backend
    from photon_tpu.cli.common import cli_logging, maybe_init_distributed

    with cli_logging(args.verbose, args.log_file):
        from photon_tpu.utils import enable_compilation_cache

        enable_compilation_cache()  # persistent XLA cache: warm runs skip compiles
        maybe_init_distributed()
        return _run(args)


def _run(args) -> int:
    import numpy as np

    from photon_tpu.io.avro_data import (
        build_index_map_from_records,
        read_training_examples,
    )
    from photon_tpu.io import avro
    from photon_tpu.io.model_io import load_game_model, save_scores
    from photon_tpu.transformers import GameTransformer

    # Feature index built from the scoring data's keys. Model features absent
    # from the data are dropped at model load; that is harmless — a feature
    # no row carries contributes zero margin either way.
    input_columns = None
    if args.input_columns:
        bad = [kv for kv in args.input_columns if "=" not in kv]
        if bad:
            raise SystemExit(
                f"--input-columns operands must be COL=FIELD, got {bad}")
        input_columns = dict(
            kv.split("=", 1) for kv in args.input_columns
        )

    from photon_tpu.io.model_io import model_feature_shard_ids

    records = avro.read_container_dir(args.input)
    needed_shards = model_feature_shard_ids(args.model_dir)

    if args.feature_shards:
        # Multi-bag layout: per-shard tables + per-shard index maps — the
        # scoring twin of the training driver's read_merged path.
        from photon_tpu.cli.index import parse_shard_spec
        from photon_tpu.io.avro_data import read_merged

        shard_bags = parse_shard_spec(args.feature_shards)
        missing = sorted(needed_shards - set(shard_bags))
        if missing:
            raise ValueError(
                f"model needs feature shard(s) {missing} but "
                f"--feature-shards only defines {sorted(shard_bags)}")
        data, index_maps = read_merged(
            args.input,
            feature_shards=shard_bags,
            id_columns=args.id_columns,
            id_tag_names=args.id_tags,
            input_columns=input_columns,
            records=records,
        )
        model, metadata = load_game_model(args.model_dir, index_maps)
    else:
        if len(needed_shards) > 1:
            raise ValueError(
                f"model was trained on multiple feature shards "
                f"{sorted(needed_shards)}; pass --feature-shards so each "
                "resolves against its own bags (aliasing them all to the "
                "single 'features' table would silently zero the random "
                "effects)")
        index_map = build_index_map_from_records(records)
        data, _ = read_training_examples(
            args.input, index_map=index_map, id_tag_names=args.id_tags,
            input_columns=input_columns, records=records,
        )
        index_maps = {s: index_map for s in needed_shards} or {
            "features": index_map}
        model, metadata = load_game_model(args.model_dir, index_maps)
        data = _alias_shards(data, needed_shards)

    from photon_tpu.data.validators import sanity_check_data

    # Scoring rows may carry dummy labels; validate everything else.
    sanity_check_data(
        data, model.task, args.data_validation, check_labels=False,
    )
    from photon_tpu.parallel.mesh import resolve_mesh

    scores, evaluation = score_game_dataset(
        model, data, mesh=resolve_mesh(args.mesh),
        evaluators=args.evaluators,
    )

    from photon_tpu.cli.common import fetch_global, is_coordinator

    # Sharded scores span hosts in a multi-host run: gather BEFORE the
    # coordinator gate (allgather is a collective — every process must
    # participate or the coordinator deadlocks).
    scores = fetch_global(scores)
    if not is_coordinator():
        # Artifacts are written once, from process 0.
        return 0
    os.makedirs(args.output, exist_ok=True)
    save_scores(
        os.path.join(args.output, "part-00000.avro"),
        np.asarray(scores),
        model_id=args.model_id or metadata.get("modelType", ""),
        uids=None if data.uids is None else data.uids,
        labels=np.asarray(data.labels),
        weights=np.asarray(data.weights),
    )
    out = {
        "num_scored": int(np.asarray(scores).shape[0]),
        "output": args.output,
    }
    if evaluation is not None:
        out["evaluation"] = evaluation.evaluations
        with open(os.path.join(args.output, "evaluation.json"), "w") as f:
            json.dump(evaluation.evaluations, f, indent=2)
    print(json.dumps(out))
    return 0


def score_game_dataset(model, data, *, mesh=None, evaluators=None):
    """Batch scoring routed through the SERVING implementation.

    Single-device batch scoring and online serving share one scoring
    path: the HBM-resident coefficient tables + the AOT score ladder
    (``serve/tables.py`` / ``serve/programs.py``), chunked over the
    dataset — so a score served online and a score computed offline for
    the same row are the same program family by construction (pinned by
    tests/test_serve.py parity tests). The mesh path (row-sharded score
    tables) and DualEll-layout shards keep the ``GameTransformer``
    route: their layouts have no fixed per-request shape.
    """
    serve_specs = None
    if mesh is None:
        from photon_tpu.serve.programs import specs_from_dataset

        try:
            serve_specs = specs_from_dataset(data)
        except TypeError:
            serve_specs = None  # DualEll shard: no fixed row layout
    if serve_specs is None:
        from photon_tpu.transformers import GameTransformer

        return GameTransformer(model, mesh=mesh).transform(
            data, evaluators=evaluators
        )
    import jax.numpy as jnp

    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.transformers import evaluate_scores

    tables = CoefficientTables.from_game_model(model)
    # compile_now=False: score_dataset compiles exactly the rungs its
    # chunk plan dispatches, so a small file never pays the top rung's
    # compile.
    programs = ScorePrograms(
        tables, ladder=ShapeLadder(BATCH_RUNGS), specs=serve_specs,
        compile_now=False,
    )
    scores = jnp.asarray(programs.score_dataset(data))
    return scores, evaluate_scores(data, scores, evaluators)


# Batch-mode score ladder: the large rung amortizes dispatch overhead
# over file-sized inputs; the small tail rung bounds padding waste. (The
# online default 1/8/64/512 ladder optimizes latency instead.)
BATCH_RUNGS = (1024, 8192)


def _alias_shards(data, shard_names):
    """Expose the single ingest feature table under every model shard name."""
    import dataclasses

    missing = {
        s for s in shard_names if s not in data.feature_shards
    }
    if not missing:
        return data
    table = data.feature_shards["features"]
    shards = dict(data.feature_shards)
    for s in missing:
        shards[s] = table
    return dataclasses.replace(data, feature_shards=shards)


if __name__ == "__main__":
    sys.exit(main())
