"""``photon score``: batch scoring driver.

TPU-native counterpart of GameScoringDriver (photon-client
cli/game/scoring/GameScoringDriver.scala:39, run :136-197): feature maps ->
read data -> load GAME model -> GameTransformer -> save ScoringResultAvro
(+ optional evaluation).

Usage:
    python -m photon_tpu.cli.score --model-dir out/models/best \
        --input data.avro --output scores/ [--evaluators AUC RMSE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon score", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--model-dir", required=True,
                        help="GAME model directory (Avro layout)")
    parser.add_argument("--input", required=True,
                        help="TrainingExampleAvro data file/dir")
    parser.add_argument("--output", required=True,
                        help="output directory for scores")
    parser.add_argument("--model-id", default="")
    parser.add_argument("--evaluators", nargs="*", default=None,
                        help="optional metrics, e.g. AUC RMSE AUC:userId")
    parser.add_argument("--id-tags", nargs="*", default=None)
    parser.add_argument("--feature-shards", nargs="*", default=None,
                        help="shard=bag[,bag...] specs for multi-bag avro "
                             "layouts (must match the model's shards)")
    parser.add_argument("--id-columns", nargs="*", default=None,
                        help="top-level record fields to expose as id tags")
    parser.add_argument("--data-validation", default="DISABLED",
                        help="FULL | SAMPLE | DISABLED")
    parser.add_argument("--input-columns", nargs="*", default=None,
                        metavar="COL=FIELD",
                        help="remap reserved record fields "
                             "(uid/response/offset/weight/metadataMap), "
                             "e.g. weight=sampleWeight "
                             "(InputColumnsNames.scala:80-88)")
    parser.add_argument("--mesh", default="auto",
                        help="multi-device scoring: auto (all devices), "
                             "off, or a device count")
    parser.add_argument("--backend", default=None)
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--log-file", default=None,
                        help="also write logs to this file (PhotonLogger "
                             "equivalent, util/PhotonLogger.scala:34)")
    args = parser.parse_args(argv)

    if args.backend:
        os.environ["JAX_PLATFORMS"] = args.backend
    from photon_tpu.cli.common import cli_logging, maybe_init_distributed

    with cli_logging(args.verbose, args.log_file):
        from photon_tpu.utils import enable_compilation_cache

        enable_compilation_cache()  # persistent XLA cache: warm runs skip compiles
        maybe_init_distributed()
        return _run(args)


def _run(args) -> int:
    import numpy as np

    from photon_tpu.io.avro_data import (
        build_index_map_from_records,
        read_training_examples,
    )
    from photon_tpu.io import avro
    from photon_tpu.io.model_io import load_game_model, save_scores
    from photon_tpu.transformers import GameTransformer

    # Feature index built from the scoring data's keys. Model features absent
    # from the data are dropped at model load; that is harmless — a feature
    # no row carries contributes zero margin either way.
    input_columns = None
    if args.input_columns:
        bad = [kv for kv in args.input_columns if "=" not in kv]
        if bad:
            raise SystemExit(
                f"--input-columns operands must be COL=FIELD, got {bad}")
        input_columns = dict(
            kv.split("=", 1) for kv in args.input_columns
        )

    records = avro.read_container_dir(args.input)
    needed_shards = set()
    import os.path as osp
    for kind in ("fixed-effect", "random-effect"):
        d = osp.join(args.model_dir, kind)
        if osp.isdir(d):
            for name in os.listdir(d):
                with open(osp.join(d, name, "id-info")) as f:
                    needed_shards.add(f.read().strip().splitlines()[-1])

    if args.feature_shards:
        # Multi-bag layout: per-shard tables + per-shard index maps — the
        # scoring twin of the training driver's read_merged path.
        from photon_tpu.cli.index import parse_shard_spec
        from photon_tpu.io.avro_data import read_merged

        shard_bags = parse_shard_spec(args.feature_shards)
        missing = sorted(needed_shards - set(shard_bags))
        if missing:
            raise ValueError(
                f"model needs feature shard(s) {missing} but "
                f"--feature-shards only defines {sorted(shard_bags)}")
        data, index_maps = read_merged(
            args.input,
            feature_shards=shard_bags,
            id_columns=args.id_columns,
            id_tag_names=args.id_tags,
            input_columns=input_columns,
            records=records,
        )
        model, metadata = load_game_model(args.model_dir, index_maps)
    else:
        if len(needed_shards) > 1:
            raise ValueError(
                f"model was trained on multiple feature shards "
                f"{sorted(needed_shards)}; pass --feature-shards so each "
                "resolves against its own bags (aliasing them all to the "
                "single 'features' table would silently zero the random "
                "effects)")
        index_map = build_index_map_from_records(records)
        data, _ = read_training_examples(
            args.input, index_map=index_map, id_tag_names=args.id_tags,
            input_columns=input_columns, records=records,
        )
        index_maps = {s: index_map for s in needed_shards} or {
            "features": index_map}
        model, metadata = load_game_model(args.model_dir, index_maps)
        data = _alias_shards(data, needed_shards)

    from photon_tpu.data.validators import sanity_check_data

    # Scoring rows may carry dummy labels; validate everything else.
    sanity_check_data(
        data, model.task, args.data_validation, check_labels=False,
    )
    from photon_tpu.parallel.mesh import resolve_mesh

    transformer = GameTransformer(model, mesh=resolve_mesh(args.mesh))
    scores, evaluation = transformer.transform(
        data, evaluators=args.evaluators
    )

    from photon_tpu.cli.common import fetch_global, is_coordinator

    # Sharded scores span hosts in a multi-host run: gather BEFORE the
    # coordinator gate (allgather is a collective — every process must
    # participate or the coordinator deadlocks).
    scores = fetch_global(scores)
    if not is_coordinator():
        # Artifacts are written once, from process 0.
        return 0
    os.makedirs(args.output, exist_ok=True)
    save_scores(
        os.path.join(args.output, "part-00000.avro"),
        np.asarray(scores),
        model_id=args.model_id or metadata.get("modelType", ""),
        uids=None if data.uids is None else data.uids,
        labels=np.asarray(data.labels),
        weights=np.asarray(data.weights),
    )
    out = {
        "num_scored": int(np.asarray(scores).shape[0]),
        "output": args.output,
    }
    if evaluation is not None:
        out["evaluation"] = evaluation.evaluations
        with open(os.path.join(args.output, "evaluation.json"), "w") as f:
            json.dump(evaluation.evaluations, f, indent=2)
    print(json.dumps(out))
    return 0


def _alias_shards(data, shard_names):
    """Expose the single ingest feature table under every model shard name."""
    import dataclasses

    missing = {
        s for s in shard_names if s not in data.feature_shards
    }
    if not missing:
        return data
    table = data.feature_shards["features"]
    shards = dict(data.feature_shards)
    for s in missing:
        shards[s] = table
    return dataclasses.replace(data, feature_shards=shards)


if __name__ == "__main__":
    sys.exit(main())
