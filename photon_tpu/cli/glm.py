"""``photon glm``: the single-GLM lambda-sweep driver (legacy Driver).

TPU-native counterpart of the reference's deprecated top-level driver
(photon-client Driver.scala:60) and its engine entry
``ModelTraining.trainGeneralizedLinearModel`` (photon-api
ModelTraining.scala:100): one generalized linear model (no random effects),
trained for a DESCENDING list of regularization weights with warm starts
between them, validated with the legacy metric map (Evaluation.scala:31-110
— MAE/MSE/RMSE for regression facets, AUC/AUPR/peak-F1 for binary
classifiers, per-datum log loss), and the best lambda selected per task
(ModelSelection.scala: AUC for classifiers, RMSE for linear regression,
Poisson loss for Poisson regression).

Stage structure mirrors DriverStage (DriverStage.scala:45): PREPROCESSED
(read + optional feature summarization + normalization) -> TRAINED (the
warm-started sweep) -> VALIDATED (metric maps + selection). Constrained
coefficients (the legacy ``constraintMap``) map to ``--coefficient-bounds``,
solved by the bound-constrained L-BFGS.

Usage:
    python -m photon_tpu.cli.glm --train data.avro --task LOGISTIC_REGRESSION \
        --lambdas 10,1,0.1 --validate val.avro --output-dir out/
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="photon glm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--train", required=True, help="training data file/dir")
    p.add_argument("--validate", help="validation data file/dir")
    p.add_argument("--format", default="avro", choices=("avro", "libsvm"))
    p.add_argument("--task", required=True,
                   help="LINEAR_REGRESSION | LOGISTIC_REGRESSION | "
                        "POISSON_REGRESSION | SMOOTHED_HINGE_LOSS_LINEAR_SVM")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--regularization", default="L2",
                   choices=("NONE", "L1", "L2", "ELASTIC_NET"))
    p.add_argument("--lambdas", default="1.0",
                   help="comma-separated regularization weights")
    p.add_argument("--alpha", type=float, default=0.5,
                   help="elastic-net L1 fraction")
    p.add_argument("--optimizer", default="LBFGS", choices=("LBFGS", "TRON"))
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization", default="NONE",
                   help="NONE | SCALE_WITH_STANDARD_DEVIATION | "
                        "SCALE_WITH_MAX_MAGNITUDE | STANDARDIZATION")
    p.add_argument("--coefficient-bounds", default=None,
                   help="lower,upper box applied to every coefficient "
                        "(legacy constraintMap; uses the bound-constrained "
                        "L-BFGS)")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-feature statistics here (legacy "
                        "summarization stage)")
    p.add_argument("--model-output-mode", default="ALL",
                   choices=("ALL", "BEST", "NONE"))
    p.add_argument("--log-file", default=None)
    p.add_argument("--verbose", action="store_true")
    return p.parse_args(argv)


# Legacy metric-map families per task (Evaluation.scala:64-110).
_SELECTION_KEY = {
    "LOGISTIC_REGRESSION": "AUC",
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": "AUC",
    "LINEAR_REGRESSION": "RMSE",
    "POISSON_REGRESSION": "POISSON_LOSS",
}
_METRICS = {
    "LINEAR_REGRESSION": ["MAE", "MSE", "RMSE"],
    "LOGISTIC_REGRESSION": [
        "AUC", "AUPR", "PEAK_F1", "LOGISTIC_LOSS", "F1=0.5", "PRECISION=0.5",
        "RECALL=0.5", "ACCURACY=0.5",
    ],
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": ["AUC", "AUPR", "PEAK_F1"],
    "POISSON_REGRESSION": ["POISSON_LOSS", "MAE", "MSE", "RMSE"],
}


def main(argv=None) -> int:
    args = _parse_args(argv)
    t_start = time.time()

    from photon_tpu.cli.common import cli_logging
    from photon_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    log = logging.getLogger("photon.glm")
    with cli_logging(args.verbose, args.log_file):
        return _run(args, log, t_start)


def _run(args, log, t_start) -> int:
    import jax.numpy as jnp
    import numpy as np

    from photon_tpu import optim
    from photon_tpu.algorithm.problems import (
        GLMOptimizationConfiguration,
        GLMOptimizationProblem,
    )
    from photon_tpu.cli.common import is_coordinator
    from photon_tpu.data.libsvm import read_libsvm
    from photon_tpu.evaluation.suite import make_suite
    from photon_tpu.io.avro_data import read_training_examples
    from photon_tpu.io.model_io import save_feature_stats, save_game_model
    from photon_tpu.models.game import FixedEffectModel, GameModel
    from photon_tpu.ops.normalization import (
        NormalizationType,
        build_normalization_context,
    )
    from photon_tpu.stat import FeatureDataStatistics
    from photon_tpu.types import TaskType
    from photon_tpu import obs

    task = TaskType(args.task.upper())
    task_name = task.name
    lambdas = sorted(
        (float(s) for s in args.lambdas.split(",") if s.strip()),
        reverse=True,  # descending: each model warm-starts the next
    )
    if not lambdas:
        raise ValueError("--lambdas is empty")
    os.makedirs(args.output_dir, exist_ok=True)

    # ---- stage PREPROCESSED (Driver.scala preprocess) --------------------
    with obs.logged_span("preprocess", log):
        if args.format == "libsvm":
            # -1/+1 -> 0/1 label mapping is a BINARY convention; regression
            # labels legitimately go negative and must pass through.
            binary = task in (
                TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            )
            train_batch = read_libsvm(
                args.train, binary_labels_to01=binary)
            imap = None
            val_batch = (
                read_libsvm(
                    args.validate,
                    num_features=train_batch.num_features - 1,
                    binary_labels_to01=binary,
                )
                if args.validate else None
            )
            intercept = train_batch.num_features - 1
        else:
            train_game, imap = read_training_examples(args.train)
            train_batch = train_game.shard_batch("features")
            val_batch = None
            if args.validate:
                val_game, _ = read_training_examples(
                    args.validate, index_map=imap)
                val_batch = val_game.shard_batch("features")
            intercept = imap.intercept_index

        norm = None
        norm_type = NormalizationType(args.normalization.upper())
        stats = None
        if (norm_type != NormalizationType.NONE
                or args.summarization_output_dir):
            stats = FeatureDataStatistics.from_features(
                train_batch.features,
                np.asarray(train_batch.weights),
                intercept_index=intercept,
            )
        if args.summarization_output_dir and is_coordinator():
            if imap is None:
                log.warning(
                    "summarization skipped: libsvm input has no feature "
                    "names (identity index)")
            else:
                save_feature_stats(
                    args.summarization_output_dir, stats, imap)
                log.info("feature stats written to %s",
                         args.summarization_output_dir)
        if norm_type != NormalizationType.NONE:
            norm = build_normalization_context(
                norm_type,
                mean=jnp.asarray(stats.mean),
                variance=jnp.asarray(stats.variance),
                min_=jnp.asarray(stats.min),
                max_=jnp.asarray(stats.max),
                intercept_index=intercept,
            )

    # ---- stage TRAINED (ModelTraining.trainGeneralizedLinearModel) -------
    box = None
    if args.coefficient_bounds:
        lo, hi = (float(x) for x in args.coefficient_bounds.split(","))
        d = train_batch.num_features
        box = (jnp.full(d, lo, train_batch.labels.dtype),
               jnp.full(d, hi, train_batch.labels.dtype))
    reg_type = optim.RegularizationType(args.regularization.upper())
    use_tron = args.optimizer == "TRON"
    if use_tron and box is not None:
        # TRON handles the box by projecting after each accepted step,
        # which can terminate at non-KKT points on bound-active problems;
        # the gradient-projection LBFGSB solver is the correct tool, so
        # bounded configs are routed there regardless of --optimizer.
        log.warning(
            "--coefficient-bounds with --optimizer TRON: routing to the "
            "bound-constrained L-BFGS-B solver (TRON's projection-after-"
            "step semantics can stall at non-KKT points)")
        use_tron = False
    opt_cfg = (
        optim.OptimizerConfig.tron(max_iterations=args.max_iterations)
        if use_tron
        else optim.OptimizerConfig.lbfgs(
            tolerance=args.tolerance, max_iterations=args.max_iterations,
            box_constraints=box)
    )

    models: list[tuple[float, object]] = []
    with obs.logged_span("train lambda sweep", log):
        prev = None
        for lam in lambdas:
            cfg = GLMOptimizationConfiguration(
                optimizer=opt_cfg,
                regularization=optim.RegularizationContext(
                    reg_type,
                    alpha=(
                        args.alpha
                        if reg_type == optim.RegularizationType.ELASTIC_NET
                        else None
                    ),
                ),
                regularization_weight=lam,
            )
            kwargs = {} if norm is None else {"normalization": norm}
            problem = GLMOptimizationProblem(
                task, cfg, intercept_index=intercept, **kwargs,
            )
            solution = problem.run(train_batch, prev)
            prev = solution.model.coefficients  # warm start (ModelTraining)
            models.append((lam, solution.model))
            log.info("lambda %g trained (%d iterations)", lam,
                     int(solution.result.iterations))

    # ---- stage VALIDATED (Evaluation.evaluate + ModelSelection) ----------
    metrics_by_lambda: dict[str, dict[str, float]] = {}
    best_lambda = lambdas[0]
    if val_batch is not None:
        with obs.logged_span("validate", log):
            suite = make_suite(
                _METRICS[task_name],
                val_batch.labels,
                offsets=val_batch.offsets,
                weights=val_batch.weights,
                dtype=val_batch.labels.dtype,
            )
            key = _SELECTION_KEY[task_name]
            best_val = None
            for lam, model in models:
                scores = model.coefficients.compute_score(
                    val_batch.features)
                res = suite.evaluate(scores)
                metrics_by_lambda[repr(lam)] = res.evaluations
                v = res.evaluations[key]
                better = (
                    best_val is None
                    or (v > best_val if key == "AUC" else v < best_val)
                )
                if better:
                    best_val, best_lambda = v, lam
            log.info("best lambda %g by %s = %g", best_lambda, key, best_val)

    # ---- outputs ---------------------------------------------------------
    if is_coordinator():
        from photon_tpu.data.index_map import IndexMap

        save_map = imap
        if save_map is None:  # libsvm: identity-named features + intercept
            save_map = IndexMap.identity(
                train_batch.num_features - 1, add_intercept=True)

        def save(lam, model, sub):
            gm = GameModel({"global": FixedEffectModel(model, "features")})
            save_game_model(
                gm, os.path.join(args.output_dir, sub),
                {"features": save_map}, task=task,
            )

        if args.model_output_mode == "ALL":
            for lam, model in models:
                save(lam, model, f"models/lambda={lam:g}")
        if args.model_output_mode in ("ALL", "BEST"):
            best_model = dict(models)[best_lambda]
            save(best_lambda, best_model, "best-model")
        summary = {
            "task": task_name,
            "lambdas": lambdas,
            "best_lambda": best_lambda,
            "metrics": metrics_by_lambda,
            "stages": ["PREPROCESSED", "TRAINED"]
            + (["VALIDATED"] if val_batch is not None else []),
            "wall_clock_seconds": round(time.time() - t_start, 2),
        }
        with open(os.path.join(args.output_dir, "glm-summary.json"),
                  "w") as f:
            json.dump(summary, f, indent=2)
        print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
