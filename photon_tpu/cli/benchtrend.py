"""``photon benchtrend``: gate the bench HISTORY, not just static floors.

Five rounds of ``BENCH_r*.json`` sat unread while CI compared each run
only against frozen floors — a slow drift (or a one-round cliff like the
round-4 11x compile regression, which the floors of the day let through)
is invisible to a static threshold but obvious in the series. This tool
reads the whole ``BENCH_r*.json`` history, prints a per-metric trend
table, and exits nonzero when the LATEST round regresses beyond a
declared tolerance against the TRAILING BEST (the best value any prior
round achieved) — run it in CI after the bench smoke so history finally
gates.

Rules:

- A tracked metric absent from every round is skipped (the serving
  block only exists from round 6 on; old history must not fail).
- No prior round carrying the metric means nothing to gate (a newly
  added metric starts its history).
- A metric present in the PREVIOUS round but missing from the latest is
  a regression in itself — a silently dead gauge is how tracked metrics
  rot.
- Otherwise: ``higher``-is-better metrics regress when
  ``latest < best_prior / tolerance``; ``lower``-is-better when
  ``latest > best_prior * tolerance``. The default tolerance (1.5x)
  matches the bench FLOORS ratchet policy: loose enough for the noisy
  2-core CI box, tight enough that the round-4 compile cliff (11x)
  would have failed the round it happened.
- The latest round's own embedded ``regressions`` list (floor
  violations the bench measured in-run) GATES too: BENCH_r05 carried
  an ingest-floor violation yet exited 0 — a populated list now fails
  the trend check unless each entry is waived with a written reason
  (``WAIVED_REGRESSIONS`` / ``--waive PATTERN=REASON``).

Usage:
    python -m photon_tpu.cli.benchtrend [--dir .] [--json PATH]

This module is the ONE implementation (the old ``tools/bench_trend.py``
script shim was deleted): every tracked metric — including the cost
ledger's ``*_attributed_fraction`` — gates in exactly one place.

The ``MULTICHIP_r*.json`` history (the multiprocess dryrun's fleet
straggler rows, round 19+) gates here too, as a second trend table over
``MULTICHIP_TRACKED`` — rounds r01-r05 carry only the old rc/tail
capture schema and contribute nothing to the series, which is exactly
what the absent-metric rules already tolerate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# metric -> (direction, tolerance, fallback keys tried in order after
# the primary). Directions: "higher" / "lower" is better.
TRACKED: dict[str, tuple[str, float, tuple[str, ...]]] = {
    "logistic_rows_per_sec": ("higher", 1.5, ()),
    "linear_rows_per_sec": ("higher", 1.5, ()),
    "logistic_ingest_rows_per_sec_best": (
        "higher", 1.5, ("logistic_ingest_rows_per_sec",)
    ),
    "logistic_compile_seconds": ("lower", 1.5, ()),
    "logistic_e2e_seconds": ("lower", 1.5, ()),
    "logistic_warm_cache_e2e_seconds": ("lower", 1.5, ()),
    # The roofline-push ratchet (ROADMAP item 2, round 15+): the ratio
    # is measured fit wall over the static roofline bound — LOWER is
    # closer to the chip's best case, and the trailing-best gate locks
    # each round's win in (the FLOORS ceiling only caps the absolute
    # worst case; this line is what makes an improvement permanent).
    "logistic_measured_vs_roofline": ("lower", 1.5, ()),
    # Achieved HBM throughput of the standalone segment-reduce kernel
    # dispatch (bench run_kernel_micro; absent on backends the kernel
    # does not serve — an absent-from-all-history metric is skipped,
    # but once a TPU round reports it, a silent die fails the trend).
    "segment_reduce_bytes_per_sec": ("higher", 1.5, ()),
    "serving_p99_ms": ("lower", 1.5, ()),
    "serving_qps": ("higher", 1.5, ()),
    # Serve-latency roofline push (round 18+): the host-gap share of
    # the serve dispatch rows' accounted wall — what the double-
    # buffered staging pipeline exists to shrink. Bounded by 1.0, so
    # the 1.5x band is a real ratchet once the fraction lands; the
    # serial baseline (`serving_dispatch_gap_fraction_serial`) rides
    # the JSON untracked for the side-by-side.
    "serving_dispatch_gap_fraction": ("lower", 1.5, ()),
    # Achieved HBM throughput of the fused serve-score kernel at the
    # top rung (bench run_serve_kernel_micro; absent off-TPU — same
    # skip-until-first-report policy as segment_reduce_bytes_per_sec).
    "serve_kernel_bytes_per_sec": ("higher", 1.5, ()),
    # Streaming scenario (round 10+, photon_tpu.data.stream): the
    # day-over-day warm-start retrain throughput and the out-of-core
    # ingest rate — a streaming-throughput regression fails the trend
    # gate the round it happens, same policy as the serving block.
    "streaming_incremental_rows_per_sec": ("higher", 1.5, ()),
    "streaming_ingest_rows_per_sec": ("higher", 1.5, ()),
    # Pilot control loop (round 11+, photon_tpu.pilot): staleness is
    # shard-landed -> model-serving seconds for the multi-day replay,
    # and the promotion count is the "did the loop keep promoting"
    # dead-man switch — a pilot that silently stops promoting, or whose
    # data-to-serving latency regresses >1.5x, fails the trend gate the
    # round it happens.
    "pilot_staleness_seconds": ("lower", 1.5, ()),
    "pilot_promotions": ("higher", 1.5, ()),
    # Cost-ledger attribution (round 12+, photon_tpu.obs.ledger): the
    # fraction of the measured steady-state fit wall attributed to
    # named (coordinate, phase, program) rows. Tracked HERE and only
    # here (tools/bench_trend.py was deleted for exactly this reason):
    # a ledger that silently starts naming less of the wall regresses
    # the round it happens. Tight tolerance — the fraction is bounded
    # by 1.0, so a 1.5x ratchet could never fire.
    "logistic_attributed_fraction": ("higher", 1.1, ()),
    "linear_attributed_fraction": ("higher", 1.1, ()),
    # HBM admission join (round 16+, photon_tpu.analysis.memory): the
    # MEASURED resident watermarks the ledger booked for the fused fit's
    # slab set and the serving tables — the tier-4 oracle predicts both
    # statically and bench gates the predicted/measured ratio in-run;
    # tracking the measured bytes here makes residency growth itself
    # (a model that quietly starts needing more HBM at the same
    # workload) fail the trend gate the round it happens.
    "fused_fit_peak_hbm_bytes": ("lower", 1.5, ()),
    "serving_peak_hbm_bytes": ("lower", 1.5, ()),
    # Mixed-precision parity (round 17+, tier-5 numerics): the measured
    # max relative coefficient error of the bf16 fused fit vs the f32
    # reference, per GLM family (bench run_parity). The fixed per-family
    # tolerances live in tests/test_precision.py and PERFORMANCE.md —
    # this line gates the TREND underneath them, so a parity gap that
    # quietly widens (new cast, changed solver routing) fails the round
    # it moves, long before it reaches the fixed ceiling. Lower is
    # better; 1.5x matches the tier-5 NUMERICS_AUDIT budget band.
    "parity_gap_linear": ("lower", 1.5, ()),
    "parity_gap_logistic": ("lower", 1.5, ()),
    "parity_gap_poisson": ("lower", 1.5, ()),
    "parity_gap_smoothed_hinge": ("lower", 1.5, ()),
}

# The MULTICHIP_r*.json series (round 19+, photon_tpu.obs.fleet): the
# multiprocess dryrun's straggler report, gated as its own trend table.
# Rounds r01-r05 predate the fleet layer and carry only rc/tail capture
# blobs — no tracked key appears in them, so the series starts the
# round the gauges first land (the absent-from-all-history skip and the
# new-metric rule both tolerate the old schema by construction; the
# dead-gauge rule arms only once a round has reported). Both gauges are
# bounded small numbers, so the tolerances are absolute-ish bands, not
# throughput ratios: skew is seconds of max-min attributed dispatch
# wall across ranks, fraction is the share of the fleet's rank-seconds
# spent waiting at the barrier.
MULTICHIP_TRACKED: dict[str, tuple[str, float, tuple[str, ...]]] = {
    "multichip_straggler_skew_seconds": (
        "lower", 3.0, ("straggler_skew_seconds",)
    ),
    "multichip_collective_fraction": (
        "lower", 3.0, ("collective_fraction",)
    ),
    # Round 20+: the dryrun's merged wall clock (fallback reaches into
    # the nested report for rows written before the flat gauge landed —
    # fallback keys may be dotted paths), the hosts-reporting count, and
    # the static collective count the tier-6 census attached
    # (fleet.crosscheck_collective_census). Hosts-reporting gates at
    # 1.0x: ANY drop from the trailing best means a rank stopped
    # shipping bundles — the fleet-side signature of the deadlock the
    # --spmd collective-order rule proves against statically (CI pins
    # the dryrun at 2 processes; an intentional fleet resize is a
    # rebaseline, not noise). Collective count gates one-sided on
    # growth: a new collective in the dryrun program is a new fleet
    # barrier and should arrive with a contract change, not silently.
    "multichip_wall_seconds": (
        "lower", 3.0, ("report.wall_seconds",)
    ),
    "multichip_hosts_reporting": (
        "higher", 1.0, ("bundles",)
    ),
    "multichip_collective_count": (
        "lower", 1.0, ("report.collective_census.count",)
    ),
}

# Waivers for BENCH-REPORTED regressions (the `regressions` list a
# bench run embeds in its own output line). A populated list in the
# LATEST round fails the trend gate — BENCH_r05 carried
# `ingest_rows_per_sec 510028 < 1000000` yet the run exited 0 and the
# entry sat unread for two rounds, which is exactly the
# advisory-not-gating rot this tool exists to kill. Waivers are
# SUBSTRING patterns with a REQUIRED written reason (the same
# reasoned-suppression convention every analysis tier uses); matched
# entries render as `waived:` rows instead of failing. `--waive
# PATTERN=reason` adds run-local ones.
WAIVED_REGRESSIONS: dict[str, str] = {
    "ingest_rows_per_sec 510028 < 1000000": (
        "re-baselined in round 13: the 1.0e6 floor was calibrated on "
        "the round-3 container; rounds 4-5 measured 400-510k on the "
        "CI-class 2-core box, so bench FLOORS now ratchets ~1.5x off "
        "the round-5 best (3.4e5) — justification in CHANGES.md"
    ),
}


def load_round(path: str) -> dict | None:
    """One round's bench line. Round-capture files wrap the line under
    ``parsed`` (next to cmd/rc/tail); a raw bench output line is taken
    as-is. Unparseable files are reported as None, never a crash — a
    corrupt capture must not take the trend gate down with it."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc if isinstance(doc, dict) else None


def load_series(
    dirpath: str, pattern: str, strip_prefix: str
) -> tuple[list[tuple[str, dict]], list[str]]:
    """Ordered (label, parsed) rounds for one history glob, plus the
    labels of files that would not parse (reported, never fatal)."""
    rounds: list[tuple[str, dict]] = []
    skipped: list[str] = []
    for p in sorted(glob.glob(os.path.join(dirpath, pattern))):
        parsed = load_round(p)
        label = os.path.splitext(os.path.basename(p))[0].replace(
            strip_prefix, ""
        )
        if parsed is None:
            skipped.append(label)
            continue
        rounds.append((label, parsed))
    return rounds, skipped


def metric_value(
    parsed: dict,
    name: str,
    tracked: dict[str, tuple[str, float, tuple[str, ...]]] | None = None,
) -> float | None:
    _, _, fallbacks = (tracked or TRACKED)[name]
    for key in (name, *fallbacks):
        # Fallback keys may be dotted paths ("report.wall_seconds") that
        # walk nested dicts — multichip rows carry the merged fleet
        # report inline, and its gauges predate the flat top-level ones.
        v: object = parsed
        for part in key.split("."):
            v = v.get(part) if isinstance(v, dict) else None
            if v is None:
                break
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None


def analyze(
    rounds: list[tuple[str, dict]],
    waivers: dict[str, str] | None = None,
    tracked: dict[str, tuple[str, float, tuple[str, ...]]] | None = None,
) -> dict:
    """Trend rows + regressions for an ordered (label, parsed) series.

    ``waivers`` (pattern -> reason) extends ``WAIVED_REGRESSIONS`` for
    the bench-reported gate below. ``tracked`` selects the gauge table
    (default the bench ``TRACKED`` set; the multichip pass hands in
    ``MULTICHIP_TRACKED``)."""
    tracked = TRACKED if tracked is None else tracked
    out: dict = {"rounds": [label for label, _ in rounds], "metrics": {},
                 "regressions": [], "waived": []}
    if not rounds:
        out["regressions"].append("no bench history found")
        return out
    latest_label = rounds[-1][0]
    # Bench-reported regressions GATE: the latest round's own
    # `regressions` list (floor violations the bench measured in-run)
    # fails the trend check unless each entry carries a reasoned
    # waiver — an exit-0 bench with a populated list is no longer
    # advisory.
    all_waivers = dict(WAIVED_REGRESSIONS)
    all_waivers.update(waivers or {})
    embedded = rounds[-1][1].get("regressions")
    if isinstance(embedded, list):
        for entry in embedded:
            entry = str(entry)
            reason = next(
                (r for pat, r in all_waivers.items() if pat in entry),
                None,
            )
            if reason is not None:
                out["waived"].append({"entry": entry, "reason": reason})
            else:
                out["regressions"].append(
                    f"{latest_label} bench-reported: {entry}"
                )
    for name, (direction, tol, _) in tracked.items():
        series = [
            metric_value(parsed, name, tracked) for _, parsed in rounds
        ]
        if all(v is None for v in series):
            continue
        prior = [v for v in series[:-1] if v is not None]
        latest = series[-1]
        best_prior = (
            None if not prior
            else (max(prior) if direction == "higher" else min(prior))
        )
        status = "ok"
        if latest is None:
            if series[:-1] and series[-2] is not None:
                status = "missing"
                out["regressions"].append(
                    f"{name}: tracked metric present in the previous "
                    f"round but missing from {latest_label} (dead gauge)"
                )
            else:
                status = "n/a"
        elif best_prior is None:
            status = "new"
        elif direction == "higher" and latest < best_prior / tol:
            status = "REGRESSED"
            out["regressions"].append(
                f"{name}: {latest:g} < trailing best {best_prior:g} "
                f"/ {tol:g} (higher is better)"
            )
        elif direction == "lower" and latest > best_prior * tol:
            status = "REGRESSED"
            out["regressions"].append(
                f"{name}: {latest:g} > trailing best {best_prior:g} "
                f"x {tol:g} (lower is better)"
            )
        out["metrics"][name] = {
            "direction": direction,
            "tolerance": tol,
            "series": series,
            "trailing_best": best_prior,
            "latest": latest,
            "status": status,
        }
    return out


def render_table(report: dict) -> str:
    labels = report["rounds"]
    head = ["metric", "dir", *labels, "best<", "status"]
    rows = [head]
    for name, m in report["metrics"].items():
        rows.append([
            name,
            m["direction"][0] + "^" if m["direction"] == "higher"
            else m["direction"][0] + "v",
            *[
                "-" if v is None else f"{v:g}" for v in m["series"]
            ],
            "-" if m["trailing_best"] is None
            else f"{m['trailing_best']:g}",
            m["status"],
        ])
    widths = [max(len(r[i]) for r in rows) for i in range(len(head))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in rows
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon benchtrend", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--dir", default=".",
                        help="directory holding the BENCH_r*.json series")
    parser.add_argument("--pattern", default="BENCH_r*.json",
                        help="history glob (lexicographic order = "
                             "round order)")
    parser.add_argument("--multichip-pattern",
                        default="MULTICHIP_r*.json",
                        help="multichip straggler history glob (same "
                             "--dir; rounds r01-r05 predate the fleet "
                             "gauges and are tolerated as empty)")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write the machine-readable trend "
                             "report to PATH")
    parser.add_argument("--waive", action="append", default=[],
                        metavar="PATTERN=REASON",
                        help="waive a bench-reported regression whose "
                             "text contains PATTERN (a reason is "
                             "REQUIRED — same convention as analysis-"
                             "tier suppressions); repeatable")
    args = parser.parse_args(argv)

    waivers: dict[str, str] = {}
    for spec in args.waive:
        pattern, sep, reason = spec.partition("=")
        if not sep or not pattern or not reason.strip():
            parser.error(
                f"--waive {spec!r}: use PATTERN=REASON (the reason is "
                "required)")
        waivers[pattern] = reason.strip()

    rounds, skipped = load_series(args.dir, args.pattern, "BENCH_")

    report = analyze(rounds, waivers=waivers)
    if skipped:
        report["skipped_unparseable"] = skipped
    print(render_table(report))
    for w in report.get("waived", ()):
        print(f"waived: {w['entry']} ({w['reason']})")

    # Second pass: the multichip straggler series. Absent history is
    # fine (single-host checkouts carry no MULTICHIP_r*.json) — the
    # gate only arms once the fleet dryrun has committed a row.
    mc_rounds, mc_skipped = load_series(
        args.dir, args.multichip_pattern, "MULTICHIP_"
    )
    mc_report: dict | None = None
    if mc_rounds:
        mc_report = analyze(
            mc_rounds, waivers=waivers, tracked=MULTICHIP_TRACKED
        )
        if mc_skipped:
            mc_report["skipped_unparseable"] = mc_skipped
        report["multichip"] = mc_report
        if mc_report["metrics"]:
            print("-- multichip (MULTICHIP_r*.json) --")
            print(render_table(mc_report))
        report["regressions"].extend(
            f"multichip: {reg}" for reg in mc_report["regressions"]
        )

    for reg in report["regressions"]:
        print(f"REGRESSION: {reg}")
    if not report["regressions"]:
        print(
            f"trend OK across {len(rounds)} bench + "
            f"{len(mc_rounds)} multichip round(s)"
        )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
