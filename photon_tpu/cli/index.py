"""``photon index``: build per-shard feature index maps + name-term lists.

TPU-native counterpart of the two vocab-builder CLIs:
- FeatureIndexingDriver (photon-client index/FeatureIndexingDriver.scala:42):
  scans input Avro data and builds one name->index store per feature shard
  (partitioned PalDB there; a JSON index map here — SURVEY §2.2 notes the
  off-heap gymnastics are unnecessary without the JVM).
- NameAndTermFeatureBagsDriver (data/avro/NameAndTermFeatureBagsDriver.scala
  :32): extracts the distinct (name, term) set per feature bag to text files
  (the ``feature-lists`` whitelist format: one "name<TAB>term" per line).

A shard unions one or more feature-bag record fields
(FeatureShardConfiguration.featureBags): ``--shards global=features`` or
``--shards user=userFeatures,features``. Outputs per shard:
``<out>/<shard>.index.json`` (IndexMap.save) and ``<out>/<shard>`` (the
whitelist, named like the reference's feature-lists files).

Usage:
    python -m photon_tpu.cli.index --input data.avro --output vocab/ \
        [--shards global=features user=userFeatures] [--no-intercept]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys


def parse_shard_spec(specs: list[str] | None) -> dict[str, list[str]]:
    """["global=features", "user=userFeatures,features"] -> shard -> bags."""
    if not specs:
        return {"features": ["features"]}
    out: dict[str, list[str]] = {}
    for spec in specs:
        if "=" not in spec:
            raise ValueError(
                f"bad shard spec {spec!r}; expected shard=bag[,bag...]")
        shard, bags = spec.split("=", 1)
        out[shard.strip()] = [b.strip() for b in bags.split(",") if b.strip()]
    return out


def build_shard_vocabularies(
    records, shard_bags: dict[str, list[str]]
) -> dict[str, list[tuple[str, str]]]:
    """Distinct (name, term) pairs per shard, sorted — the NameAndTerm set
    (NameAndTermFeatureBagsDriver semantics). ``records`` may be any
    iterable (including a streaming block decoder): one pass collects every
    shard's set, so peak memory is the vocabularies themselves, never a
    record list."""
    seen: dict[str, set] = {shard: set() for shard in shard_bags}
    for rec in records:
        for shard, bags in shard_bags.items():
            ks = seen[shard]
            for bag in bags:
                for ntv in rec.get(bag) or ():
                    ks.add((ntv["name"], ntv["term"]))
    return {shard: sorted(ks) for shard, ks in seen.items()}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon index", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--input", required=True, nargs="+",
                        help="Avro data files/dirs to scan")
    parser.add_argument("--output", required=True,
                        help="output directory for index maps + whitelists")
    parser.add_argument("--shards", nargs="*", default=None,
                        help="shard=bag[,bag...] specs; default "
                             "'features=features'")
    parser.add_argument("--no-intercept", action="store_true",
                        help="do not reserve an intercept slot")
    parser.add_argument("--hashed", action="store_true",
                        help="write npz-backed hashed index maps (the "
                             "PalDB analog for multi-million-feature "
                             "vocabularies)")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING)
    log = logging.getLogger("photon.index")

    from photon_tpu.data.index_map import HashedIndexMap, IndexMap
    from photon_tpu.io import avro
    from photon_tpu.types import make_feature_key

    shard_bags = parse_shard_spec(args.shards)

    def stream():
        found = False
        for path in args.input:
            for rec in avro.iter_container_dir(path):
                found = True
                yield rec
        if not found:
            raise ValueError(f"no records in {args.input}")

    vocabularies = build_shard_vocabularies(stream(), shard_bags)
    os.makedirs(args.output, exist_ok=True)
    summary = {}
    for shard, pairs in vocabularies.items():
        keys = [make_feature_key(n, t) for n, t in pairs]
        if args.hashed:
            imap = HashedIndexMap.from_feature_names(
                keys, add_intercept=not args.no_intercept)
            imap.save(os.path.join(args.output, f"{shard}.index.npz"))
        else:
            imap = IndexMap.from_feature_names(
                keys, add_intercept=not args.no_intercept)
            imap.save(os.path.join(args.output, f"{shard}.index.json"))
        # Reference feature-lists format: "name<TAB>term" per line.
        with open(os.path.join(args.output, shard), "w") as f:
            for n, t in pairs:
                f.write(f"{n}\t{t}\n")
        summary[shard] = len(imap)
        log.info("shard %s: %d features", shard, len(imap))
    print(json.dumps({"output": args.output, "shards": summary}))
    return 0


def load_index_maps(directory: str) -> dict[str, "object"]:
    """Load every ``<shard>.index.json`` / ``<shard>.index.npz`` under a
    ``photon index`` output dir (the train/score-side counterpart of
    PalDBIndexMapLoader; npz maps decompress into compact numpy arrays —
    tens of bytes per feature instead of per-entry Python objects)."""
    from photon_tpu.data.index_map import HashedIndexMap, IndexMap

    out = {}
    for name in sorted(os.listdir(directory)):
        if name.endswith(".index.json"):
            out[name[: -len(".index.json")]] = IndexMap.load(
                os.path.join(directory, name)
            )
        elif name.endswith(".index.npz"):
            out[name[: -len(".index.npz")]] = HashedIndexMap.load(
                os.path.join(directory, name)
            )
    if not out:
        raise ValueError(f"no *.index.json / *.index.npz files under "
                         f"{directory}")
    return out


if __name__ == "__main__":
    sys.exit(main())
