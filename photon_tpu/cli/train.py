"""``photon train``: end-to-end GAME training driver.

TPU-native counterpart of GameTrainingDriver (photon-client
cli/game/training/GameTrainingDriver.scala:54, run :363-516): read data ->
feature index map -> warm-start model load -> feature stats -> normalization
contexts -> coordinate configs x lambda grid -> GameEstimator.fit ->
model selection -> save models (Avro layout + native checkpoint + eval
summary).

Usage:
    python -m photon_tpu.cli.train --config train.yaml [--backend tpu|cpu]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon train", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--config", required=True,
                        help="YAML/JSON training configuration")
    parser.add_argument("--backend", default=None,
                        help="JAX platform override (tpu, cpu, axon, ...)")
    parser.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                        help="crash-safe training checkpoints: commit "
                             "an atomic recovery point (model npz + "
                             "manifest) after every outer CD iteration "
                             "(RESILIENCE.md)")
    parser.add_argument("--resume", default=None, metavar="DIR",
                        help="resume an interrupted run from DIR's "
                             "checkpoint (implies --checkpoint-dir DIR; "
                             "the manifest's config static key must "
                             "match this run's configuration)")
    parser.add_argument("--stream-dir", default=None, metavar="DIR",
                        help="fault-tolerant out-of-core streaming "
                             "ingest: train from DIR's Avro shards in "
                             "bounded-memory windows with per-shard "
                             "integrity checks, transient-I/O retry, "
                             "and a resumable cursor — instead of the "
                             "config's whole-dataset train_path load "
                             "(DATA.md)")
    parser.add_argument("--resume-ingest", action="store_true",
                        help="resume a killed streaming ingest from its "
                             "committed cursor (window spills are "
                             "reloaded; the resumed dataset is byte-"
                             "identical to the uninterrupted run). "
                             "Requires --stream-dir")
    parser.add_argument("--stream-window", type=int, default=1,
                        metavar="N",
                        help="shards per streaming window (decode of "
                             "window k+1 overlaps window k's device "
                             "transfer; default 1 = cursor commits at "
                             "every shard boundary)")
    parser.add_argument("--max-bad-shards", type=int, default=0,
                        metavar="N",
                        help="quarantine budget: tolerate up to N "
                             "corrupt shards (skip + count + surface "
                             "ingested_fraction; default 0 = abort on "
                             "the first corrupt shard)")
    parser.add_argument("--max-bad-fraction", type=float, default=0.0,
                        metavar="F",
                        help="quarantine budget as a fraction of the "
                             "shard count (combined with "
                             "--max-bad-shards via max)")
    parser.add_argument("--init-model", default=None, metavar="PATH",
                        help="day-over-day warm start: load yesterday's "
                             "GameModel (a native checkpoint .npz or an "
                             "Avro model directory) as the initial "
                             "model; its digest is recorded in the "
                             "training checkpoint manifest so crash "
                             "recovery resumes ingest-then-descent "
                             "end to end")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--log-file", default=None,
                        help="also write logs to this file (PhotonLogger "
                             "equivalent, util/PhotonLogger.scala:34)")
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="enable runtime telemetry (photon_tpu.obs) "
                             "and write the JSONL stream to PATH; the "
                             "snapshot also lands in "
                             "training-summary.json (OBSERVABILITY.md). "
                             "Resets the process's telemetry stream: "
                             "the run owns its stream end to end")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="write the merged Chrome-trace/Perfetto "
                             "timeline (host spans, counter tracks, "
                             "resilience events) to PATH at the end of "
                             "the run (OBSERVABILITY.md)")
    parser.add_argument("--flight-dir", default=None, metavar="DIR",
                        help="crash flight recorder destination "
                             "(default: the config's output_dir): "
                             "flight-<pid>.json is dumped there when "
                             "training is interrupted by SIGINT/SIGTERM, "
                             "dies on an unhandled exception, or hits a "
                             "crash-kind injected fault")
    parser.add_argument("--no-flight", action="store_true",
                        help="disable the crash flight recorder")
    parser.add_argument("--monitor-port", type=int, default=None,
                        metavar="PORT",
                        help="serve /metrics (Prometheus text "
                             "exposition from the live metrics "
                             "registry), /healthz, and /readyz on this "
                             "port for the whole run (0 = ephemeral; "
                             "multi-process runs bind port + "
                             "process_index so ranks sharing a host "
                             "never collide). /readyz flips 200 once "
                             "the training datasets are prepared "
                             "(OBSERVABILITY.md §live monitoring)")
    parser.add_argument("--distributed", action="store_true",
                        help="arm distributed observability "
                             "(obs/fleet.py): telemetry + the cost "
                             "ledger record for the whole run and this "
                             "rank commits an atomic obs bundle into "
                             "the shared fleet dir at exit — merge the "
                             "ranks with python -m "
                             "photon_tpu.cli.fleetview "
                             "(OBSERVABILITY.md §distributed "
                             "observability). Single-process runs ship "
                             "a 1-rank fleet")
    parser.add_argument("--fleet-dir", default=None, metavar="DIR",
                        help="shared run directory for --distributed "
                             "bundles (default: $PHOTON_FLEET_DIR, "
                             "else <output_dir>/fleet)")
    args = parser.parse_args(argv)
    if (args.resume and args.checkpoint_dir
            and os.path.abspath(args.resume)
            != os.path.abspath(args.checkpoint_dir)):
        # A divergent pair would load the manifest from --resume but look
        # up config-final/best artifacts in --checkpoint-dir, silently
        # resuming without them.
        parser.error(
            "--resume and --checkpoint-dir point at different "
            f"directories ({args.resume} vs {args.checkpoint_dir}); "
            "--resume DIR already implies --checkpoint-dir DIR")
    if args.resume_ingest and not args.stream_dir:
        parser.error("--resume-ingest requires --stream-dir")

    if args.backend:
        os.environ["JAX_PLATFORMS"] = args.backend
    from photon_tpu.cli.common import cli_logging, maybe_init_distributed

    with cli_logging(args.verbose, args.log_file):
        from photon_tpu.resilience import faults
        from photon_tpu.utils import enable_compilation_cache

        # Chaos harness: PHOTON_TPU_FAULT_PLAN arms a seeded FaultPlan
        # inside this process (no-op when unset) — how the chaos-smoke
        # CI and the kill/resume tests inject faults into a real
        # training subprocess deterministically.
        faults.arm_from_env()
        enable_compilation_cache()  # persistent XLA cache: warm runs skip compiles
        maybe_init_distributed()
        from photon_tpu import obs

        was_enabled = obs.enabled()
        from photon_tpu.obs import ledger

        ledger_was_enabled = ledger.enabled()
        if args.distributed:
            # The fleet bundle wants the full attribution surface:
            # spans + events (telemetry) AND the PR 12 ledger rows the
            # straggler report rolls up. Both are audited host-only
            # layers (the tier-2 telemetry/ledger/fleet-obs contracts).
            ledger.enable()
        if args.telemetry or args.trace or args.distributed:
            # DESTRUCTIVE by design: the --telemetry/--trace run owns
            # the process's telemetry stream (a JSONL mixing a prior
            # session's records into this run's artifact would be
            # worse); only the enabled flag is restored afterwards —
            # in-process callers who need their accumulated records
            # must snapshot before invoking main(). --trace enables
            # too: an exported timeline from rings nothing ever wrote
            # to would be an empty trace.json, silently.
            obs.reset()
            obs.enable()
        if args.distributed:
            # obs.reset() above dropped fleet state too — including the
            # init clock sample maybe_init_distributed() took. Re-arm
            # the init half of the handshake NOW, or the commit-time
            # skew bound pairs a sample against itself and degrades to
            # spread-only. And pin the run id every rank will stamp:
            # explicit set_run_id / PHOTON_RUN_ID wins; otherwise
            # derive it from the shared fleet dir path, identical on
            # every rank by construction.
            from photon_tpu.obs import fleet

            fleet.mark_init()
            if fleet.run_id() is None:
                try:
                    resolved = (
                        args.fleet_dir
                        or os.environ.get("PHOTON_FLEET_DIR")
                    )
                    if not resolved:
                        from photon_tpu.cli.config import TrainingConfig

                        resolved = os.path.join(
                            TrainingConfig.load(args.config).output_dir,
                            "fleet",
                        )
                    import zlib

                    digest = zlib.crc32(
                        os.path.abspath(resolved).encode("utf-8"))
                    fleet.set_run_id(f"train-{digest & 0xffffffff:08x}")
                except Exception:
                    # A bad config fails loudly inside _run; bundles
                    # from the doomed run just ship without a run id.
                    pass
        from photon_tpu.obs import flight

        # Live monitoring (obs/monitor.py): /healthz answers as soon as
        # the exporter binds; /readyz follows the registry's
        # train_datasets_prepared gauge (set by _run after prepare) —
        # a long training run is observable by PULLING, not only from
        # its end-of-run summary/JSONL artifacts.
        mon = None
        if args.monitor_port is not None:
            from photon_tpu.obs import monitor

            def _train_ready():
                gauges = obs.REGISTRY.snapshot()["gauges"]
                prepared = gauges.get("train_datasets_prepared", 0) >= 1
                return prepared, {"datasets_prepared": prepared}

            from photon_tpu.obs import fleet

            # Rank-offset the bind (base + process_index): several
            # ranks sharing one host must not collide on one
            # --monitor-port value.
            mon = monitor.MonitorServer(
                fleet.resolve_monitor_port(args.monitor_port),
                readiness=_train_ready,
            ).start()
            logging.getLogger("photon.train").info(
                "monitor endpoints on port %d (requested %d, rank %d) "
                "(/metrics /healthz /readyz)", mon.port,
                args.monitor_port,
                fleet.host_identity()["process_index"])

        # _run installs the CLI's own recorder (unless --no-flight);
        # dump/uninstall below are gated on that install actually having
        # happened, so an embedding caller's ambient recorder is never
        # dumped to or torn down behind its back.
        prior_rec = flight.installed()
        try:
            return _run(args)
        except BaseException as exc:
            # The flight recorder's chained sys.excepthook never fires
            # for in-process callers (they catch up-stack): dump the
            # post-mortem at the unwind. A SystemExit is an exit code,
            # not a crash.
            if (not isinstance(exc, SystemExit)
                    and flight.installed() is not prior_rec):
                flight.dump(f"exception:{type(exc).__name__}")
            raise
        finally:
            if mon is not None:
                mon.stop()
            if args.distributed:
                # Ship THIS rank's bundle before the recorder teardown
                # below (its restore path may reset the rings) — a
                # failed run still leaves its half of the fleet
                # post-mortem. The merge side (cli.fleetview) joins the
                # ranks afterwards.
                try:
                    from photon_tpu.obs import fleet

                    fleet_dir = (
                        args.fleet_dir
                        or os.environ.get("PHOTON_FLEET_DIR")
                    )
                    if not fleet_dir:
                        from photon_tpu.cli.config import TrainingConfig

                        fleet_dir = os.path.join(
                            TrainingConfig.load(args.config).output_dir,
                            "fleet",
                        )
                    out_dir = fleet.ship_bundle(fleet_dir)
                    logging.getLogger("photon.train").info(
                        "fleet bundle committed to %s", out_dir)
                except Exception:
                    logging.getLogger("photon.train").exception(
                        "failed to ship the fleet bundle")
            # Uninstall FIRST: it restores the telemetry flag to the
            # state it found at install time (inside _run), and the
            # --telemetry/--trace restore below must win over it.
            if flight.installed() is not prior_rec:
                flight.uninstall()
                if prior_rec is not None:
                    # _run's default-on install replaced an embedding
                    # caller's ambient recorder: hand it back re-armed,
                    # so the caller's post-mortem coverage survives.
                    flight.reinstall(prior_rec)
                elif (not (args.telemetry or args.trace
                           or args.distributed) and not was_enabled):
                    # The flight install was the ONLY thing recording
                    # (caller had telemetry off, asked for no exports):
                    # drop this run's records instead of leaving them
                    # to pollute the caller's next snapshot/JSONL.
                    obs.reset()
            if args.trace:
                try:
                    obs.write_chrome_trace(args.trace)
                    logging.getLogger("photon.train").info(
                        "chrome trace written to %s", args.trace)
                except Exception:
                    logging.getLogger("photon.train").exception(
                        "failed to write trace to %s", args.trace)
            if args.telemetry:
                try:
                    obs.write_jsonl(args.telemetry)
                    logging.getLogger("photon.train").info(
                        "telemetry JSONL written to %s\n%s",
                        args.telemetry, obs.summary_table(),
                    )
                except Exception:
                    # Telemetry must never mask the run's own outcome:
                    # a bad --telemetry path on a failed run would
                    # otherwise replace the real training exception.
                    logging.getLogger("photon.train").exception(
                        "failed to write telemetry to %s", args.telemetry
                    )
            if args.telemetry or args.trace or args.distributed:
                # Restore the caller's prior ENABLED FLAG (the recorded
                # stream was reset above, by design) so an in-process
                # caller that keeps telemetry on — the bench's wide-d
                # block — continues recording after we return.
                obs.TRACER.enabled = was_enabled
            if args.distributed and not ledger_was_enabled:
                ledger.disable()


def _run(args) -> int:
    log = logging.getLogger("photon.train")

    # Imports follow the backend env override.
    from photon_tpu.cli.config import TrainingConfig
    from photon_tpu.data.libsvm import read_libsvm
    from photon_tpu.data.index_map import IndexMap
    from photon_tpu.io.avro_data import read_merged, read_training_examples
    from photon_tpu.io.model_io import (
        load_game_model,
        save_checkpoint,
        save_game_model,
    )
    from photon_tpu.ops.normalization import (
        NormalizationType,
        build_normalization_context,
    )
    from photon_tpu.stat import FeatureDataStatistics
    from photon_tpu.types import TaskType

    # Section timing rides the unified telemetry layer; obs.logged_span
    # keeps the reference's Timed/PhotonLogger "begin execution" /
    # "executed in" log contract for the --log-file sink.
    from photon_tpu import obs
    from photon_tpu.obs import flight

    t_start = time.time()
    cfg = TrainingConfig.load(args.config)
    os.makedirs(cfg.output_dir, exist_ok=True)

    # Crash flight recorder (obs/flight.py): the last N seconds of
    # spans/events/metric deltas land in flight-<pid>.json when the run
    # dies. Signals stay with THIS driver's own handlers below (they
    # commit the emergency checkpoint); the interrupt path and main()'s
    # unwind call flight.dump explicitly, and crash-kind injected
    # faults dump through the faults.on_crash listener. Installing
    # enables telemetry recording (host-side only — the audited
    # zero-overhead contracts); main()'s finally uninstalls.
    recorder = None
    if not args.no_flight:
        recorder = flight.install(
            args.flight_dir or cfg.output_dir, signals=False
        )

    # ------------------------------------------------------------------
    # read data (readTrainingData :537)
    # ------------------------------------------------------------------
    def read_libsvm_game(path, index_map=None):
        """libsvm -> single-shard GameDataset + identity index map."""
        from photon_tpu.data.game_data import make_game_dataset

        # -1/+1 -> 0/1 label mapping is a BINARY convention; regression
        # labels legitimately go negative and must pass through.
        binary = cfg.task in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )
        if index_map is None:
            batch = read_libsvm(path, binary_labels_to01=binary)
            imap = IndexMap.identity(
                batch.num_features - 1, add_intercept=True
            )
        else:
            imap = index_map
            batch = read_libsvm(
                path, num_features=len(imap) - 1,
                binary_labels_to01=binary,
            )
        game = make_game_dataset(
            batch.labels,
            {"features": batch.features},
            offsets=batch.offsets,
            weights=batch.weights,
        )
        return game, imap

    # Daily-format (yyyy/MM/dd) input selection; records from every selected
    # day concatenate into one dataset (IOUtils.getInputPathsWithinDateRange).
    train_records = None
    val_records = None
    if cfg.date_range or cfg.days_range:
        if cfg.input_format != "avro":
            raise ValueError("date_range/days_range apply to avro input only")
        from photon_tpu.io import avro as avro_io
        from photon_tpu.io.paths import (
            DateRange,
            DaysRange,
            paths_for_date_range,
        )

        if cfg.date_range and cfg.days_range:
            raise ValueError("set only one of date_range / days_range")
        rng_ = (DateRange.from_string(cfg.date_range) if cfg.date_range
                else DaysRange.from_string(cfg.days_range).to_date_range())

        def read_daily(base):
            day_paths = paths_for_date_range(base, rng_)
            log.info("date range %s..%s under %s -> %d daily dir(s)",
                     rng_.start, rng_.end, base, len(day_paths))
            recs = []
            for p_ in day_paths:
                recs.extend(avro_io.read_container_dir(p_))
            return recs

        train_records = read_daily(cfg.train_path)
        if cfg.validation_path:
            val_records = read_daily(cfg.validation_path)

    prebuilt_maps = None
    if cfg.feature_index_dir:
        # Prebuilt vocab from `photon index` (the FeatureIndexingDriver /
        # PalDBIndexMapLoader path): features absent from it are dropped at
        # ingest, exactly like the reference's fixed feature maps.
        from photon_tpu.cli.index import load_index_maps

        prebuilt_maps = load_index_maps(cfg.feature_index_dir)
        log.info("loaded %d feature index map(s) from %s",
                 len(prebuilt_maps), cfg.feature_index_dir)

    prebuilt_features_map = None
    if prebuilt_maps is not None and not cfg.feature_shards:
        # Single-bag ingest reads the 'features' bag; any other shard name
        # in the vocab dir cannot be consumed here and silently training on
        # the wrong vocabulary would be worse than failing. (Multi-shard
        # configs pass the whole map dict into read_merged instead.)
        if "features" not in prebuilt_maps:
            raise ValueError(
                f"feature_index_dir {cfg.feature_index_dir!r} has no "
                f"'features' index (found: {sorted(prebuilt_maps)}); "
                "training ingest reads the 'features' bag")
        prebuilt_features_map = prebuilt_maps["features"]

    if cfg.input_format != "avro" and (
        cfg.feature_index_dir or cfg.feature_shards
    ):
        raise ValueError(
            "feature_index_dir / feature_shards apply to avro input only; "
            "libsvm data is identity-indexed single-shard "
            "(IdentityIndexMapLoader semantics)")

    multi_shard_maps = None
    stream_stats = None
    stream_work_dir = None
    if args.stream_dir:
        # ------------------------------------------------------------------
        # streaming ingest (photon_tpu.data.stream; DATA.md)
        # ------------------------------------------------------------------
        if cfg.input_format != "avro":
            raise ValueError(
                "--stream-dir streams Avro shards; set input.format to "
                "avro")
        if cfg.date_range or cfg.days_range:
            raise ValueError(
                "--stream-dir does not combine with date_range/"
                "days_range; point it at the day directory instead")
        from photon_tpu.data.stream import (
            QuarantinePolicy,
            StreamingIngest,
        )

        # Co-locate the ingest work dir (manifest/vocab/spills/cursor)
        # with the training checkpoints when crash safety is on, so one
        # directory carries the WHOLE recovery chain; else the output
        # dir.
        stream_work_dir = os.path.join(
            args.checkpoint_dir or args.resume or cfg.output_dir,
            "ingest-work")
        shard_bags = cfg.shard_bags()
        ingest = StreamingIngest(
            args.stream_dir,
            work_dir=stream_work_dir,
            feature_shards=shard_bags,
            index_maps=prebuilt_maps,
            id_tag_names=cfg.id_tags,
            id_columns=cfg.id_columns,
            input_columns=cfg.input_columns,
            add_intercept=(
                cfg.shard_intercepts() if shard_bags else True
            ),
            window_shards=args.stream_window,
            quarantine=QuarantinePolicy(
                args.max_bad_shards, args.max_bad_fraction
            ),
            resume=args.resume_ingest,
        )
        with obs.logged_span("stream ingest", log):
            train, stream_stats = ingest.run()
        log.info(
            "streamed %d row(s) from %d/%d shard(s) "
            "(ingested_fraction %.4f%s)",
            stream_stats["rows_ingested"],
            stream_stats["shards_ingested"],
            stream_stats["shards_total"],
            stream_stats["ingested_fraction"],
            f", resumed at shard {stream_stats['resumed_from_shard']}"
            if stream_stats["resumed_from_shard"] is not None else "",
        )
        if stream_stats["quarantined_paths"]:
            log.warning(
                "streaming ingest quarantined %d shard(s): %s",
                stream_stats["shards_quarantined"],
                ", ".join(stream_stats["quarantined_paths"]))
        multi_shard_maps = ingest.resolved_maps
        index_map = next(iter(multi_shard_maps.values()))
        validation = None
        if cfg.validation_path:
            if shard_bags:
                validation, _ = read_merged(
                    cfg.validation_path,
                    feature_shards=shard_bags,
                    index_maps=multi_shard_maps,
                    id_columns=cfg.id_columns,
                    id_tag_names=list(ingest.id_tag_names),
                    input_columns=cfg.input_columns,
                )
            else:
                validation, _ = read_training_examples(
                    cfg.validation_path,
                    index_map=multi_shard_maps["features"],
                    id_tag_names=list(ingest.id_tag_names),
                    input_columns=cfg.input_columns,
                )
    elif cfg.input_format == "avro" and cfg.feature_shards:
        if prebuilt_maps is not None:
            missing = sorted(set(cfg.feature_shards) - set(prebuilt_maps))
            if missing:
                raise ValueError(
                    f"feature_index_dir {cfg.feature_index_dir!r} does not "
                    f"cover shard(s) {missing}; a partially prebuilt "
                    "vocabulary would silently train those shards on a "
                    "data-derived one")
        # Multi-bag layout (AvroDataReader.readMerged): one index map and
        # one ELL matrix per configured shard.
        train, multi_shard_maps = read_merged(
            cfg.train_path,
            feature_shards=cfg.shard_bags(),
            index_maps=prebuilt_maps,
            id_columns=cfg.id_columns,
            id_tag_names=cfg.id_tags,
            input_columns=cfg.input_columns,
            add_intercept=cfg.shard_intercepts(),
            records=train_records,
        )
        index_map = next(iter(multi_shard_maps.values()))
        validation = None
        if cfg.validation_path:
            validation, _ = read_merged(
                cfg.validation_path,
                feature_shards=cfg.shard_bags(),
                index_maps=multi_shard_maps,
                id_columns=cfg.id_columns,
                id_tag_names=cfg.id_tags,
                input_columns=cfg.input_columns,
                records=val_records,
            )
    elif cfg.input_format == "avro":
        train, index_map = read_training_examples(
            cfg.train_path,
            index_map=prebuilt_features_map,
            id_tag_names=cfg.id_tags,
            input_columns=cfg.input_columns,
            records=train_records,
        )
        validation = None
        if cfg.validation_path:
            validation, _ = read_training_examples(
                cfg.validation_path,
                index_map=index_map,
                id_tag_names=cfg.id_tags,
                input_columns=cfg.input_columns,
                records=val_records,
            )
    elif cfg.input_format == "libsvm":
        train, index_map = read_libsvm_game(cfg.train_path)
        validation = None
        if cfg.validation_path:
            validation, _ = read_libsvm_game(
                cfg.validation_path, index_map=index_map
            )
    else:
        raise ValueError(f"unknown input format {cfg.input_format!r}")
    log.info("read %d train rows (%d features)",
             train.num_samples, len(index_map))

    # ------------------------------------------------------------------
    # data validation (DataValidators.sanityCheckDataFrameForTraining :433)
    # ------------------------------------------------------------------
    from photon_tpu.data.validators import sanity_check_data

    sanity_check_data(train, cfg.task, cfg.data_validation)
    if validation is not None:
        sanity_check_data(validation, cfg.task, cfg.data_validation)

    shards = sorted(train.feature_shards)
    if multi_shard_maps is not None:
        index_maps = dict(multi_shard_maps)
        intercept_indices = {
            s: m.intercept_index for s, m in multi_shard_maps.items()
            if m.intercept_index is not None
        }
    else:
        index_maps = {s: index_map for s in shards}
        intercept_indices = {}
        if index_map.intercept_index is not None:
            intercept_indices = {
                s: index_map.intercept_index for s in shards
            }

    # ------------------------------------------------------------------
    # warm start (loadGameModelFromHDFS :395-404)
    # ------------------------------------------------------------------
    initial_model = None
    init_model_digest = None
    if args.init_model:
        if cfg.warm_start_model_dir:
            raise ValueError(
                "--init-model and the config's warm_start_model_dir are "
                "both set; pass exactly one warm-start source")
        from photon_tpu.io.model_io import load_initial_model

        initial_model, init_model_digest = load_initial_model(
            args.init_model, index_maps
        )
        log.info("warm start from --init-model %s (digest %s...)",
                 args.init_model, init_model_digest[:12])
    elif cfg.warm_start_model_dir:
        initial_model, _ = load_game_model(
            cfg.warm_start_model_dir, index_maps
        )
        log.info("warm start from %s", cfg.warm_start_model_dir)
    if cfg.incremental_training and initial_model is None:
        raise ValueError(
            "incremental_training is enabled but no warm_start_model_dir "
            "is configured (GameEstimator.scala:241-382)")

    # ------------------------------------------------------------------
    # feature stats + normalization (prepareNormalizationContexts :590)
    # ------------------------------------------------------------------
    norm_contexts = {}
    if (
        cfg.normalization != NormalizationType.NONE
        or cfg.data_summary_dir
    ):
        import jax.numpy as jnp

        from photon_tpu.cli.common import is_coordinator

        for s in shards:
            stats = FeatureDataStatistics.from_features(
                train.feature_shards[s],
                train.host_column("weights"),
                intercept_index=intercept_indices.get(s),
            )
            if cfg.data_summary_dir and is_coordinator():
                # calculateAndSaveFeatureShardStats :616-627: one
                # FeatureSummarizationResultAvro dir per shard.
                from photon_tpu.io.model_io import save_feature_stats

                save_feature_stats(
                    os.path.join(cfg.data_summary_dir, s),
                    stats,
                    index_maps[s],
                )
                log.info("feature stats for shard %r written to %s",
                         s, os.path.join(cfg.data_summary_dir, s))
            if cfg.normalization != NormalizationType.NONE:
                norm_contexts[s] = build_normalization_context(
                    cfg.normalization,
                    mean=jnp.asarray(stats.mean),
                    variance=jnp.asarray(stats.variance),
                    min_=jnp.asarray(stats.min),
                    max_=jnp.asarray(stats.max),
                    intercept_index=intercept_indices.get(s),
                )

    # ------------------------------------------------------------------
    # fit over the lambda grid (GameEstimator.fit :397)
    # ------------------------------------------------------------------
    estimator = cfg.build_estimator(norm_contexts, intercept_indices)
    opt_seq = cfg.opt_config_sequence()
    log.info("training %d configuration(s)", len(opt_seq))

    # ------------------------------------------------------------------
    # crash safety (photon_tpu.resilience; RESILIENCE.md)
    # ------------------------------------------------------------------
    checkpointer = None
    resume_state = None
    ckpt_dir = args.checkpoint_dir or args.resume
    if ckpt_dir:
        from photon_tpu.resilience import (
            TrainingCheckpointer,
            load_training_checkpoint,
            training_static_key,
        )

        static_key = training_static_key(estimator, opt_seq)
        checkpointer = TrainingCheckpointer(ckpt_dir, static_key)
        # Run provenance rides every manifest commit: the streaming-
        # ingest cursor (work dir + pinned shard-manifest hash) and the
        # init-model digest, so a crash at ANY point recovers end to
        # end — `--stream-dir --resume-ingest --resume DIR` replays
        # ingest from its cursor (spill reloads, byte-identical data)
        # and the descent from its checkpoint, against a verifiable
        # warm-start identity.
        run_meta = {}
        if stream_stats is not None:
            run_meta["ingest_cursor"] = {
                "stream_dir": os.path.abspath(args.stream_dir),
                "work_dir": os.path.abspath(stream_work_dir),
                "manifest_sha256": stream_stats.get("manifest_sha256"),
                "rows_ingested": stream_stats.get("rows_ingested"),
                "ingested_fraction":
                    stream_stats.get("ingested_fraction"),
                "quarantined_shards":
                    stream_stats.get("shards_quarantined"),
            }
        if init_model_digest is not None:
            run_meta["init_model"] = {
                "path": os.path.abspath(args.init_model),
                "sha256": init_model_digest,
            }
        if run_meta:
            checkpointer.set_run_meta(run_meta)
        if args.resume:
            resume_state = load_training_checkpoint(args.resume)
            log.info(
                "resuming from %s: config %d, last completed CD "
                "iteration %d%s", args.resume,
                resume_state.config_index, resume_state.iteration,
                " (interrupted run)" if resume_state.interrupted else "")

    # SIGINT/SIGTERM: unwind the fit via TrainingInterrupted so a final
    # emergency checkpoint lands before the nonzero exit — a preempted
    # host resumes instead of restarting from scratch. Installed only
    # around the training section (the handlers are process-global
    # state; an embedding process gets them back in the finally).
    import signal

    def _interrupt(signum, frame):
        raise TrainingInterrupted(signum)

    from photon_tpu.resilience import TrainingInterrupted

    prev_handlers = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev_handlers[sig] = signal.signal(sig, _interrupt)
        except ValueError:  # pragma: no cover — non-main-thread embed
            pass
    try:
        with obs.logged_span("prepare training datasets", log):
            estimator.prepare(train, validation, initial_model)
        # Readiness signal for `--monitor-port`'s /readyz (and a useful
        # /metrics fact on its own). Registry mutations are not gated
        # on the telemetry flag, so the probe works with telemetry off.
        obs.REGISTRY.gauge("train_datasets_prepared").set(1)
        with obs.logged_span("train models", log), \
                obs.profile_session(
                    cfg.profile_dir, name="train_fit_profile"):
            results = estimator.fit(
                train, validation, opt_seq,
                initial_model=initial_model,
                checkpointer=checkpointer,
                resume=resume_state,
            )
    except TrainingInterrupted as exc:
        log.error("training interrupted by signal %d", exc.signum)
        # Post-mortem and recovery point commit together: the flight
        # dump carries the timeline that explains WHERE the run was
        # when the signal landed; the emergency checkpoint below
        # carries the state to resume from. Gated on THIS CLI's own
        # recorder — under --no-flight an embedding caller's ambient
        # recorder must not be dumped to behind its back.
        if recorder is not None:
            recorder.dump(f"signal:{exc.signum}")
        if checkpointer is not None:
            path = checkpointer.write_emergency()
            if path:
                log.error(
                    "emergency checkpoint committed to %s; resume "
                    "with: photon train --config %s --resume %s",
                    path, args.config, ckpt_dir)
            else:
                log.error(
                    "interrupted before any CD iteration completed; "
                    "no training state to checkpoint")
        return 128 + exc.signum
    finally:
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)

    # ------------------------------------------------------------------
    # hyperparameter tuning (runHyperparameterTuning :677-719)
    # ------------------------------------------------------------------
    num_tuned = 0
    tuning = cfg.hyperparameter_tuning or {}
    tuning_mode = str(tuning.get("mode", "NONE")).upper()
    if tuning_mode != "NONE" and validation is None:
        log.warning(
            "hyperparameter tuning (%s) requested but no validation_path is "
            "configured; skipping", tuning_mode)
    elif tuning_mode != "NONE":
        from photon_tpu import hyperparameter

        base_config = results[0].config
        evaluator = results[0].evaluation.primary_evaluator
        evaluation_function = (
            hyperparameter.GameEstimatorEvaluationFunction(
                estimator, base_config, train, validation,
                is_opt_max=evaluator.bigger_is_better,
                initial_model=initial_model,
            ))
        if evaluation_function.num_params == 0:
            log.warning(
                "hyperparameter tuning requested but no coordinate has a "
                "tunable regularization; skipping")
        else:
            observations = evaluation_function.convert_observations(results)
            tuned = hyperparameter.search(
                int(tuning.get("iterations", 10)),
                evaluation_function.num_params,
                tuning_mode,
                evaluation_function,
                observations,
                seed=int(tuning.get("seed", 0)),
            )
            num_tuned = len(tuned)
            log.info("hyperparameter tuning (%s) evaluated %d candidate(s)",
                     tuning_mode, num_tuned)
            results = results + tuned

    # ------------------------------------------------------------------
    # model selection + save (selectBestModel :753, saveModelToHDFS :804)
    # ------------------------------------------------------------------
    best = estimator.select_best(results)
    best_idx = next(i for i, r in enumerate(results) if r is best)

    def config_json(r):
        return {
            cid: {
                "regularization":
                    c.regularization.regularization_type.value,
                "lambda": c.regularization_weight,
                "optimizer": c.optimizer.optimizer_type.value,
            }
            for cid, c in r.config.items()
        }

    # Multi-host runs execute this driver on every process (the compute —
    # fit, tuning, scoring — is SPMD and must run everywhere), but artifact
    # writes happen once, from process 0 (the reference writes from the
    # Spark driver only).
    from photon_tpu.cli.common import is_coordinator

    write_outputs = is_coordinator()
    summary = {
        "task": cfg.task.value,
        "num_training_rows": train.num_samples,
        "num_configurations": len(results),
        "num_tuned_configurations": num_tuned,
        "best_configuration_index": best_idx,
        "configurations": [
            {
                "config": config_json(r),
                "evaluation":
                    None if r.evaluation is None else r.evaluation.evaluations,
            }
            for r in results
        ],
        "wall_clock_seconds": round(time.time() - t_start, 2),
    }
    if stream_stats is not None:
        # The streaming-ingest health block: ingested_fraction +
        # quarantined paths land in the summary artifact (and the
        # stream_* registry gauges feed /metrics for --monitor-port).
        summary["streaming_ingest"] = stream_stats
    if args.telemetry:
        # The unified telemetry snapshot (span tree with host/device
        # split, metrics, convergence series, pipeline + compile-cache
        # reports) rides the summary artifact; the full per-record
        # stream goes to the --telemetry JSONL path in main().
        from photon_tpu import obs

        summary["telemetry"] = obs.snapshot()
    if write_outputs:
        with open(
            os.path.join(cfg.output_dir, "training-summary.json"), "w"
        ) as f:
            json.dump(summary, f, indent=2)

    # Model output modes (io/ModelOutputMode.scala:47): NONE saves nothing;
    # BEST the selected model; EXPLICIT adds the lambda-grid models; TUNED
    # adds the tuner's models; ALL saves everything. The best model always
    # lands in "best/".
    num_grid = len(results) - num_tuned
    mode = cfg.model_output_mode
    if mode == "NONE":
        to_save = []
    elif mode == "BEST":
        to_save = [(best_idx, best)]
    elif mode == "EXPLICIT":
        to_save = [(best_idx, best)] + [
            (i, r) for i, r in enumerate(results[:num_grid]) if i != best_idx
        ]
    elif mode == "TUNED":
        to_save = [(best_idx, best)] + [
            (i, r) for i, r in list(enumerate(results))[num_grid:]
            if i != best_idx
        ]
    elif mode == "ALL":
        to_save = list(enumerate(results))
    else:
        raise ValueError(f"unknown model_output_mode {mode!r}")
    if write_outputs:
        for i, r in to_save:
            subdir = "best" if r is best else f"config_{i}"
            out = os.path.join(cfg.output_dir, "models", subdir)
            save_game_model(
                r.model, out, index_maps,
                task=cfg.task,
                optimization_configurations=config_json(r),
            )
            save_checkpoint(r.model, os.path.join(out, "checkpoint.npz"))
        log.info("saved %d model(s) to %s", len(to_save),
                 os.path.join(cfg.output_dir, "models"))

    # ------------------------------------------------------------------
    # per-group evaluation output (savePerGroupEvaluationToHDFS :878-901)
    # ------------------------------------------------------------------
    grouped_specs = [e for e in cfg.evaluators if ":" in e]
    if mode != "NONE" and validation is not None and grouped_specs:
        import numpy as np

        from photon_tpu.evaluation.suite import make_suite
        from photon_tpu.transformers import GameTransformer

        group_ids = {
            name: (tag.codes, tag.num_groups)
            for name, tag in validation.id_tags.items()
        }
        suite = make_suite(
            grouped_specs, validation.labels,
            offsets=validation.offsets, weights=validation.weights,
            group_ids=group_ids, dtype=validation.labels.dtype,
        )
        for i, r in to_save:
            # Scoring is SPMD compute: every process participates; only
            # the file writes below are coordinator-gated.
            scores = GameTransformer(
                r.model, mesh=estimator.resolve_mesh()
            ).score(validation)
            per_group = suite.evaluate_per_group(scores)
            if not write_outputs:
                continue
            out_dir = os.path.join(
                cfg.output_dir, "group-evaluation", str(i))
            os.makedirs(out_dir, exist_ok=True)
            for metric, values in per_group.items():
                tag = metric.split(":", 1)[1]
                keys = validation.id_tags[tag].inverse
                payload = {
                    str(k): float(v)
                    for k, v in zip(keys, values)
                    if np.isfinite(v)
                }
                fname = metric.replace(":", "_") + ".json"
                with open(os.path.join(out_dir, fname), "w") as f:
                    json.dump(payload, f, indent=2)
        log.info("wrote per-group evaluations for %d model(s)", len(to_save))
    if write_outputs:
        print(json.dumps({
            "best_configuration": config_json(best),
            "evaluation":
                None if best.evaluation is None
                else best.evaluation.evaluations,
            "output_dir": cfg.output_dir,
            "wall_clock_seconds": summary["wall_clock_seconds"],
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
