"""``photon serve``: the online-scoring driver (synchronous, no network).

TPU-native counterpart of the photon-client scoring surface run as a
resident scorer instead of a batch job: load a GAME model into
HBM-resident coefficient tables (``serve/tables.py``), AOT-compile the
fixed-shape score ladder (``serve/programs.py``), start the
micro-batching queue (``serve/queue.py``), then feed requests from an
Avro data file or a synthetic generator and print ONE JSON line with
p50/p99 latency, QPS, batch-fill fraction, and cold-entity rate.

Usage:
    python -m photon_tpu.cli.serve --model-dir out/models/best \
        [--input data.avro | --synthetic 1000] \
        [--batch-sizes 1,8,64,512] [--max-linger-ms 2] [--json PATH]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir",
                     help="GAME model directory (Avro layout)")
    src.add_argument("--checkpoint",
                     help="native .npz checkpoint (io/model_io)")
    parser.add_argument("--input", default=None,
                        help="TrainingExampleAvro file/dir to replay as "
                             "requests (one request per row)")
    parser.add_argument("--synthetic", type=int, default=1000,
                        metavar="N",
                        help="without --input: generate N synthetic "
                             "requests from the model's own shapes")
    parser.add_argument("--cold-fraction", type=float, default=0.05,
                        help="synthetic traffic: fraction of entity "
                             "lookups drawn outside the model vocabulary")
    parser.add_argument("--batch-sizes", default="1,8,64,512",
                        help="score-ladder rungs (comma-separated)")
    parser.add_argument("--max-batch", type=int, default=None,
                        help="queue flush size (default: top rung)")
    parser.add_argument("--max-linger-ms", type=float, default=2.0,
                        help="max time the oldest request waits for "
                             "batch-mates before a flush")
    parser.add_argument("--max-queue", type=int, default=4096,
                        help="queue bound; producers block beyond it")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-request deadline: a request still "
                             "queued past it fails fast with "
                             "DeadlineExceededError (RESILIENCE.md)")
    parser.add_argument("--shed-watermark", type=int, default=None,
                        help="queue depth beyond which submits are "
                             "rejected (OverloadedError) instead of "
                             "blocking")
    parser.add_argument("--breaker-threshold", type=int, default=8,
                        help="consecutive dispatch failures that trip "
                             "the circuit breaker (drain + fail fast); "
                             "0 disables")
    parser.add_argument("--reload-model", action="append", default=[],
                        metavar="PATH",
                        help="after the main drive, hot-reload this "
                             "model (npz checkpoint or Avro model dir) "
                             "into the LIVE queue and drive the "
                             "requests again — values-only refreshes "
                             "swap in place with zero recompiles, "
                             "structure changes rebuild tables + "
                             "ladder off-path and swap under the "
                             "queue's quiesce (repeatable; per-reload "
                             "summaries ride the output JSON)")
    parser.add_argument("--target-qps", type=float, default=None,
                        help="pace submissions at this offered load "
                             "(default: flood — closed-loop saturation)")
    parser.add_argument("--monitor-port", type=int, default=None,
                        metavar="PORT",
                        help="serve /metrics (Prometheus text "
                             "exposition), /healthz, and /readyz on "
                             "this port for the whole run (0 = "
                             "ephemeral; the bound port rides the "
                             "output JSON). /readyz flips 200 once "
                             "tables are loaded, the AOT ladder is "
                             "compiled, and the breaker is closed "
                             "(OBSERVABILITY.md §live monitoring)")
    parser.add_argument("--slo-p99-ms", type=float, default=250.0,
                        help="latency SLO: 99%% of served requests "
                             "must finish under this many ms")
    parser.add_argument("--slo-error-rate", type=float, default=0.001,
                        help="error-rate SLO budget (fraction of "
                             "requests allowed to fail)")
    parser.add_argument("--slo-cold-rate", type=float, default=0.2,
                        help="cold-entity-rate SLO budget (fraction "
                             "of lookups allowed out-of-vocabulary)")
    parser.add_argument("--slo-window-s", type=float, default=5.0,
                        help="short burn-rate window, seconds (the "
                             "long window is 12x)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--id-tags", nargs="*", default=None)
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write the summary JSON to PATH")
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="write the obs JSONL stream to PATH")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="write the merged Chrome-trace/Perfetto "
                             "timeline (host spans, counter tracks, "
                             "per-request span trees) to PATH "
                             "(OBSERVABILITY.md)")
    parser.add_argument("--request-log", default=None, metavar="PATH",
                        help="write the per-request JSONL stream "
                             "(one record per served/expired/shed/"
                             "breaker-failed request) to PATH")
    parser.add_argument("--health-sketch", default=None, metavar="PATH",
                        help="arm the model/data-health serve tap "
                             "(obs/health.py) and write the sampled "
                             "request/score sketch to PATH at exit — "
                             "compare against a training run's "
                             "ingest-sketch.json with `python -m "
                             "photon_tpu.cli.health`")
    parser.add_argument("--flight-dir", default=".", metavar="DIR",
                        help="crash flight recorder destination: "
                             "flight-<pid>.json is dumped there on "
                             "SIGINT/SIGTERM, unhandled exceptions, and "
                             "crash-kind injected faults")
    parser.add_argument("--no-flight", action="store_true",
                        help="disable the crash flight recorder")
    parser.add_argument("--backend", default=None)
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--log-file", default=None)
    args = parser.parse_args(argv)

    if args.checkpoint and args.input:
        # A native checkpoint stores coefficients by dense index with no
        # (name, term) keying, so there is no way to align it with the
        # index maps a data file defines — silently serving synthetic
        # traffic instead would mislabel the numbers.
        parser.error(
            "--input requires --model-dir (the Avro layout's name-keyed "
            "coefficients align with the data's index maps; a .npz "
            "checkpoint cannot)"
        )
    if args.backend:
        os.environ["JAX_PLATFORMS"] = args.backend
    from photon_tpu.cli.common import cli_logging

    with cli_logging(args.verbose, args.log_file):
        from photon_tpu.resilience import faults
        from photon_tpu.utils import enable_compilation_cache

        # Chaos harness: PHOTON_TPU_FAULT_PLAN arms a seeded FaultPlan
        # inside this process (no-op when unset) — how the chaos-smoke
        # CI injects faults into CLI subprocesses deterministically.
        faults.arm_from_env()
        # Warm server starts skip the ladder compiles entirely: the AOT
        # programs key into the same persistent cache as everything else.
        enable_compilation_cache()
        return _run(args)


def _run(args) -> int:
    from photon_tpu import obs
    from photon_tpu.obs import flight
    from photon_tpu.utils import compile_event_count

    # Telemetry for the serve run, with the enabled flag left as found
    # (the cli/train.py convention — an embedding process's obs state is
    # not ours to flip permanently).
    was_enabled = obs.enabled()
    was_health = obs.health.enabled()
    obs.reset()
    obs.enable()
    if args.health_sketch:
        # Arm the model/data-health serve tap for the run: sampled
        # request/score sketches accumulate for the exit artifact (and
        # the health_* /metrics families while serving).
        obs.health.enable()
    # Crash flight recorder (obs/flight.py): SIGINT/SIGTERM are chained
    # here (serve has no handlers of its own), unhandled exceptions and
    # crash-kind injected faults dump via the block below / the faults
    # listener — a dead serve process always leaves flight-<pid>.json.
    rec = None
    prior_rec = flight.installed()
    if not args.no_flight:
        rec = flight.install(args.flight_dir, signals=True)
    try:
        return _run_instrumented(args, obs, compile_event_count)
    except BaseException as exc:
        # In-process callers catch exceptions up-stack, so the chained
        # sys.excepthook never fires for them — dump at the unwind.
        if rec is not None and not isinstance(exc, SystemExit):
            flight.dump(f"exception:{type(exc).__name__}")
        raise
    finally:
        if rec is not None:
            flight.uninstall()
            if prior_rec is not None:
                # Our default-on install replaced an embedding caller's
                # ambient recorder — hand it back re-armed.
                flight.reinstall(prior_rec)
        obs.TRACER.enabled = was_enabled
        if not was_health:
            obs.health.disable()


def _run_instrumented(args, obs, compile_event_count) -> int:
    from photon_tpu.obs import monitor

    # Live monitoring (obs/monitor.py): the exporter comes up BEFORE
    # the model loads so /healthz answers from the first second of the
    # process, while /readyz stays 503 until tables are resident, the
    # AOT ladder is compiled, AND the breaker is closed — the
    # load-balancer handshake a resident scorer needs. The queue's
    # metrics collector is registered once the queue exists.
    ready_state = {"tables_loaded": False, "ladder_compiled": False}
    queue_ref: list = []

    def _readiness():
        breaker_open = bool(
            queue_ref and queue_ref[0].health()["breaker_open"]
        )
        ready = (
            ready_state["tables_loaded"]
            and ready_state["ladder_compiled"]
            and bool(queue_ref)
            and not breaker_open
        )
        return ready, {**ready_state, "queue_up": bool(queue_ref),
                       "breaker_open": breaker_open}

    mon = None
    if args.monitor_port is not None:
        from photon_tpu.obs import fleet

        # Rank-offset the bind (base + process_index): several ranks
        # sharing one host must not collide on one --monitor-port value.
        port = fleet.resolve_monitor_port(args.monitor_port)
        mon = monitor.MonitorServer(
            port, readiness=_readiness
        ).start()
        logging.getLogger("photon.serve").info(
            "monitor endpoints on port %d (requested %d, rank %d)",
            mon.port, args.monitor_port,
            fleet.host_identity()["process_index"],
        )
    try:
        return _serve_instrumented(
            args, obs, compile_event_count, mon, ready_state, queue_ref
        )
    finally:
        if mon is not None:
            mon.stop()


def _serve_instrumented(
    args, obs, compile_event_count, mon, ready_state, queue_ref
) -> int:
    from photon_tpu.obs import logged_span, monitor
    from photon_tpu.serve.driver import (
        dataset_requests,
        drive,
        synthetic_requests,
    )
    from photon_tpu.serve.programs import (
        ScorePrograms,
        ShapeLadder,
        specs_from_dataset,
    )
    from photon_tpu.serve.queue import MicroBatchQueue
    from photon_tpu.serve.tables import (
        CoefficientTables,
        build_index_maps_from_model,
    )

    rungs = tuple(
        int(r) for r in args.batch_sizes.split(",") if r.strip()
    )
    ladder = ShapeLadder(rungs)

    data = None
    with logged_span("serve: load model"):
        if args.checkpoint:
            from photon_tpu.io.model_io import load_checkpoint

            model = load_checkpoint(args.checkpoint)
        else:
            from photon_tpu.io.model_io import load_game_model

            if args.input:
                # Request features resolve against the DATA's index
                # maps, so the model must load against the same maps
                # (the batch-scoring convention, cli/score.py).
                from photon_tpu.io.avro_data import (
                    build_index_map_from_records,
                    read_training_examples,
                )
                from photon_tpu.io import avro

                records = avro.read_container_dir(args.input)
                index_map = build_index_map_from_records(records)
                data, _ = read_training_examples(
                    args.input, index_map=index_map,
                    id_tag_names=args.id_tags, records=records,
                )
                from photon_tpu.cli.score import _alias_shards
                from photon_tpu.io.model_io import model_feature_shard_ids

                shards = model_feature_shard_ids(args.model_dir)
                index_maps = {s: index_map for s in shards} or {
                    "features": index_map
                }
                data = _alias_shards(data, shards)
            else:
                # Standalone serving: the model directory's own records
                # define the feature space.
                index_maps = build_index_maps_from_model(args.model_dir)
            model, _ = load_game_model(args.model_dir, index_maps)

    tables = CoefficientTables.from_game_model(model)
    ready_state["tables_loaded"] = True
    with logged_span("serve: AOT-compile score ladder"):
        programs = ScorePrograms(
            tables,
            ladder=ladder,
            specs=specs_from_dataset(data) if data is not None else None,
        )
    ready_state["ladder_compiled"] = True

    if data is not None:
        requests = dataset_requests(data, programs)
    else:
        requests = synthetic_requests(
            tables, programs, args.synthetic,
            cold_fraction=args.cold_fraction, seed=args.seed,
        )

    # Steady-state zero-recompile evidence: compile-cache activity across
    # the measured window must be flat (the static half of the claim is
    # the tier-2 `serving` contract; this is the runtime half).
    before = compile_event_count()
    with logged_span("serve: drive requests"):
        with MicroBatchQueue(
            programs,
            max_batch=args.max_batch,
            max_linger_s=args.max_linger_ms / 1e3,
            max_queue=args.max_queue,
            default_deadline_s=(
                None if args.deadline_ms is None
                else args.deadline_ms / 1e3
            ),
            shed_watermark=args.shed_watermark,
            breaker_threshold=args.breaker_threshold or None,
            slo=monitor.SloPolicy(
                p99_ms=args.slo_p99_ms,
                error_rate=args.slo_error_rate,
                cold_entity_rate=args.slo_cold_rate,
                short_window_s=args.slo_window_s,
                long_window_s=12 * args.slo_window_s,
            ),
        ) as queue:
            queue_ref.append(queue)
            if mon is not None:
                # From here /readyz is 200 and /metrics carries the
                # queue collector (depth, per-coordinate cold, window
                # quantiles, hotness, SLO burn).
                mon.add_collector(queue.metrics_families)
            summary = drive(queue, requests, rate=args.target_qps)
            reloads = []
            for path in args.reload_model:
                # Hot model swap on the LIVE queue (serve/tables.py
                # rebuild_from via queue.reload_model): values-only
                # refreshes flip references under dispatch; structure
                # changes rebuild tables + ladder off-path and swap
                # under quiesce — then the SAME requests drive again
                # so the output proves the swapped generation serves.
                refreshed = _load_reload_model(args, path)
                r_before = compile_event_count()
                info = queue.reload_model(refreshed)
                info["compile_events"] = (
                    compile_event_count() - r_before
                )
                info["model"] = path
                info["summary"] = drive(
                    queue, requests, rate=args.target_qps
                )
                reloads.append(info)
            health = queue.health()
    after = compile_event_count()

    out = {
        "metric": "serving",
        "model": args.model_dir or args.checkpoint,
        "rungs": list(programs.ladder.rungs),
        "max_batch": queue.max_batch,
        "max_linger_ms": args.max_linger_ms,
        "programs_compiled": programs.stats["programs_compiled"],
        "aot_compile_seconds": round(
            programs.stats["aot_compile_seconds"], 4
        ),
        "dispatches": programs.stats["dispatches"],
        "compile_events_during_serving": after - before,
        # Degraded-mode snapshot (queue depth, shed/deadline/breaker/
        # retry counters, table generation, window quantiles, SLO burn)
        # — what a health probe reads.
        "health": health,
        "tables": tables.coordinate_stats(),
    }
    if mon is not None:
        out["monitor"] = {"port": mon.port, **mon.scrape_stats()}
    if reloads:
        out["reloads"] = reloads
    out.update(summary)
    if args.telemetry:
        obs.write_jsonl(args.telemetry)
    if args.trace:
        obs.write_chrome_trace(args.trace)
    if args.request_log:
        obs.trace.write_request_jsonl(args.request_log)
    if args.health_sketch:
        out["health_sketch"] = {
            "path": args.health_sketch,
            "requests_sampled": obs.health.save_serve_sketch(
                args.health_sketch),
        }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))
    # Partial failures must be visible to exit-code-only consumers
    # (health checks): errored requests already excluded the latency
    # stats, and a clean exit would mislabel the run healthy — in any
    # generation, including post-reload drives.
    errors = summary["errors"] + sum(
        r["summary"]["errors"] for r in reloads
    )
    return 0 if errors == 0 else 1


def _load_reload_model(args, path: str):
    """A ``--reload-model`` artifact: native checkpoint (self-
    contained) or Avro model directory (keyed against its own records,
    the standalone-serving convention — a values-only swap therefore
    needs the refreshed model saved against the same feature space)."""
    if os.path.isfile(path) or path.endswith(".npz"):
        from photon_tpu.io.model_io import load_checkpoint

        return load_checkpoint(path)
    from photon_tpu.io.model_io import load_game_model
    from photon_tpu.serve.tables import build_index_maps_from_model

    model, _ = load_game_model(path, build_index_maps_from_model(path))
    return model


if __name__ == "__main__":
    sys.exit(main())
