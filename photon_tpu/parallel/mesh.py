"""Device mesh construction and dataset sharding rules.

The reference's "communication backend" is Spark: broadcast coefficients out,
treeAggregate gradients back, partitioner-aligned shuffles for routing
(SURVEY §5.8). The TPU-native backend is a ``jax.sharding.Mesh`` plus
NamedSharding annotations: coefficients live replicated in HBM, data rows are
sharded over the ``data`` axis, and XLA inserts the psum/all-gather
collectives over ICI (DCN for multi-slice) wherever the GLM objective's
reductions cross the sharded axis. There is no per-iteration broadcast and no
host round trip.

Mirrors (in spirit) SparkSessionConfiguration (photon-api
SparkSessionConfiguration.scala:109) and LongHashPartitioner
(util/LongHashPartitioner.scala:24): session setup becomes mesh construction,
row partitioning becomes an even row split.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import GLMBatch, pad_batch

DATA_AXIS = "data"


def make_mesh(
    devices=None, *, axis_name: str = DATA_AXIS
) -> Mesh:
    """One-axis data mesh over the given (default: all) devices.

    GLM/GLMix training is data-parallel + entity-parallel; both shard the
    sample/entity dimension, so a single mesh axis covers every coordinate
    type. Multi-host meshes come straight from jax.devices() spanning hosts.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis_name,))


def row_sharding(mesh: Mesh, ndim: int, *, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) axis, replicate the rest."""
    return NamedSharding(mesh, P(axis_name, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(
    batch: GLMBatch, mesh: Mesh, *, axis_name: str = DATA_AXIS
) -> GLMBatch:
    """Pad rows to the device count and place every leaf row-sharded.

    The weight-0 padding rows are inert in all aggregations, so sharded and
    unsharded objectives agree bit-for-bit up to reduction order.
    """
    n_dev = mesh.shape[axis_name]
    batch = pad_batch(batch, n_dev)
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, row_sharding(mesh, np.ndim(leaf), axis_name=axis_name)
        ),
        batch,
    )
