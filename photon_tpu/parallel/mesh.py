"""Device mesh construction and dataset sharding rules.

The reference's "communication backend" is Spark: broadcast coefficients out,
treeAggregate gradients back, partitioner-aligned shuffles for routing
(SURVEY §5.8). The TPU-native backend is a ``jax.sharding.Mesh`` plus
NamedSharding annotations: coefficients live replicated in HBM, data rows are
sharded over the ``data`` axis, and XLA inserts the psum/all-gather
collectives over ICI (DCN for multi-slice) wherever the GLM objective's
reductions cross the sharded axis. There is no per-iteration broadcast and no
host round trip.

Mirrors (in spirit) SparkSessionConfiguration (photon-api
SparkSessionConfiguration.scala:109) and LongHashPartitioner
(util/LongHashPartitioner.scala:24): session setup becomes mesh construction,
row partitioning becomes an even row split.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import GLMBatch, pad_batch

DATA_AXIS = "data"


def shard_random_effect_dataset(
    ds, mesh: Mesh, *, axis_name: str = DATA_AXIS
):
    """Shard a RandomEffectDataset's entity axis over the mesh (ep).

    Each size bucket's entity axis is padded to a multiple of the device
    count with inert entities (weight 0, empty subspace, entity code ==
    num_entities so their scatter back into the coefficient matrix is
    dropped as out-of-bounds), then every block leaf is placed with its
    leading axis sharded. The per-entity solves are embarrassingly parallel
    (RandomEffectCoordinate.scala:243-292 runs them executor-local), so
    sharding the vmapped solver's batch axis keeps all solver FLOPs local
    to each device — the TPU analog of the reference's entity partitioning
    (RandomEffectDatasetPartitioner.scala:44).

    The scoring table's row axis is sharded too when evenly divisible
    (otherwise left as-is: scoring is one gather-multiply-reduce either way).
    """
    import dataclasses

    from photon_tpu.data.random_effect import EntityBlocks

    n_dev = mesh.shape[axis_name]

    def place(leaf):
        return jax.device_put(
            leaf, row_sharding(mesh, np.ndim(leaf), axis_name=axis_name)
        )

    import jax.numpy as jnp

    def pad_block(b: EntityBlocks) -> EntityBlocks:
        pad = (-b.num_entities) % n_dev
        if pad:
            fills = {"entity_codes": ds.num_entities,
                     "proj": -1, "intercept_slots": -1}

            def pad_leaf(name, leaf):
                widths = [(0, pad)] + [(0, 0)] * (np.ndim(leaf) - 1)
                return jnp.pad(
                    leaf, widths, constant_values=fills.get(name, 0)
                )

            b = EntityBlocks(**{
                f.name: pad_leaf(f.name, getattr(b, f.name))
                for f in dataclasses.fields(EntityBlocks)
            })
        return jax.tree.map(place, b)

    blocks = tuple(pad_block(b) for b in ds.blocks)
    rep = {"blocks": blocks}
    if ds.score_codes.shape[0] % n_dev == 0:
        rep.update(
            score_codes=place(ds.score_codes),
            score_indices=place(ds.score_indices),
            score_values=place(ds.score_values),
        )
    return dataclasses.replace(ds, **rep)


def make_mesh(
    devices=None, *, axis_name: str = DATA_AXIS
) -> Mesh:
    """One-axis data mesh over the given (default: all) devices.

    GLM/GLMix training is data-parallel + entity-parallel; both shard the
    sample/entity dimension, so a single mesh axis covers every coordinate
    type. Multi-host meshes come straight from jax.devices() spanning hosts.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis_name,))


def row_sharding(mesh: Mesh, ndim: int, *, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) axis, replicate the rest."""
    return NamedSharding(mesh, P(axis_name, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(
    batch: GLMBatch, mesh: Mesh, *, axis_name: str = DATA_AXIS
) -> GLMBatch:
    """Pad rows to the device count and place every leaf row-sharded.

    The weight-0 padding rows are inert in all aggregations, so sharded and
    unsharded objectives agree bit-for-bit up to reduction order.
    """
    n_dev = mesh.shape[axis_name]
    batch = pad_batch(batch, n_dev)
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, row_sharding(mesh, np.ndim(leaf), axis_name=axis_name)
        ),
        batch,
    )
