"""Device mesh construction and dataset sharding rules.

The reference's "communication backend" is Spark: broadcast coefficients out,
treeAggregate gradients back, partitioner-aligned shuffles for routing
(SURVEY §5.8). The TPU-native backend is a ``jax.sharding.Mesh`` plus
NamedSharding annotations: coefficients live replicated in HBM, data rows are
sharded over the ``data`` axis, and XLA inserts the psum/all-gather
collectives over ICI (DCN for multi-slice) wherever the GLM objective's
reductions cross the sharded axis. There is no per-iteration broadcast and no
host round trip.

Mirrors (in spirit) SparkSessionConfiguration (photon-api
SparkSessionConfiguration.scala:109) and LongHashPartitioner
(util/LongHashPartitioner.scala:24): session setup becomes mesh construction,
row partitioning becomes an even row split.
"""

from __future__ import annotations

import dataclasses as _dataclasses
import re as _re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import GLMBatch, pad_batch

DATA_AXIS = "data"

# Program contract (audited by `python -m photon_tpu.analysis --semantic`;
# machinery in analysis/program.py): every hot-loop operand of a sharded
# fixed-effect batch carries the DATA_AXIS NamedSharding; random-effect
# plan arrays shard their entity axis while the shared raw leaves stay
# replicated; and the lowered data-parallel objective's only collective is
# the gradient all-reduce — an all-gather appearing here means sharding
# propagation broke and every dispatch pays a cross-device transfer.
PROGRAM_AUDIT = dict(
    name="mesh-sharding",
    entry="parallel.mesh.shard_batch / shard_random_effect_dataset "
    "+ ops.glm objective",
    builder="build_mesh_sharding",
    hot_loop=True,
    sharded_operands=(
        "features", "labels", "offsets", "weights",
        "re_entity_codes", "re_row_ids",
    ),
    replicated_operands=("re_raw",),
    axis=DATA_AXIS,
    allowed_collectives=("all-reduce",),
)

# SPMD contract (audited by `python -m photon_tpu.analysis --spmd`;
# machinery in analysis/spmd.py): the sharded objective must trace to
# byte-identical jaxprs on every simulated host, its compiled HLO must
# carry the same ordered collective sequence on every host and nothing
# beyond the gradient all-reduce, and every placed leaf must be covered
# by exactly one PARTITION_RULES entry whose spec the placement agrees
# with. This is the acceptance harness for the pjit/NamedSharding mesh
# rebuild (ROADMAP item 1): the rebuild lands when it passes this
# contract, and `covers` pins the tier-2 census to the tier-6 one so
# the two audits cannot drift.
SPMD_AUDIT = dict(
    name="mesh-spmd",
    entry="parallel.mesh.shard_batch / shard_random_effect_dataset "
    "+ ops.glm objective",
    builder="build_mesh_spmd",
    hosts=2,
    ordered_collectives=("all-reduce",),
    partition_rules="PARTITION_RULES",
    covers=("mesh-sharding",),
)

# The regex partition-rule tree for every leaf the mesh places, in the
# match_partition_rules shape (first match wins; the SPMD auditor holds
# the stronger line that exactly one rule matches each leaf). Leaf names
# are slash-joined pytree paths: "fe/<field>" for the fixed-effect
# batch, "re/block<i>/<field>" for random-effect plan arrays,
# "re/raw*"/"re/score_*" for the shared scoring tables, "coef/*" for
# coefficient vectors. The pjit rebuild (ROADMAP item 1) feeds these
# specs to pjit instead of per-leaf device_put calls; until then they
# document — and the auditor verifies — what the placement code does.
PARTITION_RULES = (
    # Fixed-effect batch leaves: rows sharded over the data axis
    # (shard_batch pads to the device count first).
    (r"^fe/(features|labels|offsets|weights|uids)$", P(DATA_AXIS)),
    # Random-effect plan arrays: entity axis sharded — the per-entity
    # solves are embarrassingly parallel (shard_random_effect_dataset).
    (
        r"^re/block\d+/(entity_codes|row_ids|row_counts|proj"
        r"|intercept_slots)$",
        P(DATA_AXIS),
    ),
    # Shared raw leaves: replicated — BlockPlans gather arbitrary rows,
    # so every device needs the full table (the memory-for-zero-shuffle
    # tradeoff documented on shard_random_effect_dataset).
    (r"^re/raw(/|$)", P()),
    # Residual-scorer tables: per-row work, rows sharded when divisible.
    (r"^re/score_(codes|indices|values)$", P(DATA_AXIS)),
    # Coefficients: replicated in HBM; gradients all-reduce into them.
    (r"^coef(/|$)", P()),
)


def match_partition_rules(rules, leaves: dict):
    """Map named leaves to PartitionSpecs via first-match regex rules.

    ``leaves`` maps slash-joined pytree path names to arrays (anything
    with ``ndim``). Scalars take ``P()`` without consuming a rule; an
    array leaf no rule matches raises — silence here would mean a slab
    lands wherever jit defaults put it. Returns ``(specs, matches)``
    where ``matches[name]`` lists every matching rule index (the SPMD
    auditor checks the list has length exactly 1).
    """
    specs: dict[str, P] = {}
    matches: dict[str, list[int]] = {}
    for name, leaf in leaves.items():
        hit = [
            i for i, (pat, _) in enumerate(rules) if _re.search(pat, name)
        ]
        matches[name] = hit
        if int(getattr(leaf, "ndim", 0)) == 0:
            specs[name] = P()
        elif hit:
            specs[name] = rules[hit[0]][1]
        else:
            raise ValueError(
                f"no partition rule matches leaf {name!r}"
            )
    return specs, matches


def shard_random_effect_dataset(
    ds, mesh: Mesh, *, axis_name: str = DATA_AXIS
):
    """Shard a RandomEffectDataset's entity axis over the mesh (ep).

    Each size bucket's entity axis is padded to a multiple of the device
    count with inert entities (weight 0 / row_count 0, empty subspace,
    entity code == num_entities so their scatter back into the coefficient
    matrix is dropped as out-of-bounds), then every block leaf is placed
    with its leading axis sharded. The per-entity solves are embarrassingly
    parallel (RandomEffectCoordinate.scala:243-292 runs them
    executor-local), so sharding the vmapped solver's batch axis keeps all
    solver FLOPs local to each device — the TPU analog of the reference's
    entity partitioning (RandomEffectDatasetPartitioner.scala:44).

    Lazy ``BlockPlan`` buckets shard their plan arrays on the entity axis;
    the shared raw leaves are replicated over the mesh (each device gathers
    its own entities' rows locally — the replication rides ICI once, and is
    the memory-for-zero-shuffle tradeoff the reference pays per iteration
    in shuffles instead). The materialized scoring table's row axis is
    sharded when evenly divisible.
    """
    import dataclasses

    from photon_tpu.data.random_effect import BlockPlan, EntityBlocks

    n_dev = mesh.shape[axis_name]

    def place(leaf):
        return jax.device_put(
            leaf, row_sharding(mesh, np.ndim(leaf), axis_name=axis_name)
        )

    def replicate(leaf):
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    import jax.numpy as jnp

    _rep_cache: dict[int, object] = {}

    def replicate_cached(leaf):
        got = _rep_cache.get(id(leaf))
        if got is None:
            got = jax.tree.map(replicate, leaf)
            _rep_cache[id(leaf)] = got
        return got

    fills = {"entity_codes": ds.num_entities,
             "proj": -1, "intercept_slots": -1}
    plan_fields = (
        "entity_codes", "row_ids", "row_counts", "proj", "intercept_slots"
    )

    def pad_leaf(name, leaf, pad):
        if leaf is None:  # dense-layout EntityBlocks carry x_indices=None
            return None
        widths = [(0, pad)] + [(0, 0)] * (np.ndim(leaf) - 1)
        return jnp.pad(leaf, widths, constant_values=fills.get(name, 0))

    codes_np, ints_np = [], []

    def pad_host_mirror(arr, pad, fill):
        a = np.asarray(arr)
        return np.pad(a, (0, pad), constant_values=fill) if pad else a

    def pad_block(i, b):
        pad = (-b.num_entities) % n_dev
        # Host mirrors are padded host-side (never pulled from the device:
        # on a multi-host mesh the placed arrays span non-addressable
        # devices and cannot be fetched back).
        codes_np.append(
            pad_host_mirror(ds.block_codes_np[i], pad, ds.num_entities)
        )
        ints_np.append(pad_host_mirror(ds.block_intercepts_np[i], pad, -1))
        if isinstance(b, BlockPlan):
            vals = {
                name: pad_leaf(name, getattr(b, name), pad) if pad
                else getattr(b, name)
                for name in plan_fields
            }
            # Placement deferred: every block's plan leaves ride ONE
            # batched sharded device_put below (one transfer-path setup
            # per ingest instead of 5 x n_buckets — the sharded analog of
            # the packed single-device plan buffer).
            deferred.append((i, b, vals))
            return b
        if pad:
            b = EntityBlocks(**{
                f.name: pad_leaf(f.name, getattr(b, f.name), pad)
                for f in dataclasses.fields(EntityBlocks)
            })
        return jax.tree.map(place, b)

    deferred: list[tuple] = []
    out_blocks = [
        pad_block(i, b) for i, b in enumerate(ds.device_plans())
    ]
    if deferred:
        from photon_tpu.data.pipeline import PIPELINE_STATS

        leaves = [
            vals[name] for _, _, vals in deferred for name in plan_fields
        ]
        shardings = [
            row_sharding(mesh, np.ndim(leaf), axis_name=axis_name)
            for leaf in leaves
        ]
        with PIPELINE_STATS.stage("transfer"):
            placed = jax.device_put(leaves, shardings)
        it = iter(placed)
        for i, b, vals in deferred:
            out_blocks[i] = dataclasses.replace(
                b,
                raw=replicate_cached(b.raw),
                raw_labels=replicate_cached(b.raw_labels),
                raw_offsets=replicate_cached(b.raw_offsets),
                raw_weights=replicate_cached(b.raw_weights),
                **{name: next(it) for name in plan_fields},
            )
    blocks = tuple(out_blocks)
    rep = {
        "blocks": blocks,
        "block_codes_np": tuple(codes_np),
        "block_intercepts_np": tuple(ints_np),
        # The sharded dataset's plan arrays are mesh-placed above; the
        # single-device packed buffer must not shadow them.
        "packed_view": None,
    }
    if ds.is_lazy:
        # Raw leaves must be replicated (BlockPlans gather arbitrary rows),
        # but the residual scorer is per-row: sharding score_codes row-wise
        # (when divisible) makes the fused score dp-parallel — GSPMD slices
        # the replicated raw operand locally for free.
        codes = ds.score_codes
        if codes.shape[0] % n_dev == 0:
            codes = place(codes)
        else:
            codes = replicate(codes)
        rep.update(
            raw=replicate_cached(ds.raw),
            score_codes=codes,
            proj_dev=replicate_cached(ds.proj_device()),
        )
    elif ds.score_codes.shape[0] % n_dev == 0:
        rep.update(
            score_codes=place(ds.score_codes),
            score_indices=place(ds.score_indices),
            score_values=place(ds.score_values),
        )
    return dataclasses.replace(ds, **rep)


def make_mesh(
    devices=None, *, axis_name: str = DATA_AXIS
) -> Mesh:
    """One-axis data mesh over the given (default: all) devices.

    GLM/GLMix training is data-parallel + entity-parallel; both shard the
    sample/entity dimension, so a single mesh axis covers every coordinate
    type. Multi-host meshes come straight from jax.devices() spanning hosts.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis_name,))


def resolve_mesh(setting) -> Mesh | None:
    """Shared mesh-setting resolution for the estimator and the CLIs.

    ``"auto"`` -> all devices (None when single-device), ``"off"``/``None``/
    ``False``/``1`` -> None, an int or digit string -> that many devices, a
    ``Mesh`` -> itself. Unrecognized strings raise — a typo like ``"fof"``
    must not silently mean "auto".
    """
    m = setting
    if isinstance(m, str):
        key = m.lower()
        if key == "auto":
            return make_mesh() if len(jax.devices()) > 1 else None
        if key in ("off", "none", "1"):
            return None
        if key.isdigit():
            m = int(key)
        else:
            raise ValueError(f"unknown mesh setting {setting!r}")
    if isinstance(m, bool):
        return make_mesh() if (m and len(jax.devices()) > 1) else None
    if isinstance(m, int):
        if m < 1:
            raise ValueError(f"mesh setting must be >= 1 device, got {m}")
        if m > len(jax.devices()):
            raise ValueError(
                f"mesh setting requests {m} devices but only "
                f"{len(jax.devices())} are visible")
        return make_mesh(jax.devices()[:m]) if m > 1 else None
    if m is None or isinstance(m, Mesh):
        return m
    raise TypeError(f"unknown mesh setting {setting!r}")


def row_sharding(mesh: Mesh, ndim: int, *, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) axis, replicate the rest."""
    return NamedSharding(mesh, P(axis_name, *([None] * (ndim - 1))))


def maybe_row_shard(mesh: Mesh | None, *leaves):
    """Place [n, ...] leaves row-sharded over the mesh's leading axis when n
    divides its extent evenly; otherwise return them unchanged.

    The shared no-padding placement policy for one-pass tables (batch
    scoring, score tables): the per-row work is identical either way, only
    the placement changes, so padding machinery isn't worth it here.
    """
    if mesh is None:
        return leaves
    axis = mesh.axis_names[0]
    if leaves[0].shape[0] % mesh.shape[axis]:
        return leaves
    return tuple(
        jax.device_put(
            leaf, row_sharding(mesh, np.ndim(leaf), axis_name=axis)
        )
        for leaf in leaves
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


MODEL_AXIS = "model"


@jax.tree_util.register_dataclass
@_dataclasses.dataclass(frozen=True)
class FeatureShardedSparse:
    """ELL features sharded over the FEATURE axis (tensor-parallel GLM).

    For d too large to replicate comfortably (SURVEY §7.3 "sparse
    fixed-effect matvec at scale"), each device owns a contiguous feature
    range [j*d_local, (j+1)*d_local) and holds only the ELL entries whose
    feature falls in its range, with LOCAL indices. The coefficient vector is
    sharded over the same axis:

    - ``matvec``: per-device partial margins + one psum over ICI (the
      feature-axis analog of ValueAndGradientAggregator's treeAggregate);
    - ``rmatvec``/``rmatvec_sq``: purely local scatters — each feature is
      owned by exactly one device, no collective at all.

    ``d`` is padded up to a device-count multiple; the padded coefficients
    receive no data gradient (L2 pins them at zero). ``logical_d`` is the
    caller's true feature count.
    """

    local_indices: Array  # [n_dev, n, k_loc] int32, device-local feature ids
    local_values: Array  # [n_dev, n, k_loc]
    d: int = _dataclasses.field(metadata=dict(static=True))  # padded
    logical_d: int = _dataclasses.field(metadata=dict(static=True))
    mesh: Mesh = _dataclasses.field(metadata=dict(static=True))
    axis: str = _dataclasses.field(metadata=dict(static=True))

    @property
    def num_features(self) -> int:
        return self.d

    @property
    def _d_local(self) -> int:
        return self.d // self.mesh.shape[self.axis]

    def matvec(self, w: Array):
        from jax import shard_map

        axis = self.axis
        if w.shape[0] < self.d:
            # Trained models are trimmed to logical_d at the coordinate
            # boundary; re-pad here so scoring accepts them directly.
            w = jnp.pad(w, (0, self.d - w.shape[0]))

        def local(idx, val, w_local):
            z = jnp.sum(val[0] * w_local[idx[0]], axis=-1)
            return jax.lax.psum(z, axis)

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=P(),
        )(self.local_indices, self.local_values, w)

    def _scatter(self, g: Array, squared: bool):
        from jax import shard_map

        d_local = self._d_local

        def local(idx, val, g_rep):
            v = val[0] * val[0] if squared else val[0]
            contrib = v * g_rep[:, None]
            return jnp.zeros(d_local, dtype=contrib.dtype).at[idx[0]].add(
                contrib)

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P()),
            out_specs=P(self.axis),
        )(self.local_indices, self.local_values, g)

    def rmatvec(self, g: Array):
        return self._scatter(g, squared=False)

    def rmatvec_sq(self, g: Array):
        return self._scatter(g, squared=True)


def shard_features_by_column(
    indices: np.ndarray,  # [n, k] host-side global feature ids
    values: np.ndarray,  # [n, k]
    num_features: int,
    mesh: Mesh,
    *,
    axis_name: str = MODEL_AXIS,
    dtype=None,
) -> FeatureShardedSparse:
    """Host-side build: split every row's ELL entries by feature range.

    Per-device slab width is the max over devices of the max per-row local
    nnz — rows hash features roughly uniformly, so the width is ~k/n_dev
    plus skew, not k.
    """
    if dtype is None:
        dtype = values.dtype
    n_dev = int(mesh.shape[axis_name])
    d_pad = ((num_features + n_dev - 1) // n_dev) * n_dev
    d_local = d_pad // n_dev
    n, k = indices.shape
    owner = indices // d_local  # [n, k]
    present = values != 0.0

    k_loc = 1
    for j in range(n_dev):
        sel = present & (owner == j)
        k_loc = max(k_loc, int(sel.sum(axis=1).max(initial=0)))

    li = np.zeros((n_dev, n, k_loc), dtype=np.int32)
    lv = np.zeros((n_dev, n, k_loc), dtype=values.dtype)
    for j in range(n_dev):
        sel = present & (owner == j)
        # Compact this device's entries left per row.
        order = np.argsort(~sel, axis=1, kind="stable")
        idx_c = np.take_along_axis(
            np.where(sel, indices - j * d_local, 0), order, axis=1)
        val_c = np.take_along_axis(
            np.where(sel, values, 0.0), order, axis=1)
        li[j] = idx_c[:, :k_loc]
        lv[j] = val_c[:, :k_loc]

    place = NamedSharding(mesh, P(axis_name, None, None))
    return FeatureShardedSparse(
        local_indices=jax.device_put(jnp.asarray(li), place),
        local_values=jax.device_put(jnp.asarray(lv, dtype=dtype), place),
        d=d_pad,
        logical_d=num_features,
        mesh=mesh,
        axis=axis_name,
    )


def shard_batch(
    batch: GLMBatch, mesh: Mesh, *, axis_name: str = DATA_AXIS
) -> GLMBatch:
    """Pad rows to the device count and place every leaf row-sharded.

    The weight-0 padding rows are inert in all aggregations, so sharded and
    unsharded objectives agree bit-for-bit up to reduction order.
    """
    n_dev = mesh.shape[axis_name]
    batch = pad_batch(batch, n_dev)
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, row_sharding(mesh, np.ndim(leaf), axis_name=axis_name)
        ),
        batch,
    )
