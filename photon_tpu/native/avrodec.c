/* Native Avro binary block decoder.
 *
 * The runtime half of the from-scratch Avro codec (photon_tpu/io/avro.py):
 * the pure-Python record decoder tops out around 50k records/s on
 * bag-of-features data (every record is ~100 varint/string decode calls),
 * which makes ingest decode-bound. This CPython extension walks a
 * pre-compiled schema "program" (nested tuples of integer opcodes built by
 * photon_tpu/io/avro.py:schema_to_program) over one decompressed container
 * block and materializes the same Python objects the interpreter codec
 * produces — dicts for records, lists for arrays, etc. — at millions of
 * records per second.
 *
 * Counterpart of the reference's data-loader layer (AvroUtils.scala:62 /
 * AvroDataReader.scala:54, which lean on the JVM Avro runtime's generated
 * decoders); built lazily by photon_tpu/native/__init__.py with the system
 * compiler and loaded as an extension module, with transparent fallback to
 * the interpreter codec when unavailable.
 *
 * Program encoding (must match schema_to_program):
 *   (0,)                      null
 *   (1,)                      boolean
 *   (2,)                      int/long         -> PyLong
 *   (3,)                      float            -> PyFloat
 *   (4,)                      double           -> PyFloat
 *   (5,)                      string           -> str
 *   (6,)                      bytes            -> bytes
 *   (7, names, progs)         record           -> dict  (names: tuple[str])
 *   (8, item_prog)            array            -> list
 *   (9, value_prog)           map              -> dict
 *   (10, branch_progs)        union            (long index, then branch)
 *   (11, symbols)             enum             -> str
 *   (12, size)                fixed            -> bytes
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

typedef struct {
    const unsigned char *data;
    Py_ssize_t pos;
    Py_ssize_t len;
} Cursor;

static int
cursor_fail(const char *what)
{
    PyErr_Format(PyExc_EOFError, "truncated input: %s", what);
    return -1;
}

/* zigzag varint -> int64; returns -1 on error (with exception set). */
static int
read_long(Cursor *c, long long *out)
{
    unsigned long long acc = 0;
    int shift = 0;
    for (;;) {
        unsigned char b;
        if (c->pos >= c->len)
            return cursor_fail("varint");
        b = c->data[c->pos++];
        acc |= ((unsigned long long)(b & 0x7F)) << shift;
        if (!(b & 0x80))
            break;
        shift += 7;
        if (shift > 63) {
            PyErr_SetString(PyExc_ValueError, "varint too long");
            return -1;
        }
    }
    *out = (long long)(acc >> 1) ^ -(long long)(acc & 1);
    return 0;
}

static int
read_exact(Cursor *c, Py_ssize_t n, const unsigned char **out)
{
    if (n < 0 || c->pos + n > c->len)
        return cursor_fail("bytes");
    *out = c->data + c->pos;
    c->pos += n;
    return 0;
}

/* Forward declaration. */
static PyObject *decode_node(Cursor *c, PyObject *prog);

static PyObject *
decode_node(Cursor *c, PyObject *prog)
{
    long op;
    long long n;
    const unsigned char *raw;

    if (!PyTuple_Check(prog) || PyTuple_GET_SIZE(prog) < 1) {
        PyErr_SetString(PyExc_TypeError, "bad program node");
        return NULL;
    }
    op = PyLong_AsLong(PyTuple_GET_ITEM(prog, 0));
    if (op == -1 && PyErr_Occurred())
        return NULL;

    switch (op) {
    case 0: /* null */
        Py_RETURN_NONE;
    case 1: /* boolean */
        if (read_exact(c, 1, &raw) < 0)
            return NULL;
        if (raw[0])
            Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    case 2: /* int/long */
        if (read_long(c, &n) < 0)
            return NULL;
        return PyLong_FromLongLong(n);
    case 3: { /* float */
        float f;
        if (read_exact(c, 4, &raw) < 0)
            return NULL;
        memcpy(&f, raw, 4);
        return PyFloat_FromDouble((double)f);
    }
    case 4: { /* double */
        double d;
        if (read_exact(c, 8, &raw) < 0)
            return NULL;
        memcpy(&d, raw, 8);
        return PyFloat_FromDouble(d);
    }
    case 5: /* string */
        if (read_long(c, &n) < 0)
            return NULL;
        if (read_exact(c, (Py_ssize_t)n, &raw) < 0)
            return NULL;
        return PyUnicode_DecodeUTF8((const char *)raw, (Py_ssize_t)n, NULL);
    case 6: /* bytes */
        if (read_long(c, &n) < 0)
            return NULL;
        if (read_exact(c, (Py_ssize_t)n, &raw) < 0)
            return NULL;
        return PyBytes_FromStringAndSize((const char *)raw, (Py_ssize_t)n);
    case 7: { /* record */
        PyObject *names = PyTuple_GET_ITEM(prog, 1);
        PyObject *progs = PyTuple_GET_ITEM(prog, 2);
        Py_ssize_t nf = PyTuple_GET_SIZE(names);
        PyObject *d = PyDict_New();
        Py_ssize_t i;
        if (d == NULL)
            return NULL;
        for (i = 0; i < nf; i++) {
            PyObject *v = decode_node(c, PyTuple_GET_ITEM(progs, i));
            if (v == NULL) {
                Py_DECREF(d);
                return NULL;
            }
            if (PyDict_SetItem(d, PyTuple_GET_ITEM(names, i), v) < 0) {
                Py_DECREF(v);
                Py_DECREF(d);
                return NULL;
            }
            Py_DECREF(v);
        }
        return d;
    }
    case 8: { /* array: blocks until 0 count; negative => byte size follows */
        PyObject *item_prog = PyTuple_GET_ITEM(prog, 1);
        PyObject *list = PyList_New(0);
        if (list == NULL)
            return NULL;
        for (;;) {
            long long count, i;
            if (read_long(c, &count) < 0)
                goto arr_fail;
            if (count == 0)
                break;
            if (count < 0) {
                long long sz;
                count = -count;
                if (read_long(c, &sz) < 0)
                    goto arr_fail;
            }
            for (i = 0; i < count; i++) {
                PyObject *v = decode_node(c, item_prog);
                if (v == NULL)
                    goto arr_fail;
                if (PyList_Append(list, v) < 0) {
                    Py_DECREF(v);
                    goto arr_fail;
                }
                Py_DECREF(v);
            }
        }
        return list;
    arr_fail:
        Py_DECREF(list);
        return NULL;
    }
    case 9: { /* map */
        PyObject *val_prog = PyTuple_GET_ITEM(prog, 1);
        PyObject *d = PyDict_New();
        if (d == NULL)
            return NULL;
        for (;;) {
            long long count, i;
            if (read_long(c, &count) < 0)
                goto map_fail;
            if (count == 0)
                break;
            if (count < 0) {
                long long sz;
                count = -count;
                if (read_long(c, &sz) < 0)
                    goto map_fail;
            }
            for (i = 0; i < count; i++) {
                PyObject *k, *v;
                long long klen;
                if (read_long(c, &klen) < 0)
                    goto map_fail;
                if (read_exact(c, (Py_ssize_t)klen, &raw) < 0)
                    goto map_fail;
                k = PyUnicode_DecodeUTF8(
                    (const char *)raw, (Py_ssize_t)klen, NULL);
                if (k == NULL)
                    goto map_fail;
                v = decode_node(c, val_prog);
                if (v == NULL) {
                    Py_DECREF(k);
                    goto map_fail;
                }
                if (PyDict_SetItem(d, k, v) < 0) {
                    Py_DECREF(k);
                    Py_DECREF(v);
                    goto map_fail;
                }
                Py_DECREF(k);
                Py_DECREF(v);
            }
        }
        return d;
    map_fail:
        Py_DECREF(d);
        return NULL;
    }
    case 10: { /* union */
        PyObject *branches = PyTuple_GET_ITEM(prog, 1);
        if (read_long(c, &n) < 0)
            return NULL;
        if (n < 0 || n >= PyTuple_GET_SIZE(branches)) {
            PyErr_Format(PyExc_ValueError,
                         "union index %lld out of range", n);
            return NULL;
        }
        return decode_node(c, PyTuple_GET_ITEM(branches, (Py_ssize_t)n));
    }
    case 11: { /* enum */
        PyObject *symbols = PyTuple_GET_ITEM(prog, 1);
        PyObject *sym;
        if (read_long(c, &n) < 0)
            return NULL;
        if (n < 0 || n >= PyTuple_GET_SIZE(symbols)) {
            PyErr_Format(PyExc_ValueError,
                         "enum index %lld out of range", n);
            return NULL;
        }
        sym = PyTuple_GET_ITEM(symbols, (Py_ssize_t)n);
        Py_INCREF(sym);
        return sym;
    }
    case 12: { /* fixed */
        long long size = PyLong_AsLongLong(PyTuple_GET_ITEM(prog, 1));
        if (size == -1 && PyErr_Occurred())
            return NULL;
        if (read_exact(c, (Py_ssize_t)size, &raw) < 0)
            return NULL;
        return PyBytes_FromStringAndSize((const char *)raw,
                                         (Py_ssize_t)size);
    }
    default:
        PyErr_Format(PyExc_ValueError, "bad opcode %ld", op);
        return NULL;
    }
}

/* decode_block(data: bytes, count: int, program: tuple) -> list */
static PyObject *
avrodec_decode_block(PyObject *self, PyObject *args)
{
    Py_buffer buf;
    Py_ssize_t count, i;
    PyObject *prog, *out;
    Cursor c;

    if (!PyArg_ParseTuple(args, "y*nO", &buf, &count, &prog))
        return NULL;
    c.data = (const unsigned char *)buf.buf;
    c.pos = 0;
    c.len = buf.len;

    out = PyList_New(count);
    if (out == NULL) {
        PyBuffer_Release(&buf);
        return NULL;
    }
    for (i = 0; i < count; i++) {
        PyObject *rec = decode_node(&c, prog);
        if (rec == NULL) {
            Py_DECREF(out);
            PyBuffer_Release(&buf);
            return NULL;
        }
        PyList_SET_ITEM(out, i, rec); /* steals */
    }
    if (c.pos != c.len) {
        PyErr_Format(PyExc_ValueError,
                     "block decode consumed %zd of %zd bytes",
                     c.pos, c.len);
        Py_DECREF(out);
        PyBuffer_Release(&buf);
        return NULL;
    }
    PyBuffer_Release(&buf);
    return out;
}

static PyMethodDef avrodec_methods[] = {
    {"decode_block", avrodec_decode_block, METH_VARARGS,
     "Decode one decompressed Avro container block into a list of records."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef avrodec_module = {
    PyModuleDef_HEAD_INIT, "photon_avrodec",
    "Native Avro binary block decoder.", -1, avrodec_methods,
};

PyMODINIT_FUNC
PyInit_photon_avrodec(void)
{
    return PyModule_Create(&avrodec_module);
}
