"""Native runtime components, built lazily with the system toolchain.

The compute path is JAX/XLA; the HOST runtime around it (here: the Avro
block decoder feeding ingest) is native C, mirroring how the reference
leans on the JVM Avro runtime's generated decoders (AvroUtils.scala:62)
rather than interpreting schemas per record.

``get_avro_decoder()`` compiles ``avrodec.c`` into a per-user cache
directory on first use (source-hash keyed, so edits rebuild) and returns
the extension module, or None when no working compiler is available —
callers fall back to the interpreter codec, so the native layer is a pure
accelerator, never a dependency.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger(__name__)

_SOURCE = os.path.join(os.path.dirname(__file__), "avrodec.c")
_cached = None
_failed = False


def _cache_dir() -> str:
    base = os.environ.get(
        "PHOTON_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "photon_tpu_native"),
    )
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> str | None:
    with open(_SOURCE, "rb") as f:
        src = f.read()
    tag = hashlib.blake2b(
        src + sysconfig.get_config_var("EXT_SUFFIX").encode(),
        digest_size=8,
    ).hexdigest()
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_cache_dir(), f"photon_avrodec_{tag}{ext}")
    if os.path.exists(out):
        return out
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "cc")
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = [cc, "-O2", "-fPIC", "-shared", f"-I{include}", _SOURCE, "-o", tmp]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        logger.info(
            "native avro decoder unavailable (%s: %s); falling back to the "
            "interpreter codec", e, detail.decode(errors="replace")[:500],
        )
        # A failed compile can leave a partial object behind; the tmp name
        # is per-pid, so stragglers would accumulate in the shared cache.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    os.replace(tmp, out)
    return out


def get_avro_decoder():
    """The compiled ``photon_avrodec`` module, or None (fallback)."""
    global _cached, _failed
    if _cached is not None or _failed:
        return _cached
    path = None
    try:
        path = _build()
        if path is None:
            _failed = True
            return None
        spec = importlib.util.spec_from_file_location("photon_avrodec", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _cached = mod
    except Exception as e:  # any load failure -> interpreter fallback
        logger.info("native avro decoder failed to load (%s)", e)
        # A corrupted cache file would otherwise poison every later
        # process; drop it so the next call rebuilds from source.
        try:
            if path is not None:
                os.unlink(path)
        except OSError:
            pass
        _failed = True
        return None
    return _cached
