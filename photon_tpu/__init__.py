"""photon-tpu: a TPU-native GLM / GLMix (GAME) training framework.

A from-scratch JAX/XLA re-design of the capabilities of LinkedIn Photon-ML
(Spark/Scala): generalized linear models (linear, logistic, Poisson,
smoothed-hinge SVM), GLMix mixed-effect models trained by block coordinate
descent, L-BFGS / OWL-QN / TRON optimizers, normalization, evaluation,
hyperparameter tuning, and Avro-compatible model I/O — with Spark RDD
machinery replaced by sharded device arrays, XLA collectives, and vmapped
batched per-entity solvers.
"""

__version__ = "0.1.0"
