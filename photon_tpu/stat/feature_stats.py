"""Per-feature summary statistics: one pass over the feature matrix.

TPU-native counterpart of FeatureDataStatistics (photon-lib
stat/FeatureDataStatistics.scala:44-139), which wraps Spark's
MultivariateOnlineSummarizer: weighted per-feature mean / variance / min /
max / numNonzeros over all rows, implicit zeros included. Feeds
NormalizationContext construction (build_normalization_context) and the
feature-stats Avro output of the training driver
(GameTrainingDriver.calculateAndSaveFeatureShardStats :616-647).

Moments come from the batch's fused matvec reductions (rmatvec /
rmatvec_sq — device kernels); min/max/nnz are host-side numpy over the ELL
slabs (computed once at ingest, like the reference's one summarizer pass).
Variance uses the same unbiased weighted estimator as Spark's summarizer:
  var_j = (sumW / (sumW - 1)) * (E[x^2] - E[x]^2).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_tpu.data.dataset import (
    DenseFeatures,
    DualEllFeatures,
    Features,
    SparseFeatures,
)


@dataclasses.dataclass(frozen=True)
class FeatureDataStatistics:
    """Reference: stat/FeatureDataStatistics.scala:44."""

    mean: np.ndarray  # [d] weighted mean
    variance: np.ndarray  # [d] unbiased weighted variance
    min: np.ndarray  # [d]
    max: np.ndarray  # [d]
    num_nonzeros: np.ndarray  # [d] weighted nnz count
    count: float  # total weight
    intercept_index: int | None = None
    # Weighted norms (Spark summarizer normL1 = sum w|x|, normL2 =
    # sqrt(sum w x^2)) — consumed by the feature-stats output artifact
    # (ModelProcessingUtils.writeBasicStatistics metrics map).
    norm_l1: np.ndarray | None = None  # [d]
    norm_l2: np.ndarray | None = None  # [d]

    @property
    def dim(self) -> int:
        return self.mean.shape[0]

    @staticmethod
    def from_features(
        features: Features,
        weights: np.ndarray | None = None,
        *,
        intercept_index: int | None = None,
    ) -> "FeatureDataStatistics":
        if isinstance(features, DenseFeatures):
            x = np.asarray(features.x, dtype=np.float64)
            n, d = x.shape
            w = np.ones(n) if weights is None else np.asarray(
                weights, dtype=np.float64)
            sum_w = float(w.sum())
            mean = (w @ x) / sum_w
            ex2 = (w @ (x * x)) / sum_w
            norm_l1 = w @ np.abs(x)
            # Spark's MultivariateOnlineSummarizer skips non-positive-weight
            # rows entirely; keep min/max parity by masking them out.
            xw = x[w > 0.0]
            if xw.shape[0] == 0:
                mn = np.zeros(d)
                mx = np.zeros(d)
            else:
                mn = xw.min(axis=0)
                mx = xw.max(axis=0)
            nnz = (w[:, None] * (x != 0.0)).sum(axis=0)
        else:
            assert isinstance(features, (SparseFeatures, DualEllFeatures))
            idx = np.asarray(features.indices)
            val = np.asarray(features.values, dtype=np.float64)
            if isinstance(features, DualEllFeatures):
                # Fold the COO overflow tail back into extra ELL columns so
                # the one-pass reductions below see every entry.
                tr = np.asarray(features.tail_rows)
                if tr.size:
                    n_rows = idx.shape[0]
                    extra = int(np.bincount(tr, minlength=n_rows).max())
                    idx = np.concatenate(
                        [idx, np.zeros((n_rows, extra), idx.dtype)], axis=1)
                    val = np.concatenate(
                        [val, np.zeros((n_rows, extra), val.dtype)], axis=1)
                    slot = np.zeros(n_rows, dtype=np.int64)
                    base = idx.shape[1] - extra
                    for r, fi, fv in zip(
                        tr,
                        np.asarray(features.tail_indices),
                        np.asarray(features.tail_values, dtype=np.float64),
                    ):
                        idx[r, base + slot[r]] = fi
                        val[r, base + slot[r]] = fv
                        slot[r] += 1
            n = idx.shape[0]
            d = features.d
            w = np.ones(n) if weights is None else np.asarray(
                weights, dtype=np.float64)
            sum_w = float(w.sum())
            # Zero-weight rows are skipped entirely (min/max, nnz, implicit-
            # zero detection), matching Spark's MultivariateOnlineSummarizer.
            present = (val != 0.0) & (w[:, None] > 0.0)
            n_pos = int((w > 0.0).sum())
            flat_idx = idx[present]
            flat_val = val[present]
            flat_w = np.broadcast_to(w[:, None], idx.shape)[present]
            s1 = np.zeros(d)
            s2 = np.zeros(d)
            nnz = np.zeros(d)
            norm_l1 = np.zeros(d)
            np.add.at(s1, flat_idx, flat_w * flat_val)
            np.add.at(s2, flat_idx, flat_w * flat_val * flat_val)
            np.add.at(nnz, flat_idx, flat_w)
            np.add.at(norm_l1, flat_idx, flat_w * np.abs(flat_val))
            mean = s1 / sum_w
            ex2 = s2 / sum_w
            # min/max over stored values; implicit zeros count whenever a
            # column has any row without that feature.
            mn = np.full(d, np.inf)
            mx = np.full(d, -np.inf)
            np.minimum.at(mn, flat_idx, flat_val)
            np.maximum.at(mx, flat_idx, flat_val)
            rows_per_col = np.zeros(d)
            np.add.at(rows_per_col, flat_idx, 1.0)
            has_zero = rows_per_col < n_pos
            mn = np.where(has_zero, np.minimum(mn, 0.0), mn)
            mx = np.where(has_zero, np.maximum(mx, 0.0), mx)
            mn = np.where(np.isinf(mn), 0.0, mn)
            mx = np.where(np.isinf(mx), 0.0, mx)

        correction = sum_w / max(sum_w - 1.0, 1.0)
        variance = np.maximum(correction * (ex2 - mean * mean), 0.0)
        return FeatureDataStatistics(
            mean=mean,
            variance=variance,
            min=mn,
            max=mx,
            num_nonzeros=nnz,
            count=sum_w,
            intercept_index=intercept_index,
            norm_l1=norm_l1,
            norm_l2=np.sqrt(np.maximum(ex2 * sum_w, 0.0)),
        )
