from photon_tpu.stat.feature_stats import FeatureDataStatistics

__all__ = ["FeatureDataStatistics"]
