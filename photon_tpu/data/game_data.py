"""GameDataset: the canonical columnar table every coordinate trains against.

TPU-native counterpart of the reference's ``RDD[(UniqueSampleId, GameDatum)]``
(photon-api data/GameDatum.scala:37, GameConverters.scala:28): response /
offset / weight columns, one feature matrix per feature shard, and integer-
coded id tags (the ``idTagToValueMap``: random-effect grouping columns and
evaluation grouping columns).

Because every array shares one canonical row order fixed at ingest, all of
the reference's join/groupByKey plumbing (keying by uid, routing residuals by
REId) reduces to index arithmetic: a coordinate's scores are a [n] device
array aligned with this table (the CoordinateDataScores equivalent,
data/scoring/CoordinateDataScores.scala:30).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import Features, GLMBatch

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class IdTag:
    """One grouping column: dense int codes + the key vocabulary."""

    codes: Array  # [n] int32
    vocab: dict  # str key -> code
    inverse: tuple  # code -> str key

    @property
    def num_groups(self) -> int:
        return len(self.inverse)

    @staticmethod
    def from_raw(raw_ids) -> "IdTag":
        # Entity keys are normalized to str at ingest: the Avro model format
        # stores modelId as a string (BayesianLinearModelAvro), so keeping
        # numeric keys here would make every vocab lookup after a model
        # reload miss silently ('5' vs np.int64(5)).
        raw = np.asarray(raw_ids)
        uniq, codes = np.unique(raw, return_inverse=True)
        keys = tuple(
            str(k.item() if hasattr(k, "item") else k) for k in uniq
        )
        if len(set(keys)) != len(keys):
            raise ValueError(
                "id tag keys collide after str normalization"
            )
        return IdTag(
            codes=jnp.asarray(codes.astype(np.int32)),
            vocab={k: i for i, k in enumerate(keys)},
            inverse=keys,
        )


@dataclasses.dataclass(frozen=True)
class GameDataset:
    """Columnar GAME table in canonical row order."""

    labels: Array  # [n]
    offsets: Array  # [n]
    weights: Array  # [n]
    feature_shards: dict[str, Features]
    id_tags: dict[str, IdTag]
    uids: np.ndarray | None = None  # host-side original row ids, optional

    @property
    def num_samples(self) -> int:
        return int(self.labels.shape[0])

    def shard_batch(self, shard_id: str) -> GLMBatch:
        """A GLMBatch view for one feature shard (FixedEffectDataset
        equivalent, data/FixedEffectDataset.scala:32)."""
        return GLMBatch(
            features=self.feature_shards[shard_id],
            labels=self.labels,
            offsets=self.offsets,
            weights=self.weights,
        )

    def tag_codes(self, tag: str) -> tuple[Array, int]:
        t = self.id_tags[tag]
        return t.codes, t.num_groups


def make_game_dataset(
    labels,
    feature_shards: dict[str, Features],
    *,
    offsets=None,
    weights=None,
    id_tags: dict[str, np.ndarray] | None = None,
    uids=None,
    dtype=jnp.float32,
) -> GameDataset:
    labels = jnp.asarray(np.asarray(labels), dtype=dtype)
    n = labels.shape[0]
    for name, feats in feature_shards.items():
        rows = (feats.x.shape[0] if hasattr(feats, "x") else feats.indices.shape[0])
        if rows != n:
            raise ValueError(
                f"feature shard {name!r} has {rows} rows, expected {n}")
    return GameDataset(
        labels=labels,
        offsets=(jnp.zeros(n, dtype) if offsets is None
                 else jnp.asarray(np.asarray(offsets), dtype)),
        weights=(jnp.ones(n, dtype) if weights is None
                 else jnp.asarray(np.asarray(weights), dtype)),
        feature_shards=dict(feature_shards),
        id_tags={k: IdTag.from_raw(v) for k, v in (id_tags or {}).items()},
        uids=None if uids is None else np.asarray(uids),
    )
