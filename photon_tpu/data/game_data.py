"""GameDataset: the canonical columnar table every coordinate trains against.

TPU-native counterpart of the reference's ``RDD[(UniqueSampleId, GameDatum)]``
(photon-api data/GameDatum.scala:37, GameConverters.scala:28): response /
offset / weight columns, one feature matrix per feature shard, and integer-
coded id tags (the ``idTagToValueMap``: random-effect grouping columns and
evaluation grouping columns).

Because every array shares one canonical row order fixed at ingest, all of
the reference's join/groupByKey plumbing (keying by uid, routing residuals by
REId) reduces to index arithmetic: a coordinate's scores are a [n] device
array aligned with this table (the CoordinateDataScores equivalent,
data/scoring/CoordinateDataScores.scala:30).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import (
    DenseFeatures,
    DualEllFeatures,
    Features,
    GLMBatch,
    SparseFeatures,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class IdTag:
    """One grouping column: dense int codes + the key vocabulary."""

    codes: Array  # [n] int32
    vocab: dict  # str key -> code
    inverse: tuple  # code -> str key
    # Host mirror of ``codes``: the ingest planner (entity grouping,
    # reservoir sampling) is host-side numpy; keeping the codes it was built
    # from avoids a device->host round trip per dataset build.
    codes_np: np.ndarray | None = None

    @property
    def num_groups(self) -> int:
        return len(self.inverse)

    def host_codes(self) -> np.ndarray:
        if self.codes_np is not None:
            return self.codes_np
        return np.asarray(self.codes)

    @staticmethod
    def from_raw(raw_ids) -> "IdTag":
        # Entity keys are normalized to str at ingest: the Avro model format
        # stores modelId as a string (BayesianLinearModelAvro), so keeping
        # numeric keys here would make every vocab lookup after a model
        # reload miss silently ('5' vs np.int64(5)).
        raw = np.asarray(raw_ids)
        uniq, codes = np.unique(raw, return_inverse=True)
        keys = tuple(
            str(k.item() if hasattr(k, "item") else k) for k in uniq
        )
        if len(set(keys)) != len(keys):
            raise ValueError(
                "id tag keys collide after str normalization"
            )
        codes = codes.astype(np.int32)
        return IdTag(
            codes=jnp.asarray(codes),
            vocab={k: i for i, k in enumerate(keys)},
            inverse=keys,
            codes_np=codes,
        )


@dataclasses.dataclass(frozen=True)
class GameDataset:
    """Columnar GAME table in canonical row order."""

    labels: Array  # [n]
    offsets: Array  # [n]
    weights: Array  # [n]
    feature_shards: dict[str, Features]
    id_tags: dict[str, IdTag]
    uids: np.ndarray | None = None  # host-side original row ids, optional
    # Host numpy mirrors captured at ingest (``make_game_dataset`` stashes
    # the numpy inputs before pushing them to the device). The dataset-build
    # planner works entirely on these, so ingest never pulls device arrays
    # back over the (potentially slow) host<->device link. Keys:
    # "labels"/"offsets"/"weights" -> [n] column arrays;
    # ("shard", <name>) -> the ELL view of ``host_shard_coo``;
    # ("tail", <name>) -> the COO overflow of ``host_shard_tail``.
    # Shard names live in their own tuple namespace so a shard named,
    # say, "weights" cannot clobber the column mirror.
    host: dict | None = None

    @property
    def num_samples(self) -> int:
        return int(self.labels.shape[0])

    def host_column(self, name: str) -> np.ndarray:
        """Host view of labels/offsets/weights (mirror or cached pull)."""
        if self.host is not None and name in self.host:
            return self.host[name]
        view = np.asarray(getattr(self, name))
        if self.host is not None:
            self.host[name] = view
        return view

    def host_shard_coo(self, shard_id: str):
        """Host-side ``(indices [n, k], values [n, k], d)`` ELL view of a
        feature shard, preferring the ingest-time mirror. Computed views are
        cached into the mirror dict so repeated planning passes pull the
        device data at most once.

        For ``DualEllFeatures`` this is the bounded-width SLAB only — the
        overflow entries live in ``host_shard_tail`` (re-widening the slab
        to the widest row would reintroduce exactly the memory hazard the
        dual-ELL layout bounds, SURVEY §7.3)."""
        key = ("shard", shard_id)
        if self.host is not None and key in self.host:
            return self.host[key]
        feats = self.feature_shards[shard_id]
        if isinstance(feats, DenseFeatures):
            x = np.asarray(feats.x)
            n, d = x.shape
            idx = np.broadcast_to(np.arange(d, dtype=np.int32), (n, d))
            view = (idx, x, d)
        elif isinstance(feats, (SparseFeatures, DualEllFeatures)):
            view = (
                np.asarray(feats.indices), np.asarray(feats.values), feats.d
            )
        else:
            raise TypeError(
                f"shard {shard_id!r}: no host COO view for "
                f"{type(feats).__name__}"
            )
        if self.host is not None:
            self.host[key] = view
        return view

    def host_shard_tail(self, shard_id: str):
        """Host ``(rows, indices, values)`` COO overflow of a DualEll shard
        (rows sorted ascending), or None for rectangular layouts."""
        feats = self.feature_shards[shard_id]
        if not isinstance(feats, DualEllFeatures):
            return None
        key = ("tail", shard_id)
        if self.host is not None and key in self.host:
            return self.host[key]
        tail = (
            np.asarray(feats.tail_rows),
            np.asarray(feats.tail_indices),
            np.asarray(feats.tail_values),
        )
        if tail[0].size == 0:
            tail = None
        if self.host is not None:
            self.host[key] = tail
        return tail

    def shard_batch(self, shard_id: str) -> GLMBatch:
        """A GLMBatch view for one feature shard (FixedEffectDataset
        equivalent, data/FixedEffectDataset.scala:32)."""
        return GLMBatch(
            features=self.feature_shards[shard_id],
            labels=self.labels,
            offsets=self.offsets,
            weights=self.weights,
        )

    def tag_codes(self, tag: str) -> tuple[Array, int]:
        t = self.id_tags[tag]
        return t.codes, t.num_groups


def make_game_dataset(
    labels,
    feature_shards: dict[str, Features],
    *,
    offsets=None,
    weights=None,
    id_tags: dict[str, np.ndarray] | None = None,
    uids=None,
    dtype=jnp.float32,
) -> GameDataset:
    np_dtype = np.dtype(dtype)
    labels_np = np.asarray(labels, dtype=np_dtype)
    n = labels_np.shape[0]
    offsets_np = (
        np.zeros(n, np_dtype) if offsets is None
        else np.asarray(offsets, dtype=np_dtype)
    )
    weights_np = (
        np.ones(n, np_dtype) if weights is None
        else np.asarray(weights, dtype=np_dtype)
    )
    host: dict = {
        "labels": labels_np, "offsets": offsets_np, "weights": weights_np,
    }
    # Feature shards may arrive with host numpy arrays inside (the cheap way
    # to ingest: the dataset build plans on the numpy mirror and the device
    # copy is pushed exactly once, here). Device-backed shards pass through
    # untouched (no mirror; host views fall back to a one-time pull).
    # jax.device_put moves large host buffers ~2x faster than jnp.asarray
    # (no trace/convert layer), and EVERY push — all shards' arrays plus
    # the three columns — batches into ONE device_put call, enqueued
    # asynchronously so the ingest planner starts on the host mirrors
    # while the raw data is still crossing the link (the transfer time is
    # accounted in PIPELINE_STATS as "raw_transfer").
    from photon_tpu.data.pipeline import PIPELINE_STATS

    staged: list[np.ndarray] = []

    def stage_arr(arr: np.ndarray) -> int:
        staged.append(arr)
        return len(staged) - 1

    specs: dict[str, tuple] = {}
    shards: dict[str, Features] = {}
    for name, feats in feature_shards.items():
        rows = (feats.x.shape[0] if hasattr(feats, "x") else feats.indices.shape[0])
        if rows != n:
            raise ValueError(
                f"feature shard {name!r} has {rows} rows, expected {n}")
        if isinstance(feats, DenseFeatures) and isinstance(feats.x, np.ndarray):
            x = np.asarray(feats.x, dtype=np_dtype)
            d = x.shape[1]
            host[("shard", name)] = (
                np.broadcast_to(np.arange(d, dtype=np.int32), x.shape), x, d,
            )
            specs[name] = ("dense", stage_arr(x))
        elif isinstance(feats, SparseFeatures) and isinstance(
            feats.indices, np.ndarray
        ):
            idx = np.asarray(feats.indices, dtype=np.int32)
            val = np.asarray(feats.values, dtype=np_dtype)
            host[("shard", name)] = (idx, val, feats.d)
            specs[name] = ("sparse", stage_arr(idx), stage_arr(val), feats.d)
        shards[name] = feats
    i_lab = stage_arr(labels_np)
    i_off = stage_arr(offsets_np)
    i_wt = stage_arr(weights_np)
    with PIPELINE_STATS.stage("raw_transfer"):
        devs = jax.device_put(staged)
    for name, spec in specs.items():
        if spec[0] == "dense":
            shards[name] = DenseFeatures(devs[spec[1]])
        else:
            shards[name] = SparseFeatures(
                devs[spec[1]], devs[spec[2]], spec[3]
            )
    return GameDataset(
        labels=devs[i_lab],
        offsets=devs[i_off],
        weights=devs[i_wt],
        feature_shards=shards,
        id_tags={k: IdTag.from_raw(v) for k, v in (id_tags or {}).items()},
        uids=None if uids is None else np.asarray(uids),
        host=host,
    )
