"""Pipelined ingest: executors, stage accounting, and chunked transfer.

The reference's ingest is a cluster-wide shuffle pipeline
(RandomEffectDataset.scala's groupBy/foldByKey); ours is a host-side numpy
planning pass feeding one packed device transfer and one AOT compile. Run
serially those three phases ADD (bench round 5: ``e2e_seconds =
ingest_seconds + compile_seconds``, and the planner fell below the 1M
rows/s ingest floor). This module owns the machinery that overlaps them:

- **Planning executors** (``plan_executor`` / ``chunk_executor``): the
  per-coordinate planning passes run concurrently (the hot numpy ops —
  radix argsort, bincount, fancy gathers — release the GIL), and
  within-coordinate elementwise passes chunk over rows
  (``map_chunked`` / ``bincount_chunked`` — exact, order-preserving, so
  results are BIT-IDENTICAL to the serial path; the deterministic
  reservoir hash order is the contract). Two separate pools: coordinate
  tasks block on their own chunk tasks, so running both levels on one
  bounded pool could deadlock (all workers waiting on queued chunks).
- **Chunked double-buffered transfer** (``packed_device_put``): the single
  packed plan buffer is pushed as granule-aligned chunks with each
  ``jax.device_put`` enqueued ASYNCHRONOUSLY while the host fills the
  next chunk's staging buffer, then fused into the one contiguous buffer
  by a donated in-trace concatenate (the chunk buffers' HBM is donated,
  so peak device memory stays ~1x). Small builds (below one chunk) take
  the legacy single-shot path — byte-identical layout either way.
- **PIPELINE_STATS**: per-stage seconds (plan / pack / transfer /
  compile / compile_wait) + the measured compile-overlap fraction, reset
  per prepare and reported by ``bench.py``.

``PHOTON_TPU_SERIAL_INGEST=1`` forces everything back to the serial
in-line path (the determinism property tests diff the two);
``PHOTON_TPU_INGEST_THREADS`` bounds the chunk pool (CI uses 2).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

logger = logging.getLogger(__name__)

# Program contract (audited by `python -m photon_tpu.analysis --semantic`;
# machinery in analysis/program.py): the ingest pipeline's AOT warm-compile
# entry must trace EXACTLY the programs the production fused fit runs — the
# skeleton-predicted materialize/fit jaxprs match the real generation's
# signatures (dispatch census unchanged: warm compile adds ZERO programs),
# and the overlap window introduces no host callback into either jaxpr.
PROGRAM_AUDIT = dict(
    name="ingest-pipeline",
    entry="data.pipeline + estimators.game_estimator._warm_compile "
    "(AOT warm compile from predicted shapes)",
    builder="build_ingest_pipeline",
    max_programs=2,
    stable_under=("aot_warm_compile",),
    hot_loop=True,
)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`; machinery in analysis/concurrency.py). The threading
# model: `_Pool._lock` guards lazy pool construction/teardown;
# `PipelineStats._stats_lock` guards every accounting map plus the
# generation counter (worker threads in all three pools write stages
# concurrently with the training thread's reset). The two locks carry
# DISTINCT terminal names on purpose: the auditor identifies locks by
# terminal name within a module (and flags ambiguity), which is what
# keeps its lock-order and lockset checks sound here. Chunk thunks
# (`map_chunked.run`) are pure numpy over disjoint row spans — no JAX
# dispatch off-thread here; the AOT compile thread's dispatch is
# declared (with its reason) in game_estimator's contract, next to
# `_warm_compile` itself. `_concat_cache` is deliberately NOT
# lock-guarded: it is written only from the single thread that runs
# `packed_device_put`, and the worst case of a future race is one
# duplicate jit wrapper, never corruption.
CONCURRENCY_AUDIT = dict(
    name="ingest-pipeline",
    locks={
        "_Pool._lock": ("_Pool._pool",),
        "PipelineStats._stats_lock": (
            "PipelineStats._generation",
            "PipelineStats._seconds",
            "PipelineStats._spans",
            "PipelineStats._counts",
        ),
    },
    thread_entries=("map_chunked.run",),
    jax_dispatch_ok={},
)


def serial_ingest() -> bool:
    """True when the serial reference path is forced (env contract)."""
    return os.environ.get("PHOTON_TPU_SERIAL_INGEST", "") == "1"


def ingest_threads() -> int:
    raw = os.environ.get("PHOTON_TPU_INGEST_THREADS", "")
    if raw.isdigit() and int(raw) > 0:
        return int(raw)
    return min(8, os.cpu_count() or 1)


# Minimum rows before an elementwise pass is worth chunking across
# threads: below this the submit/join overhead exceeds the work.
_CHUNK_MIN_ROWS = 1 << 19
_TRANSFER_GRANULE_ELEMS = (4 << 20) // 4  # 4 MiB of int32 elements


def transfer_chunk_elems() -> int:
    """Transfer chunk size in int32 elements (PHOTON_TPU_TRANSFER_CHUNK_MB,
    default 64 MiB), rounded up to the packed buffer's 4 MiB granule so
    every chunk but the last has one recurring transfer shape."""
    raw = os.environ.get("PHOTON_TPU_TRANSFER_CHUNK_MB", "")
    mb = int(raw) if raw.isdigit() and int(raw) > 0 else 64
    elems = (mb << 20) // 4
    g = _TRANSFER_GRANULE_ELEMS
    return max(-(-elems // g) * g, g)


class _Immediate(Future):
    """Already-resolved future for the serial in-line path."""

    def __init__(self, result=None, exc=None):
        super().__init__()
        if exc is not None:
            self.set_exception(exc)
        else:
            self.set_result(result)


class _Pool:
    """Lazy thread pool that degrades to in-line execution when serial
    ingest is forced (or only one worker would exist)."""

    def __init__(self, name: str, workers):
        self._name = name
        self._workers = workers  # int or callable () -> int
        self._pool: ThreadPoolExecutor | None = None
        self._lock = threading.Lock()

    def _resolve_workers(self) -> int:
        w = self._workers
        return w() if callable(w) else w

    def submit(self, fn, *args, **kwargs) -> Future:
        if serial_ingest() or self._resolve_workers() <= 1:
            try:
                return _Immediate(fn(*args, **kwargs))
            except Exception as exc:  # noqa: BLE001 — parity with Future
                return _Immediate(exc=exc)
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._resolve_workers(),
                    thread_name_prefix=self._name,
                )
            # Submit INSIDE the lock: shutdown() swaps the pool out
            # under this lock before shutting it down, so a submit that
            # escaped the critical section could land on an executor
            # already past shutdown ("cannot schedule new futures").
            # Executor.submit is a quick enqueue; the blocking
            # shutdown(wait=True) stays outside the lock.
            return self._pool.submit(fn, *args, **kwargs)

    def shutdown(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)


# Coordinate-level planning tasks (each may block on its own chunk tasks,
# hence the separate pool) and one background slot for the AOT warm
# compile (XLA compiles in C++ with the GIL released).
plan_executor = _Pool("photon-plan", 4)
chunk_executor = _Pool("photon-chunk", ingest_threads)
compile_executor = _Pool("photon-compile", 2)


def reset_executors() -> None:
    """Drop pools so the next use re-reads the env (tests).

    Nested try/finally: a shutdown that raises (an interpreter tearing
    down, a worker's late exception surfacing in join) must still shut
    the remaining pools down — leaking the chunk or compile pool after
    a failed plan-pool shutdown strands daemon-less workers."""
    try:
        plan_executor.shutdown()
    finally:
        try:
            chunk_executor.shutdown()
        finally:
            compile_executor.shutdown()


def consume_futures(futs) -> list:
    """``[f.result() for f in futs]`` that consumes EVERY future.

    The naive loop abandons the remaining futures on the first raising
    ``result()`` — their thunks keep running and any exception they
    raise is silently swallowed (the auditor's ``dropped-future`` class,
    in its dynamic form). Here every future is awaited; the FIRST
    exception propagates (matching the naive loop's contract) after the
    rest completed, and later exceptions are logged so no failure is
    invisible."""
    results: list = []
    first_exc: Exception | None = None
    for f in futs:
        try:
            results.append(f.result())
        # Exception, NOT BaseException: a main-thread KeyboardInterrupt
        # or SystemExit delivered while blocked in result() must abort
        # the wait immediately — deferring it until every remaining
        # thunk completes could hold the interrupt for minutes.
        except Exception as exc:  # noqa: BLE001 — re-raised below
            if first_exc is None:
                first_exc = exc
            else:
                logger.warning(
                    "additional worker-thunk failure (first is being "
                    "re-raised): %r", exc,
                )
    if first_exc is not None:
        raise first_exc
    return results


class PipelineStats:
    """Thread-safe per-stage wall-clock accounting for one ingest.

    Stage seconds ACCUMULATE (two coordinates planning concurrently both
    add their thread-local seconds — the report also keeps the wall span
    per stage, which is what overlap claims are judged on).
    """

    def __init__(self):
        self._stats_lock = threading.Lock()
        self._generation = 0
        self.reset()

    def reset(self, keep: tuple = ()) -> None:
        """Start a new accounting generation.

        Stages entered BEFORE the reset record nothing when they finish
        (the generation token they captured is stale) — an orphaned
        background compile from a previous dataset generation must not
        write its seconds into the new generation's report. ``keep``
        names stages whose accumulation survives the reset (the raw-data
        transfer recorded at ``make_game_dataset`` time, which happens
        before any estimator exists).
        """
        with self._stats_lock:
            kept_s = {
                k: v
                for k, v in getattr(self, "_seconds", {}).items()
                if k in keep
            }
            kept_sp = {
                k: v
                for k, v in getattr(self, "_spans", {}).items()
                if k in keep
            }
            kept_c = {
                k: v
                for k, v in getattr(self, "_counts", {}).items()
                if k in keep
            }
            self._generation += 1
            self._seconds: dict[str, float] = kept_s
            self._spans: dict[str, list[float]] = kept_sp
            self._counts: dict[str, int] = kept_c

    @contextlib.contextmanager
    def stage(self, name: str):
        # Every stage also lands in the unified telemetry layer: a
        # "pipeline/<stage>" span (worker-thread stages root their own
        # subtree, labeled by thread) plus a per-stage histogram. Both
        # are no-ops while telemetry is disabled; this accounting stays
        # authoritative either way.
        from photon_tpu import obs

        with self._stats_lock:
            gen = self._generation
        t0 = time.perf_counter()
        try:
            with obs.span(f"pipeline/{name}"):
                yield
        finally:
            t1 = time.perf_counter()
            with self._stats_lock:
                # A stale generation token (reset() ran mid-stage, e.g.
                # an orphaned background compile) records nothing — it
                # must not pollute the new generation's report. The
                # telemetry histogram below follows the SAME rule so the
                # two absorbed views never diverge (the span above still
                # records: spans are a faithful trace of wall events,
                # not generation accounting).
                if gen == self._generation:
                    if obs.enabled():
                        obs.REGISTRY.histogram(
                            "pipeline_stage_seconds", stage=name
                        ).observe(t1 - t0)
                    self._seconds[name] = self._seconds.get(
                        name, 0.0
                    ) + (t1 - t0)
                    self._counts[name] = self._counts.get(name, 0) + 1
                    span = self._spans.get(name)
                    if span is None:
                        self._spans[name] = [t0, t1]
                    else:
                        span[0] = min(span[0], t0)
                        span[1] = max(span[1], t1)

    def add(self, name: str, seconds: float) -> None:
        with self._stats_lock:
            self._seconds[name] = self._seconds.get(name, 0.0) + seconds
            self._counts[name] = self._counts.get(name, 0) + 1

    def seconds(self, name: str) -> float:
        with self._stats_lock:
            return self._seconds.get(name, 0.0)

    def report(self) -> dict:
        """The JSON-ready stage breakdown ``bench.py`` embeds.

        ``compile_overlap_fraction`` is measured, not inferred: the AOT
        warm compile's duration minus the time the first fit actually
        BLOCKED waiting for it, over the duration — 1.0 means the compile
        hid entirely under ingest + operand assembly, 0.0 means it was
        paid serially after all (and None means no warm compile ran)."""
        with self._stats_lock:
            seconds = dict(self._seconds)
            spans = {k: tuple(v) for k, v in self._spans.items()}
        compile_s = seconds.get("compile", 0.0)
        wait_s = seconds.get("compile_wait", 0.0)
        overlap = (
            max(0.0, min(1.0, 1.0 - wait_s / compile_s))
            if compile_s > 0.0
            else None
        )
        out = {
            "plan_seconds": round(seconds.get("plan", 0.0), 4),
            "pack_seconds": round(seconds.get("pack", 0.0), 4),
            "transfer_seconds": round(seconds.get("transfer", 0.0), 4),
            "compile_seconds": round(compile_s, 4),
            "compile_wait_seconds": round(wait_s, 4),
            "compile_overlap_fraction": (
                None if overlap is None else round(overlap, 4)
            ),
            "stages": {k: round(v, 4) for k, v in sorted(seconds.items())},
        }
        plan_span = spans.get("plan")
        if plan_span is not None:
            out["plan_wall_seconds"] = round(
                plan_span[1] - plan_span[0], 4
            )
        return out


PIPELINE_STATS = PipelineStats()


# --------------------------------------------------------------------------
# chunked host passes (bit-identical to the serial forms)
# --------------------------------------------------------------------------


def _chunk_bounds(n: int, workers: int) -> list[tuple[int, int]]:
    per = -(-n // workers)
    return [(lo, min(lo + per, n)) for lo in range(0, n, per)]


def map_chunked(fn, out: np.ndarray, *arrays: np.ndarray) -> np.ndarray:
    """``out[lo:hi] = fn(*[a[lo:hi] for a in arrays])`` over row chunks.

    For ELEMENTWISE ``fn`` only (each output row depends on the same row
    of the inputs): chunking is then exact, so the parallel result is
    byte-identical to ``out[:] = fn(*arrays)``. Serial mode (or small
    inputs) takes the one-shot path.
    """
    n = out.shape[0]
    workers = ingest_threads()
    if serial_ingest() or workers <= 1 or n < _CHUNK_MIN_ROWS:
        out[:] = fn(*arrays)
        return out

    def run(lo: int, hi: int) -> None:
        from photon_tpu.resilience import faults

        # Chaos boundary: a chunk worker dying mid-pass must surface
        # through consume_futures (first exception re-raised after all
        # complete), never silently zero a span of the output.
        faults.check("ingest.chunk")
        out[lo:hi] = fn(*[a[lo:hi] for a in arrays])

    consume_futures(
        [
            chunk_executor.submit(run, lo, hi)
            for lo, hi in _chunk_bounds(n, workers)
        ]
    )
    return out


def bincount_chunked(codes: np.ndarray, minlength: int) -> np.ndarray:
    """Exact parallel ``np.bincount`` (partial integer counts sum
    associatively, so the chunked result is identical)."""
    n = codes.shape[0]
    workers = ingest_threads()
    if serial_ingest() or workers <= 1 or n < _CHUNK_MIN_ROWS:
        return np.bincount(codes, minlength=minlength)
    parts = consume_futures(
        [
            chunk_executor.submit(
                np.bincount, codes[lo:hi], minlength=minlength
            )
            for lo, hi in _chunk_bounds(n, workers)
        ]
    )
    total = parts[0].astype(np.int64, copy=True)
    for p in parts[1:]:
        total += p
    return total


# --------------------------------------------------------------------------
# chunked double-buffered packed transfer
# --------------------------------------------------------------------------


def padded_len(n: int) -> int:
    """Packed-buffer length after granule padding — THE pad rule shared
    by the real transfer and the shape oracle's predicted layout."""
    g = _TRANSFER_GRANULE_ELEMS
    return max(-(-n // g) * g, g)


def _packed_len(arrays) -> tuple[int, int]:
    n = sum(int(np.prod(a.shape)) if a.shape else 1 for a in arrays)
    return n, padded_len(n)


def _fill_chunks(arrays, n_pad: int, chunk_elems: int):
    """Yield freshly allocated int32 staging buffers covering the packed
    layout [0, n_pad) in order. Fresh per chunk: ``jax.device_put`` may
    read the source asynchronously, so staging buffers are never reused
    while a transfer could still be draining (the double-buffering
    contract)."""
    remaining = n_pad
    chunk = np.zeros(min(chunk_elems, remaining), dtype=np.int32)
    filled = 0
    for a in arrays:
        flat = np.ascontiguousarray(a, dtype=np.int32).reshape(-1)
        o = 0
        while o < flat.size:
            take = min(flat.size - o, chunk.size - filled)
            chunk[filled:filled + take] = flat[o:o + take]
            filled += take
            o += take
            if filled == chunk.size:
                yield chunk
                remaining -= chunk.size
                chunk = np.zeros(
                    min(chunk_elems, remaining), dtype=np.int32
                )
                filled = 0
    while remaining > 0:  # zero padding tail (buffers start zeroed)
        yield chunk
        remaining -= chunk.size
        chunk = np.zeros(min(chunk_elems, remaining), dtype=np.int32)


_concat_cache: dict[int, object] = {}


def _concat_chunks(chunks: tuple):
    """Donated in-trace concatenate: one program per chunk COUNT (chunk
    sizes recur — all equal but the last — so similarly sized ingests
    share the executable), with the chunk buffers' device memory donated
    into the output."""
    import jax

    fn = _concat_cache.get(len(chunks))
    if fn is None:
        import jax.numpy as jnp

        # Donation frees the chunk buffers' HBM into the output on
        # accelerators; the CPU backend would warn on every call.
        donate = (
            (0,) if jax.default_backend() not in ("cpu",) else ()
        )
        fn = jax.jit(
            lambda cs: jnp.concatenate(cs), donate_argnums=donate
        )
        _concat_cache[len(chunks)] = fn
    return fn(tuple(chunks))


def packed_device_put(arrays) -> tuple:
    """Place the packed int32 plan layout on device; returns (buf, shapes).

    Below one chunk this is the legacy single-shot path (one staging fill,
    one ``device_put``). Above it, granule-aligned chunks stream out with
    the host filling chunk i+1 while chunk i's transfer drains, and a
    donated concatenate restores the ONE contiguous buffer every packed
    consumer slices at static offsets (the layout contract is unchanged —
    byte-identical to the single-shot buffer).

    The transfer is a RETRIED site (resilience layer): a transient
    host->device failure — preemption blips, the injected
    ``transfer.packed`` fault — re-runs the whole put (it is pure: host
    arrays in, fresh device buffer out), with backoff; stage seconds
    accumulate across attempts because the time was really spent.
    """
    from photon_tpu.resilience import retry

    return retry.retrying_check(
        "transfer.packed",
        lambda: _packed_device_put_once(arrays),
        site="ingest.packed_transfer",
    )


def _packed_device_put_once(arrays) -> tuple:
    import jax

    shapes = tuple(a.shape for a in arrays)
    n, n_pad = _packed_len(arrays)
    chunk_elems = transfer_chunk_elems()
    if serial_ingest() or n_pad <= chunk_elems:
        with PIPELINE_STATS.stage("pack"):
            flat = np.empty(n_pad, dtype=np.int32)
            o = 0
            for a in arrays:
                flat[o:o + a.size] = np.ascontiguousarray(
                    a, dtype=np.int32
                ).reshape(-1)
                o += a.size
            flat[o:] = 0
        with PIPELINE_STATS.stage("transfer"):
            buf = jax.device_put(flat)
        return buf, shapes
    parts = []
    with PIPELINE_STATS.stage("transfer"):
        for chunk in _fill_chunks(arrays, n_pad, chunk_elems):
            parts.append(jax.device_put(chunk))
        buf = _concat_chunks(tuple(parts))
    return buf, shapes
