"""Device-side dataset representations: dense and padded-sparse (ELL) batches.

TPU-native counterpart of the reference's ``LabeledPoint`` / RDD row
partitions (photon-lib data/LabeledPoint.scala:30, photon-api
data/FixedEffectDataset.scala:32). Instead of millions of JVM objects, a
dataset is a struct-of-arrays batch resident in HBM:

- ``DenseBatch``: features ``[n, d]`` — right for small/medium d where the
  MXU eats the matvec directly.
- ``SparseBatch``: ELL/padded-row layout ``indices[n, k]``, ``values[n, k]``
  with a fixed per-row capacity k = max nnz. Padding slots point at a valid
  column with value 0, so ``matvec`` is a gather + fused multiply-reduce and
  ``rmatvec`` a scatter-add — both static-shape, both XLA-tileable. This is
  the TPU answer to Breeze sparse vectors: bag-of-features data (the
  reference's domain) is hash-sparse with bounded row nnz, so ELL padding is
  cheap and every shape is static.

Rows carry (label, offset, weight) exactly like ``LabeledPoint``; weight 0
removes a row from every aggregation, which is how padding rows added for
even device sharding stay inert.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class FeatureMatrix(Protocol):
    """The two matvecs every GLM computation is built from."""

    num_features: int

    def matvec(self, w: Array) -> Array:
        """X @ w -> [n] margins."""

    def rmatvec(self, g: Array) -> Array:
        """X^T @ g -> [d] aggregation."""

    def rmatvec_sq(self, g: Array) -> Array:
        """(X*X)^T @ g -> [d]; Hessian-diagonal helper."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DenseFeatures:
    x: Array  # [n, d]

    @property
    def num_features(self) -> int:
        return self.x.shape[-1]

    def matvec(self, w: Array) -> Array:
        return self.x @ w

    def rmatvec(self, g: Array) -> Array:
        return self.x.T @ g

    def rmatvec_sq(self, g: Array) -> Array:
        return (self.x * self.x).T @ g


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseFeatures:
    """ELL layout: per-row index/value slabs with static capacity.

    ``indices`` entries for padding slots MUST be valid column ids (0 is
    fine) with ``values`` 0 — gathers stay in-bounds and scatters add zeros.
    """

    indices: Array  # [n, k] int32
    values: Array  # [n, k]
    d: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_features(self) -> int:
        return self.d

    def matvec(self, w: Array) -> Array:
        return jnp.sum(self.values * w[self.indices], axis=-1)

    def rmatvec(self, g: Array) -> Array:
        contrib = self.values * g[:, None]
        return jnp.zeros(self.d, dtype=contrib.dtype).at[self.indices].add(contrib)

    def rmatvec_sq(self, g: Array) -> Array:
        contrib = self.values * self.values * g[:, None]
        return jnp.zeros(self.d, dtype=contrib.dtype).at[self.indices].add(contrib)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DualEllFeatures:
    """Bounded-width ELL slab + COO overflow tail.

    Plain ELL sizes every row at the GLOBAL max nnz — one dense row inflates
    the whole table (the SURVEY §7.3 width hazard). Here the slab width is
    capped; entries beyond the cap spill into a COO tail whose contributions
    are segment-summed back per row. Storage is O(n * cap + overflow) instead
    of O(n * max_nnz), which is what makes heavy-tailed bag-of-features data
    (the reference's domain) storable at scale.

    ``tail_rows`` MUST be sorted ascending (segment_sum indices_are_sorted).
    """

    indices: Array  # [n, cap] int32; padding -> (0, value 0)
    values: Array  # [n, cap]
    tail_rows: Array  # [t] int32 row id per overflow entry, sorted
    tail_indices: Array  # [t] int32
    tail_values: Array  # [t]
    d: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_features(self) -> int:
        return self.d

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    def matvec(self, w: Array) -> Array:
        base = jnp.sum(self.values * w[self.indices], axis=-1)
        tail = self.tail_values * w[self.tail_indices]
        return base + jax.ops.segment_sum(
            tail, self.tail_rows, num_segments=self.num_rows,
            indices_are_sorted=True,
        )

    def rmatvec(self, g: Array) -> Array:
        contrib = self.values * g[:, None]
        out = jnp.zeros(self.d, dtype=contrib.dtype).at[self.indices].add(
            contrib)
        return out.at[self.tail_indices].add(
            self.tail_values * g[self.tail_rows])

    def rmatvec_sq(self, g: Array) -> Array:
        contrib = self.values * self.values * g[:, None]
        out = jnp.zeros(self.d, dtype=contrib.dtype).at[self.indices].add(
            contrib)
        return out.at[self.tail_indices].add(
            self.tail_values * self.tail_values * g[self.tail_rows])


def ell_to_dual_ell(
    indices: np.ndarray,  # [n, k] host-side
    values: np.ndarray,  # [n, k]
    num_features: int,
    width_cap: int,
    dtype=np.float32,
) -> DualEllFeatures:
    """Split an ELL slab at ``width_cap``: widest entries spill to the tail."""
    n, k = indices.shape
    cap = max(min(width_cap, k), 1)
    present = values != 0.0
    # Compact valid entries left so the first `cap` slots hold real entries.
    order = np.argsort(~present, axis=1, kind="stable")
    idx_c = np.take_along_axis(np.where(present, indices, 0), order, axis=1)
    val_c = np.take_along_axis(np.where(present, values, 0.0), order, axis=1)
    tail_mask = val_c[:, cap:] != 0.0
    rows = np.broadcast_to(
        np.arange(n, dtype=np.int64)[:, None], tail_mask.shape)
    return DualEllFeatures(
        indices=jnp.asarray(idx_c[:, :cap].astype(np.int32)),
        values=jnp.asarray(val_c[:, :cap], dtype=dtype),
        tail_rows=jnp.asarray(rows[tail_mask].astype(np.int32)),
        tail_indices=jnp.asarray(
            idx_c[:, cap:][tail_mask].astype(np.int32)),
        tail_values=jnp.asarray(val_c[:, cap:][tail_mask], dtype=dtype),
        d=num_features,
    )


Features = Union[DenseFeatures, SparseFeatures, DualEllFeatures]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GLMBatch:
    """One coordinate's training slab: features + (label, offset, weight).

    The reference's ``FixedEffectDataset`` is an RDD of these rows plus
    persistence choreography; here the whole dataset is one pytree, and
    "persistence" is just the arrays living in HBM (optionally sharded over
    the mesh's data axis by the caller via NamedSharding).
    """

    features: Features
    labels: Array  # [n]
    offsets: Array  # [n]
    weights: Array  # [n]

    @property
    def num_samples(self) -> int:
        return self.labels.shape[-1]

    @property
    def num_features(self) -> int:
        return self.features.num_features

    def with_offsets(self, offsets: Array) -> "GLMBatch":
        """Functional offset update — the residual-score plumbing of
        coordinate descent (Coordinate.scala:52-53 addScoresToOffsets)."""
        return dataclasses.replace(self, offsets=offsets)

    def with_weights(self, weights: Array) -> "GLMBatch":
        """Functional weight update (down-sampling masks)."""
        return dataclasses.replace(self, weights=weights)

    def weighted_count(self) -> Array:
        return jnp.sum(self.weights)


def make_dense_batch(
    x: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    dtype=jnp.float32,
) -> GLMBatch:
    n = x.shape[0]
    return GLMBatch(
        features=DenseFeatures(jnp.asarray(x, dtype=dtype)),
        labels=jnp.asarray(labels, dtype=dtype),
        offsets=jnp.zeros(n, dtype=dtype) if offsets is None else jnp.asarray(offsets, dtype=dtype),
        weights=jnp.ones(n, dtype=dtype) if weights is None else jnp.asarray(weights, dtype=dtype),
    )


def rows_to_ell(
    rows: list[list[tuple[int, float]]],
    num_features: int,
    *,
    capacity: int | None = None,
    dtype=np.float32,
) -> tuple[np.ndarray, np.ndarray]:
    """Pack per-row (index, value) lists into ELL index/value slabs."""
    k = capacity if capacity is not None else max((len(r) for r in rows), default=1)
    k = max(k, 1)
    n = len(rows)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    for i, row in enumerate(rows):
        if len(row) > k:
            raise ValueError(f"row {i} has {len(row)} nnz > capacity {k}")
        for j, (idx, val) in enumerate(row):
            if not (0 <= idx < num_features):
                raise ValueError(f"feature index {idx} out of range [0, {num_features})")
            indices[i, j] = idx
            values[i, j] = val
    return indices, values


def make_sparse_batch(
    rows: list[list[tuple[int, float]]],
    num_features: int,
    labels: np.ndarray,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    capacity: int | None = None,
    dtype=jnp.float32,
) -> GLMBatch:
    indices, values = rows_to_ell(
        rows, num_features, capacity=capacity, dtype=np.dtype(dtype)
    )
    n = len(rows)
    return GLMBatch(
        features=SparseFeatures(jnp.asarray(indices), jnp.asarray(values, dtype=dtype), num_features),
        labels=jnp.asarray(labels, dtype=dtype),
        offsets=jnp.zeros(n, dtype=dtype) if offsets is None else jnp.asarray(offsets, dtype=dtype),
        weights=jnp.ones(n, dtype=dtype) if weights is None else jnp.asarray(weights, dtype=dtype),
    )


def pad_batch(batch: GLMBatch, multiple: int) -> GLMBatch:
    """Pad the sample axis to a multiple (for even device sharding) with
    weight-0 rows; padding rows contribute exactly zero to every aggregate."""
    n = batch.num_samples
    rem = (-n) % multiple
    if rem == 0:
        return batch

    def pad1(a):
        return jnp.concatenate([a, jnp.zeros((rem,) + a.shape[1:], dtype=a.dtype)])

    feats = batch.features
    if isinstance(feats, DenseFeatures):
        feats = DenseFeatures(pad1(feats.x))
    elif isinstance(feats, SparseFeatures):
        feats = SparseFeatures(pad1(feats.indices), pad1(feats.values), feats.d)
    else:
        raise TypeError(
            "pad_batch/shard_batch do not support DualEllFeatures: the COO "
            "tail is not row-aligned, so row sharding would misroute it. "
            "Use plain SparseFeatures for data-axis sharding, or "
            "FeatureShardedSparse for the feature axis.")
    return GLMBatch(
        features=feats,
        labels=pad1(batch.labels),
        offsets=pad1(batch.offsets),
        weights=pad1(batch.weights),  # zeros: inert rows
    )
