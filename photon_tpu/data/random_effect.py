"""RandomEffectDataset: per-entity data as size-bucketed device blocks.

TPU-native counterpart of the heart of GLMix scaling (photon-api
data/RandomEffectDataset.scala:54, apply :264-354). The reference's build
pipeline — key by REId, per-entity ``LinearSubspaceProjector`` from the union
of active feature indices (:390-426), deterministic reservoir-sampling cap
(groupDataByKeyAndSample :468-527 with byteswap64 hash keys :510), feature
projection to the subspace (:538-550), optional Pearson-correlation feature
selection (:562-576), active-data lower-bound filter (:586-606) and passive
data as the leftovers (:631-640) — happens ONCE at ingest, in two stages:

1. **Plan (host)**: a fully vectorized numpy pass over the id codes — one
   ``(entity, hash)`` lexsort gives the deterministic reservoir order, one
   global ``unique`` over (entity, feature) pairs gives every subspace
   projector, and one global ``searchsorted`` against the concatenated
   projector key table remaps any (entity, feature) pair to its subspace
   slot. There are NO per-entity Python loops; the reference's shuffles
   (RandomEffectDataset.scala:264-354) become O(n log n) host sorts.
2. **Device placement**: by default the plan is *lazy* — only the small
   index arrays (bucket membership ``row_ids``, projector tables) are
   pushed; the big per-bucket feature slabs and the scoring table are
   **gathered on device, inside the already-jitted solver/scorer, from the
   raw feature arrays resident in HBM**. The raw data crosses the
   host->device link exactly once (at ``make_game_dataset``), and HBM
   bandwidth — not the host link — feeds the MXU. ``lazy=False`` keeps the
   fully materialized layout (used for ``DualEllFeatures`` shards and by
   layout-introspection tests).

- **EntityBlocks / BlockPlan** (training): entities grouped into size
  buckets; each bucket materializes to a ``[B, R, k]`` ELL slab plus
  per-entity projector index arrays, so one vmapped solver call fits all B
  entities simultaneously. This replaces the reference's per-partition
  ``mapValues`` local solves (RandomEffectCoordinate.scala:243-292) and its
  partitioner bin-packing (RandomEffectDatasetPartitioner.scala:44): padding
  buckets instead of packing bins.
- **Scoring** (active + passive rows): every canonical row scores against
  the ``[num_entities, max_sub_dim]`` coefficient matrix — lazily as a fused
  remap-gather-reduce over the raw features (models/game.py
  score_raw_features), or through the materialized width-capped table with
  COO tail. Features outside an entity's subspace contribute nothing (the
  projector drop semantics of LinearSubspaceProjector.projectForward).

Residual routing (addScoresToOffsets :83-110) reduces to gathering the
canonical offsets vector through each block's ``row_ids``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import (
    DenseFeatures,
    Features,
    SparseFeatures,
)
from photon_tpu.data.game_data import GameDataset
from photon_tpu.ops import segment_reduce
from photon_tpu.data.pipeline import (
    PIPELINE_STATS,
    bincount_chunked,
    chunk_executor,
    consume_futures,
    map_chunked,
    packed_device_put,
)

Array = jax.Array

# Row-count caps for entity size buckets: entities are padded up to the next
# cap, so worst-case padding waste is bounded within a bucket (SURVEY §7.3).
# The ratio-4 ladder keeps the number of distinct solver shapes (one jit
# compile each) small; padding rows carry weight 0 and cost only flops.
DEFAULT_BUCKET_CAPS = (16, 64, 256, 1024, 4096)


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Per-coordinate random-effect data config.

    Reference: RandomEffectDataConfiguration in
    data/CoordinateDataConfiguration.scala:77 — REType, feature shard, active
    data bounds, features-to-samples ratio (Pearson filter).
    """

    random_effect_type: str
    feature_shard_id: str
    active_data_upper_bound: int | None = None
    active_data_lower_bound: int | None = None
    features_to_samples_ratio: float | None = None
    bucket_caps: tuple[int, ...] = DEFAULT_BUCKET_CAPS
    # Scoring-table ELL width bound (SURVEY §7.3 width hazard) for the
    # MATERIALIZED layout: rows with more nnz spill into a COO tail instead
    # of inflating every row's slab. The lazy layout reads the raw feature
    # arrays directly and never builds a table, so the cap is moot there.
    score_table_width_cap: int | None = None
    # Entity-bucket batching: buckets with fewer member entities than
    # this merge UPWARD into the next-larger row cap (more padding, but
    # fewer/fatter solver programs — a bucket-tail of a handful of
    # entities otherwise dispatches its own program per warm refit and
    # instantiates its own solver inside the fused sweep). 0 = off (one
    # bucket per occupied cap, the historical layout). Shared with the
    # ingest pipeline's shape oracle through ``_assign_buckets`` so
    # predicted block shapes can never drift from built ones.
    min_bucket_entities: int = 0


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBlocks:
    """One size bucket of entities, padded to common shapes (materialized).

    Training slab for a vmapped per-entity solver: leading axis B is the
    entity axis. Padding rows carry weight 0; padded subspace slots have
    ``proj == -1`` and never receive data gradient.
    """

    entity_codes: Array  # [B] int32 — global entity code per slot
    # Feature slabs, one of two layouts:
    # - ELL: x_indices [B, R, k] int32 subspace slots + x_values [B, R, k]
    # - subspace-dense: x_indices is None, x_values [B, R, S] holds the
    #   densified per-entity design matrix. Preferred for small sub_dims:
    #   it keeps every downstream op a matmul (MXU) and avoids batched
    #   gather/scatter lowerings, which compile catastrophically slowly on
    #   TPU (tens of seconds per shape vs <1s for the one-hot einsum).
    x_indices: Array | None
    x_values: Array  # [B, R, k] or [B, R, S]
    labels: Array  # [B, R]
    offsets: Array  # [B, R] base offsets (residuals added per train call)
    weights: Array  # [B, R]; 0 for padding rows
    row_ids: Array  # [B, R] int32 canonical row ids; 0 for padding (weight 0)
    proj: Array  # [B, S] int32 original feature id per subspace slot; -1 pad
    penalty_mask: Array  # [B, S] 1.0 for penalized slots (valid, non-intercept)
    valid_mask: Array  # [B, S] 1.0 for valid subspace slots
    intercept_slots: Array  # [B] int32 subspace slot of intercept; -1 if none

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def sub_dim(self) -> int:
        return self.proj.shape[-1]

    @property
    def is_dense(self) -> bool:
        return self.x_indices is None


# Subspace-dense materialization bound: up to this sub_dim the [B, R, S]
# dense slab (built by one-hot einsum, no gather/scatter) is both the
# fastest-compiling and the most MXU-friendly layout. Above it, the one-hot
# tensors get large and blocks stay in ELL form.
DENSE_SUB_DIM_MAX = 128
# Element budget for materialized one-hot operands (the dot_general operand
# is NOT fused away): beyond this, fall back to gather/scatter lowerings,
# which compile slowly but keep memory at the ELL slab's order.
ONE_HOT_ELEMENT_BUDGET = 1 << 28


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BlockPlan:
    """One size bucket in lazy form: plan indices + raw-data references.

    ``materialize`` runs INSIDE the jitted solver, so the [B, R, k] slabs are
    gathered from HBM-resident raw arrays by the compiled program — they
    never exist on the host and never cross the host<->device link. The raw
    leaves (``raw``/``labels``/``offsets``/``weights``) are shared references
    to the GameDataset's arrays: every bucket's jit call sees the same
    buffers.
    """

    entity_codes: Array  # [B] int32
    row_ids: Array  # [B, R] int32 canonical rows; 0 for padding slots
    row_counts: Array  # [B] int32 valid rows per entity
    proj: Array  # [B, S] int32 sorted feature ids; -1 pads (trailing)
    intercept_slots: Array  # [B] int32; -1 if none
    raw: Features  # device-resident feature shard (Dense or Sparse ELL)
    raw_labels: Array  # [n] shared
    raw_offsets: Array  # [n] shared (base offsets)
    raw_weights: Array  # [n] shared

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def sub_dim(self) -> int:
        return self.proj.shape[-1]

    def materialize(self, residuals: Array | None = None) -> EntityBlocks:
        """Gather the bucket's training slabs (traceable; runs in jit).

        Returns an ``EntityBlocks`` whose ``offsets`` already include the
        coordinate-descent residuals. For sub_dims up to
        ``DENSE_SUB_DIM_MAX`` (within the one-hot element budget) the
        feature slab comes out subspace-DENSE, built by one-hot einsums
        (comparisons feeding a matmul) — row gathers are plain
        ``jnp.take``; no batched gather/scatter, because those lower to
        pathologically slow-compiling programs on TPU while the one-hot
        contraction compiles in under a second and runs on the MXU. Wider
        subspaces (or over-budget one-hot operands) fall back to ELL form
        via gather lowerings: slower compiles, bounded memory.
        """
        b, r = self.row_ids.shape
        s = self.proj.shape[-1]
        rows = self.row_ids
        dtype = self.raw_weights.dtype
        row_mask = jnp.arange(r, dtype=jnp.int32)[None, :] < (
            self.row_counts[:, None]
        )
        labels = jnp.take(self.raw_labels, rows)
        weights = jnp.where(
            row_mask, jnp.take(self.raw_weights, rows), 0
        )
        offs = jnp.take(self.raw_offsets, rows)
        if residuals is not None:
            offs = offs + jnp.take(residuals, rows)
        offs = jnp.where(row_mask, offs, 0)

        proj = self.proj
        valid = (proj >= 0).astype(dtype)
        iota_s = jnp.arange(s, dtype=jnp.int32)[None, :]
        penalty = jnp.where(
            iota_s == self.intercept_slots[:, None], 0.0, valid
        ).astype(dtype)

        if isinstance(self.raw, DenseFeatures):
            d = self.raw.x.shape[1]
            xr = jnp.take(self.raw.x, rows, axis=0)  # [B, R, d]
            if s <= DENSE_SUB_DIM_MAX and b * d * s <= ONE_HOT_ELEMENT_BUDGET:
                # Feature->slot one-hot per entity:
                # M[b, f, s] = proj[b,s] == f; -1 pads never match.
                onehot = (
                    proj[:, None, :]
                    == jnp.arange(d, dtype=proj.dtype)[None, :, None]
                ).astype(dtype)  # [B, d, S]
                x_values = jnp.einsum("brf,bfs->brs", xr, onehot)
                x_values = jnp.where(row_mask[:, :, None], x_values, 0)
                x_indices = None
            else:
                # Guarded fallback: LUT gather keeps memory at O(B d + B R d)
                # at the cost of a slow-compiling batched scatter/gather.
                pr = jnp.where(proj >= 0, proj, d)
                lut = jnp.full((b, d + 1), -1, jnp.int32)
                lut = lut.at[
                    jnp.arange(b, dtype=jnp.int32)[:, None], pr
                ].set(jnp.broadcast_to(iota_s, (b, s)))
                lut = lut[:, :d]  # [B, d]
                x_indices = jnp.broadcast_to(
                    jnp.maximum(lut, 0)[:, None, :], (b, r, d)
                )
                x_values = jnp.where(
                    (lut >= 0)[:, None, :] & row_mask[:, :, None], xr, 0
                )
        else:
            idx = jnp.take(self.raw.indices, rows, axis=0)  # [B, R, k]
            val = jnp.take(self.raw.values, rows, axis=0)
            val = jnp.where(row_mask[:, :, None], val, 0)
            k = idx.shape[-1]
            if (
                s <= DENSE_SUB_DIM_MAX
                and b * r * k * s <= ONE_HOT_ELEMENT_BUDGET
            ):
                # Slot one-hot: idx[b,r,k] == proj[b,s]; the contraction
                # densifies without any gather/scatter.
                onehot = (
                    idx[:, :, :, None] == proj[:, None, None, :]
                ).astype(dtype)  # [B, R, k, S]
                x_values = jnp.einsum("brk,brks->brs", val, onehot)
                x_indices = None
            else:
                # Guarded fallback: binary-search remap keeps ELL form
                # (O(B R k) memory, slow-compiling batched gathers).
                sentinel = jnp.iinfo(jnp.int32).max
                psort = jnp.where(proj >= 0, proj, sentinel)  # ascending
                flat = idx.reshape(b, r * k)
                slot = jax.vmap(jnp.searchsorted)(psort, flat)
                slot = jnp.minimum(slot, s - 1)
                hit = jnp.take_along_axis(psort, slot, axis=1) == flat
                slot = slot.reshape(b, r, k).astype(jnp.int32)
                ok = hit.reshape(b, r, k) & (val != 0)
                x_indices = jnp.where(ok, slot, 0)
                x_values = jnp.where(ok, val, 0)

        return EntityBlocks(
            entity_codes=self.entity_codes,
            x_indices=x_indices,
            x_values=x_values,
            labels=labels,
            offsets=offs,
            weights=weights,
            row_ids=jnp.where(row_mask, rows, 0),
            proj=proj,
            penalty_mask=penalty,
            valid_mask=valid,
            intercept_slots=self.intercept_slots,
        )

    # Eager conveniences so layout introspection (tests, debugging) works on
    # either block form. Each access re-gathers; not for hot paths.
    @property
    def weights(self) -> Array:
        return self.materialize().weights

    @property
    def labels(self) -> Array:
        return self.materialize().labels

    @property
    def offsets(self) -> Array:
        return self.materialize().offsets

    @property
    def x_values(self) -> Array:
        return self.materialize().x_values

    @property
    def x_indices(self) -> Array:
        return self.materialize().x_indices

    @property
    def valid_mask(self) -> Array:
        return self.materialize().valid_mask

    @property
    def penalty_mask(self) -> Array:
        return self.materialize().penalty_mask


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """All device-resident state for one random-effect coordinate."""

    config: RandomEffectDataConfiguration
    num_entities: int
    entity_keys: tuple  # code -> raw entity key
    blocks: tuple  # active data, size-bucketed: EntityBlocks | BlockPlan
    max_sub_dim: int
    sub_dims: np.ndarray  # [E] host-side subspace dims
    proj_all: np.ndarray  # [E, max_sub_dim] original feature ids; -1 pad
    num_features: int  # original feature-space dim of the shard
    dtype: object = np.float32
    # Scoring state, lazy form: owning-entity code per canonical row plus
    # the device projector table; scores fuse against ``raw`` in HBM.
    score_codes: Array | None = None  # [n] int32
    raw: Features | None = None  # device raw shard (lazy mode)
    proj_dev: Array | None = None  # [E, max_sub_dim] device; -1 pad
    # Scoring state, materialized form (score_indices is None in lazy mode):
    score_indices: Array | None = None  # [n, k] int32 subspace-remapped
    score_values: Array | None = None  # [n, k]; 0 where outside the subspace
    # COO overflow tail for rows wider than the configured score-table cap
    # (empty arrays when uncapped); tail rows are sorted ascending.
    score_tail_rows: Array | None = None  # [t] int32
    score_tail_indices: Array | None = None  # [t] int32 subspace slots
    score_tail_values: Array | None = None  # [t]
    # Host-computed max tail entries per row: the static multiplicity
    # bound the tiled segment-reduce kernel needs (ops/segment_reduce).
    score_tail_mult: int | None = None
    # Host mirrors of small per-block plan arrays (one per ``blocks`` entry)
    # so per-fit bookkeeping never pulls from the device.
    block_codes_np: tuple = ()
    block_intercepts_np: tuple = ()
    # Per-bucket (grad_mult, hess_mult) WINDOW bounds for the direct ELL
    # gram route (ops/segment_reduce.ell_gram_supported documents the
    # currency), or None per bucket when the route cannot engage there
    # (small subspaces densify up front; over-budget pair passes).
    # Empty for lazy datasets — their slabs never exist on the host, so
    # there is nothing to bound at plan time.
    block_gram_mults: tuple = ()
    # [n] bool host mask: rows kept into some training block (built from the
    # planner's rows_flat, so no device work is needed to derive it).
    covered_np: np.ndarray | None = None
    # Lazy device placement: every plan array of the build rides ONE packed
    # int32 device buffer (one transfer-shape setup for the whole ingest,
    # ~65ms instead of ~30 x 65ms on remote links); the fused fit slices it
    # IN-TRACE (zero extra programs), while eager consumers split it once
    # through ``device_plans()``. ``blocks`` carries host-numpy plan leaves
    # when this is set.
    packed_view: object | None = None

    @property
    def num_rows(self) -> int:
        """Canonical row count of the table this dataset was built from."""
        return int(self.score_codes.shape[0])

    def device_plans(self) -> tuple:
        """``blocks`` with DEVICE plan arrays (cached).

        Lazy-packed datasets split the packed buffer with one jitted
        program on first need — only the unfused training/scoring paths
        pay it; the fused fit slices the buffer inside its own programs.
        """
        cached = getattr(self, "_device_plans", None)
        if cached is not None:
            return cached
        first = self.blocks[0] if self.blocks else None
        if first is None or not isinstance(first, BlockPlan) or isinstance(
            first.entity_codes, jax.Array
        ):
            out = self.blocks  # already device-resident (or materialized)
        elif self.packed_view is not None:
            devs = self.packed_view.device_arrays()
            out = tuple(
                dataclasses.replace(
                    b,
                    entity_codes=devs[PLAN_ARRAYS_PER_BUCKET * i],
                    row_ids=devs[PLAN_ARRAYS_PER_BUCKET * i + 1],
                    row_counts=devs[PLAN_ARRAYS_PER_BUCKET * i + 2],
                    proj=devs[PLAN_ARRAYS_PER_BUCKET * i + 3],
                    intercept_slots=devs[PLAN_ARRAYS_PER_BUCKET * i + 4],
                )
                for i, b in enumerate(self.blocks)
            )
        else:
            leaves = jax.device_put([
                arr for b in self.blocks
                for arr in (b.entity_codes, b.row_ids, b.row_counts,
                            b.proj, b.intercept_slots)
            ])
            out = tuple(
                dataclasses.replace(
                    b,
                    entity_codes=leaves[PLAN_ARRAYS_PER_BUCKET * i],
                    row_ids=leaves[PLAN_ARRAYS_PER_BUCKET * i + 1],
                    row_counts=leaves[PLAN_ARRAYS_PER_BUCKET * i + 2],
                    proj=leaves[PLAN_ARRAYS_PER_BUCKET * i + 3],
                    intercept_slots=leaves[PLAN_ARRAYS_PER_BUCKET * i + 4],
                )
                for i, b in enumerate(self.blocks)
            )
        object.__setattr__(self, "_device_plans", out)
        return out

    def score_inv_device(self) -> Array | None:
        """[n] int32 inverse score map (device), or None when absent.

        Maps each canonical row to its flat position in the concatenation
        of all buckets' [B, cap] score blocks followed by the passive-row
        score vector — the scatter-free scoring contract (trailing array
        of the packed plan layout)."""
        if self.packed_view is None:
            return None
        n_blocks = len(self.blocks)
        if len(self.packed_view) != packed_len_with_score_inv(n_blocks):
            return None  # pre-score-map packed layout
        cached = getattr(self, "_score_inv_cache", None)
        if cached is None:
            cached = self.packed_view.device_arrays()[
                packed_score_inv_index(n_blocks)]
            object.__setattr__(self, "_score_inv_cache", cached)
        return cached

    def proj_device(self) -> Array:
        """[E, max_sub_dim] int32 device projector table (cached)."""
        if self.proj_dev is not None:
            return self.proj_dev
        cached = getattr(self, "_proj_dev_cache", None)
        if cached is None:
            if self.packed_view is not None:
                cached = self.packed_view.device_arrays()[
                    packed_proj_index(len(self.blocks))]
            else:
                cached = jnp.asarray(self.proj_all.astype(np.int32))
            object.__setattr__(self, "_proj_dev_cache", cached)
        return cached

    def device_blocks(self) -> tuple:
        """Training blocks with feature slabs materialized ON DEVICE (cached).

        Lazy ``BlockPlan`` buckets re-gather their [B, R, S] feature slab
        from the raw arrays on EVERY solve call; the slab is
        residual-independent, so materializing it once per dataset cuts the
        per-solve gather traffic to the [B, R] residual rows (~S x less).
        The one-time cost is HBM for the slabs — gated by
        ``_DEVICE_SLAB_BUDGET_BYTES``, beyond which the lazy form is kept
        (gather per solve, bounded memory). Materialization runs as one
        jitted program per bucket, so slabs never touch the host.
        """
        cached = getattr(self, "_device_blocks", None)
        if cached is not None:
            return cached
        out = []
        spent = 0  # the budget bounds the TOTAL cached bytes, not per block
        itemsize = np.dtype(self.dtype).itemsize
        for b in self.device_plans():
            if isinstance(b, BlockPlan):
                bb, r = b.row_ids.shape
                s = b.proj.shape[-1]
                # Conservative estimate of the materialized layout: the
                # subspace-dense [B, R, S] slab, or the ELL fallback's
                # values + int32 slot indices at the raw row width.
                k_raw = (
                    b.raw.indices.shape[1]
                    if isinstance(b.raw, SparseFeatures)
                    else b.raw.x.shape[1]
                )
                slab_bytes = max(
                    itemsize * bb * r * s,
                    (itemsize + 4) * bb * r * min(k_raw, s),
                )
                if spent + slab_bytes <= _DEVICE_SLAB_BUDGET_BYTES:
                    spent += slab_bytes
                    b = _materialize_block_jit(b)
            out.append(b)
        out = tuple(out)
        object.__setattr__(self, "_device_blocks", out)
        return out

    def covered_row_partition(self):
        """(covered_mask [n] bool HOST array, passive_rows host int32 array).

        "Covered" rows appear in some training block (the active kept
        rows); "passive" rows — beyond the reservoir cap or owned by
        inactive entities with a trained model — still need scoring
        (RandomEffectDataset's activeData/passiveData split, :631-640).
        Cached per dataset.

        Derived ENTIRELY on the host: the planner's kept-row lists are host
        arrays, and the former device derivation (per-bucket eager
        iota/compare/scatter-max at 4M-row shapes) cost ~95s of one-off XLA
        compiles per fit on the tunneled TPU backend.
        """
        cached = getattr(self, "_covered", None)
        if cached is not None:
            return cached
        assert self.is_lazy, "row partition is defined for lazy datasets"
        if self.covered_np is not None:
            covered = self.covered_np
        else:
            # Fallback for datasets built before covered_np existed (e.g.
            # dataclasses.replace-based shims in tests): one host pass over
            # the block plans. A real row with data weight 0 is still
            # covered and must score.
            covered = np.zeros(self.num_rows, dtype=bool)
            for b in self.blocks:
                rows = np.asarray(b.row_ids)
                counts = np.asarray(b.row_counts)
                r = rows.shape[1]
                valid = np.arange(r, dtype=np.int32)[None, :] < counts[:, None]
                covered[rows[valid]] = True
        passive = np.nonzero(~covered)[0].astype(np.int32)
        result = (covered, passive)
        object.__setattr__(self, "_covered", result)
        return result

    @property
    def is_lazy(self) -> bool:
        return self.score_indices is None

    def real_entity_mask(self, block_index: int) -> np.ndarray:
        """[B] bool — True for real entities of block ``block_index``.
        Mesh-sharded blocks pad the entity axis with inert entities whose
        code is ``num_entities`` (parallel/mesh.py
        shard_random_effect_dataset); this helper owns that convention."""
        return self.block_codes_np[block_index] < self.num_entities

    @property
    def num_active_entities(self) -> int:
        return sum(
            int(self.real_entity_mask(i).sum())
            for i in range(len(self.blocks))
        )


# Total-HBM budget for cached materialized feature slabs (device_blocks):
# datasets whose slabs exceed this stay lazy (gather per solve).
_DEVICE_SLAB_BUDGET_BYTES = 2 << 30


@jax.jit
def _materialize_block_jit(block):
    """One bucket's residual-independent slabs, gathered on device."""
    return block.materialize(None)


def _stable_type_seed(re_type: str) -> np.uint64:
    """Deterministic 64-bit seed from the REType name (the reference XORs
    ``REType.hashCode`` into the sample key, RandomEffectDataset.scala:510)."""
    import zlib

    return np.uint64(zlib.crc32(re_type.encode()) | (0x9E3779B9 << 32))


def _byteswap64_mix(uids: np.ndarray, seed: np.uint64) -> np.ndarray:
    """splitmix64-style deterministic hash of sample ids — the moral
    equivalent of the reference's ``byteswap64(hash ^ uid)`` reservoir keys:
    a fixed pseudo-random total order over samples, reproducible across
    re-ingests (SURVEY §5.2 determinism requirement)."""
    z = uids.astype(np.uint64) ^ seed
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _pearson_select(
    values: np.ndarray,  # [r, k] ELL values for one entity's active rows
    indices: np.ndarray,  # [r, k]
    labels: np.ndarray,  # [r]
    active_features: np.ndarray,  # sorted original ids
    keep: int,
    intercept_index: int | None,
    num_features: int,
) -> np.ndarray:
    """Rank an entity's active features by |Pearson corr with the label| and
    keep the top ``keep`` (intercept always kept).

    Reference: LocalDataset.filterFeaturesByPearsonCorrelationScore
    (data/LocalDataset.scala:103, stableComputePearsonCorrelationScore :132):
    features with near-constant columns get score ~0 except the intercept,
    which is always retained.
    """
    if keep >= active_features.size:
        return active_features
    r = labels.shape[0]
    pos = np.full(num_features, -1, dtype=np.int64)
    pos[active_features] = np.arange(active_features.size)
    sub = pos[indices]
    valid = (values != 0.0) & (sub >= 0)
    rows = np.broadcast_to(np.arange(r)[:, None], indices.shape)
    cols = np.zeros((r, active_features.size), dtype=np.float64)
    cols[rows[valid], sub[valid]] = values[valid]
    y = labels.astype(np.float64)
    yc = y - y.mean()
    xc = cols - cols.mean(axis=0, keepdims=True)
    num = xc.T @ yc
    den = np.sqrt((xc * xc).sum(axis=0) * (yc * yc).sum()) + 1e-12
    score = np.abs(num / den)
    if intercept_index is not None and pos[intercept_index] >= 0:
        score[pos[intercept_index]] = np.inf  # always keep the intercept
    order = np.argsort(-score, kind="stable")[:keep]
    return np.sort(active_features[order])


@dataclasses.dataclass(frozen=True)
class _ProjectorTable:
    """Flat per-entity subspace projectors (all host numpy).

    ``keys`` is ``entity * stride + feature`` for every (entity, feature)
    pair in any subspace, globally sorted — so ONE ``np.searchsorted``
    resolves any batch of pairs to subspace slots (``slot = pos -
    offsets[entity]``). This replaces the reference's per-entity
    LinearSubspaceProjector maps (projector/LinearSubspaceProjector.scala:36)
    with index arithmetic.
    """

    keys: np.ndarray  # [total] int64, sorted
    offsets: np.ndarray  # [E + 1] int64
    stride: int
    num_entities: int

    @property
    def sub_dims(self) -> np.ndarray:
        return np.diff(self.offsets)

    def features_of(self, e: int) -> np.ndarray:
        return self.keys[self.offsets[e]:self.offsets[e + 1]] % self.stride

    def lookup(
        self, codes: np.ndarray, feats: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized (entity, feature) -> (slot, found). Any shape; codes
        broadcastable to feats. Negative codes never match."""
        codes = np.broadcast_to(codes, feats.shape)
        keys = (
            np.maximum(codes, 0).astype(np.int64) * self.stride
            + feats.astype(np.int64)
        )
        if self.keys.size == 0:
            z = np.zeros(feats.shape, dtype=np.int64)
            return z, np.zeros(feats.shape, dtype=bool)
        pos = np.searchsorted(self.keys, keys)
        pos_c = np.minimum(pos, self.keys.size - 1)
        found = (self.keys[pos_c] == keys) & (codes >= 0)
        slot = pos_c - self.offsets[np.maximum(codes, 0)]
        return np.where(found, slot, 0), found

    @staticmethod
    def from_lists(
        projs: list[np.ndarray], stride: int
    ) -> "_ProjectorTable":
        e = len(projs)
        sizes = np.array([p.size for p in projs], dtype=np.int64)
        offsets = np.zeros(e + 1, dtype=np.int64)
        np.cumsum(sizes, out=offsets[1:])
        if e and offsets[-1]:
            ids = np.repeat(np.arange(e, dtype=np.int64), sizes)
            keys = ids * stride + np.concatenate(
                [p.astype(np.int64) for p in projs if p.size]
            )
        else:
            keys = np.empty(0, dtype=np.int64)
        return _ProjectorTable(keys, offsets, stride, e)


def _subset_rows_widened(
    ell_idx: np.ndarray,
    ell_val: np.ndarray,
    tail,  # (rows, indices, values) sorted by row, or None
    rows: np.ndarray,  # unique row ids to take
) -> tuple[np.ndarray, np.ndarray]:
    """ELL view of a row subset with the rows' COO tail entries appended as
    extra columns. Width grows only to the widest row IN THE SUBSET, so
    per-bucket / per-entity widening stays bounded by that group's own
    content — never by the single widest row of the whole table."""
    si = ell_idx[rows]
    sv = ell_val[rows]
    if tail is None:
        return si, sv
    tr, ti, tv = tail
    n = ell_idx.shape[0]
    m = rows.shape[0]
    inv = np.full(n, -1, dtype=np.int64)
    inv[rows] = np.arange(m)
    sel = inv[tr] >= 0
    if not sel.any():
        return si, sv
    # Global within-row rank of tail entries (tail rows sorted ascending).
    g_starts = np.searchsorted(tr, np.arange(n))
    g_rank = np.arange(tr.size) - g_starts[tr]
    r_of = inv[tr[sel]]
    kx = int(g_rank[sel].max()) + 1
    k0 = si.shape[1]
    out_i = np.zeros((m, k0 + kx), dtype=si.dtype)
    out_v = np.zeros((m, k0 + kx), dtype=sv.dtype)
    out_i[:, :k0] = si
    out_v[:, :k0] = sv
    out_i[r_of, k0 + g_rank[sel]] = ti[sel]
    out_v[r_of, k0 + g_rank[sel]] = tv[sel]
    return out_i, out_v


def _compact_left(
    slot: np.ndarray, val: np.ndarray, found: np.ndarray, k_out: int
) -> tuple[np.ndarray, np.ndarray]:
    """Left-compact valid ELL entries per row; truncate/pad to ``k_out``."""
    order = np.argsort(~found, axis=1, kind="stable")
    slot_c = np.take_along_axis(np.where(found, slot, 0), order, axis=1)
    val_c = np.take_along_axis(np.where(found, val, 0.0), order, axis=1)
    n, k = slot_c.shape
    if k_out > k:
        slot_c = np.pad(slot_c, ((0, 0), (0, k_out - k)))
        val_c = np.pad(val_c, ((0, 0), (0, k_out - k)))
    return slot_c[:, :k_out].astype(np.int32), val_c[:, :k_out]


@dataclasses.dataclass
class _Plan:
    """Host-side build plan: everything downstream layout needs, no loops."""

    codes: np.ndarray  # [n] int64 owning-entity code per row
    perm: np.ndarray  # [n] rows sorted by (entity, reservoir hash)
    sorted_codes: np.ndarray  # [n] codes[perm] (computed once; hoisted
    # out of the per-bucket row selection, which used to re-gather it
    # per bucket — the round-5 ingest-floor bisect's actual culprit)
    starts: np.ndarray  # [E]
    counts_full: np.ndarray  # [E] rows per entity
    counts: np.ndarray  # [E] kept (reservoir-capped) rows per entity
    keep_sorted: np.ndarray  # [n] bool mask in sorted order
    rank_sorted: np.ndarray  # [n] within-entity rank in sorted order
    active: np.ndarray  # [E] bool — trains a model
    table: _ProjectorTable
    proj_all: np.ndarray  # [E, S] feature ids, -1 pad
    sub_dims: np.ndarray  # [E]
    max_sub_dim: int
    intercept_slots_all: np.ndarray  # [E] int32; -1 none
    bucket_members: dict  # cap -> np.ndarray of entity codes
    num_features: int


def _plan_random_effect(
    game_data: GameDataset,
    config: RandomEffectDataConfiguration,
    *,
    intercept_index: int | None,
    extra_features: dict[int, np.ndarray] | None,
) -> _Plan:
    """Vectorized host planning pass (see module docstring, stage 1)."""
    tag = game_data.id_tags[config.random_effect_type]
    codes = tag.host_codes().astype(np.int64, copy=False)
    num_entities = tag.num_groups
    n = codes.shape[0]
    ell_idx, ell_val, num_features = game_data.host_shard_coo(
        config.feature_shard_id
    )
    labels_np = game_data.host_column("labels")
    uids = (
        game_data.uids.astype(np.int64)
        if game_data.uids is not None
        else np.arange(n, dtype=np.int64)
    )

    # --- 1. deterministic reservoir cap: per entity keep the
    # active_data_upper_bound rows with smallest hash keys -----------------
    # Chunked passes (bincount partial sums, elementwise hash mixing) are
    # EXACT: the parallel planner's output is bit-identical to serial.
    counts_full = bincount_chunked(codes, num_entities).astype(
        np.int64, copy=False
    )
    upper = config.active_data_upper_bound
    lower = config.active_data_lower_bound
    cap_binds = upper is not None and bool(
        counts_full.max(initial=0) > upper
    )
    if cap_binds:
        seed = _stable_type_seed(config.random_effect_type)
        order_keys = map_chunked(
            lambda u: _byteswap64_mix(u, seed),
            np.empty(n, dtype=np.uint64),
            uids,
        )
        # Group-by-entity, ordered by hash within the group. A two-key
        # lexsort costs two comparison sorts (~1.5s at 4M rows — the
        # single hottest planning op); packing (code, high hash bits) into
        # one int64 lets numpy's stable integer argsort run as an O(n)
        # radix sort instead. Within-entity ties on the truncated hash
        # fall back to stable row order — still a deterministic uniform
        # reservoir (the hash bits kept exceed 2x log2(n) for any E below
        # 2^20, so ties are vanishing).
        code_bits = max(int(num_entities - 1).bit_length(), 1)
        if code_bits <= 40:
            hash_bits = 63 - code_bits
            key = map_chunked(
                lambda c, k: (c << hash_bits) | (
                    k >> np.uint64(64 - hash_bits)
                ).astype(np.int64),
                np.empty(n, dtype=np.int64),
                codes, order_keys,
            )
            perm = np.argsort(key, kind="stable")
        else:  # pathological entity counts: keep the exact two-key sort
            perm = np.lexsort((order_keys, codes))
    else:
        # No entity exceeds the cap (or no cap): the reservoir keeps every
        # row, so within-entity order is irrelevant — group by entity
        # alone with a narrow radix sort and skip the hashing pass.
        sort_codes = (
            codes.astype(np.int32) if num_entities <= (1 << 31) - 1
            else codes
        )
        perm = np.argsort(sort_codes, kind="stable")
    sorted_codes = codes[perm]
    starts = np.searchsorted(sorted_codes, np.arange(num_entities))
    counts = (
        counts_full if upper is None else np.minimum(counts_full, upper)
    )
    # Within-entity rank of each sorted position (0 = smallest hash key).
    rank_sorted = np.arange(n, dtype=np.int64) - np.repeat(
        starts, counts_full
    ) if n else np.empty(0, dtype=np.int64)
    keep_sorted = (
        np.ones(n, dtype=bool) if upper is None else rank_sorted < upper
    )
    # Lower-bound filter: too-small entities train no model (their rows
    # still score via the zero row of the coefficient matrix).
    active = counts >= (lower or 1)

    # --- 2. per-entity subspace projectors (one global unique) ------------
    stride = num_features
    if extra_features:
        for arr in extra_features.values():
            a = np.asarray(arr)
            if a.size:
                stride = max(stride, int(a.max()) + 1)
    tail = game_data.host_shard_tail(config.feature_shard_id)
    proj_mask = keep_sorted & active[sorted_codes]
    rows_p = perm[proj_mask]
    pair_codes = sorted_codes[proj_mask]
    dense_view = isinstance(
        game_data.feature_shards[config.feature_shard_id], DenseFeatures
    )
    if rows_p.size and dense_view and tail is None:
        # Dense shards: every row touches every column, so the per-entity
        # active-feature union is a [E, d] presence matrix computed by one
        # segment-OR over the entity-grouped rows — no 17M-key sort. This
        # is the hot ingest path for dense GLMix shards (the reference
        # amortizes the equivalent union across the cluster's foldByKey,
        # RandomEffectDataset.scala:390-426).
        # Compare/gather in whichever order moves fewer bytes: when most
        # rows are kept, compare first (the bool matrix is 4x narrower
        # than the floats, so the fancy-index moves 4x fewer bytes); when
        # the reservoir cap discards most rows, gather the kept rows
        # first and compare only those.
        if rows_p.size * 2 > ell_val.shape[0]:
            present = (ell_val != 0.0)[rows_p]  # [m, d]
        else:
            present = ell_val[rows_p] != 0.0
        if present.all():
            # Fully dense kept rows (no exact zeros anywhere): every
            # active entity's subspace is the whole feature set — skip
            # the segment-OR entirely.
            presence = np.zeros((num_entities, ell_val.shape[1]), bool)
            presence[np.unique(pair_codes)] = True
        else:
            m = rows_p.shape[0]
            seg_starts = np.searchsorted(
                pair_codes, np.arange(num_entities))
            seg_ends = np.append(seg_starts[1:], m)
            nonempty = seg_starts < seg_ends
            # reduceat over the NONEMPTY starts only: consecutive empty
            # segments share their successor's start, so a naive clamp of
            # trailing starts to m-1 would shave the last row off the
            # preceding entity's union. Nonempty starts partition [0, m)
            # exactly (each spans to the next nonempty start).
            presence = np.zeros(
                (num_entities, ell_val.shape[1]), dtype=bool)
            if nonempty.any():
                presence[nonempty] = np.logical_or.reduceat(
                    present, seg_starts[nonempty], axis=0
                )
        rows_e, cols_f = np.nonzero(presence)
        # Row-major nonzero order == ascending key order (stride >= d).
        uniq = rows_e.astype(np.int64) * np.int64(stride) + cols_f
    elif rows_p.size:
        iv = ell_idx[rows_p]
        present = ell_val[rows_p] != 0.0
        pair_keys = (
            np.broadcast_to(pair_codes[:, None], iv.shape)[present]
            * np.int64(stride)
            + iv[present].astype(np.int64)
        )
        if tail is not None:
            # Dual-ELL overflow entries contribute subspace features too.
            mask_rows = np.zeros(n, dtype=bool)
            mask_rows[rows_p] = True
            tr, ti, tv = tail
            sel = mask_rows[tr] & (tv != 0.0)
            if sel.any():
                tail_keys = (
                    codes[tr[sel]] * np.int64(stride)
                    + ti[sel].astype(np.int64)
                )
                pair_keys = np.concatenate([pair_keys, tail_keys])
        uniq = np.unique(pair_keys)
    else:
        uniq = np.empty(0, dtype=np.int64)

    needs_rework = bool(extra_features) or (
        config.features_to_samples_ratio is not None
    )
    if needs_rework:
        e_of = uniq // stride
        f_of = uniq % stride
        e_starts = np.searchsorted(e_of, np.arange(num_entities))
        e_ends = np.searchsorted(
            e_of, np.arange(num_entities), side="right"
        )
        projs = [f_of[e_starts[e]:e_ends[e]] for e in range(num_entities)]
        ratio = config.features_to_samples_ratio
        active_ids = np.nonzero(active)[0]
        for e in active_ids:
            act = projs[e]
            if ratio is not None:
                # Kept rows are the first counts[e] of the entity's sorted
                # span (rank < upper by construction) — O(rows_e), not a
                # full-array scan.
                rows_e = perm[starts[e]:starts[e] + counts[e]]
                keep = max(int(ratio * rows_e.size), 1)
                pe_i, pe_v = _subset_rows_widened(
                    ell_idx, ell_val, tail, rows_e
                )
                act = _pearson_select(
                    pe_v, pe_i, labels_np[rows_e],
                    act, keep, intercept_index, num_features,
                )
            # Prior-model support is unioned AFTER the Pearson filter:
            # features a warm-start model depends on must stay in the
            # subspace even when inactive/filtered in the current data
            # (RandomEffectDataset.scala:390-426 unions unconditionally).
            if extra_features and e in extra_features:
                act = np.union1d(
                    act, np.asarray(extra_features[e], dtype=act.dtype)
                )
            projs[e] = act
        table = _ProjectorTable.from_lists(projs, stride)
    else:
        offsets = np.zeros(num_entities + 1, dtype=np.int64)
        e_of = uniq // stride
        offsets[1:] = np.searchsorted(
            e_of, np.arange(num_entities), side="right"
        )
        table = _ProjectorTable(uniq, offsets, stride, num_entities)

    sub_dims = table.sub_dims
    max_sub_dim = max(int(sub_dims.max()) if num_entities else 1, 1)
    # proj_all scatter-fill: one flat write.
    proj_all = np.full((num_entities, max_sub_dim), -1, dtype=np.int64)
    if table.keys.size:
        row_of = np.repeat(np.arange(num_entities), sub_dims)
        col_of = np.arange(table.keys.size) - np.repeat(
            table.offsets[:-1], sub_dims
        )
        proj_all[row_of, col_of] = table.keys % stride

    # Intercept slot per entity (vectorized projector lookup).
    if intercept_index is not None and num_entities:
        slots, found = table.lookup(
            np.arange(num_entities),
            np.full(num_entities, intercept_index, dtype=np.int64),
        )
        intercept_slots_all = np.where(found, slots, -1).astype(np.int32)
    else:
        intercept_slots_all = np.full(num_entities, -1, dtype=np.int32)

    # --- 3. size-bucket membership ----------------------------------------
    bucket_members = _assign_buckets(
        counts, active, config.bucket_caps, config.min_bucket_entities
    )
    return _Plan(
        codes=codes,
        perm=perm,
        sorted_codes=sorted_codes,
        starts=starts,
        counts_full=counts_full,
        counts=counts,
        keep_sorted=keep_sorted,
        rank_sorted=rank_sorted,
        active=active,
        table=table,
        proj_all=proj_all,
        sub_dims=sub_dims,
        max_sub_dim=max_sub_dim,
        intercept_slots_all=intercept_slots_all,
        bucket_members=bucket_members,
        num_features=num_features,
    )


def _assign_buckets(
    counts: np.ndarray,
    active: np.ndarray,
    bucket_caps: tuple,
    min_bucket_entities: int = 0,
) -> dict:
    """cap -> member entity codes (ascending), shared between the planner
    and the ingest pipeline's shape oracle (``predict_plan_shapes``) so
    predicted block shapes can never drift from the built ones.

    ``min_bucket_entities`` > 0 merges undersized buckets UPWARD into
    the next occupied (or next configured) cap: a warm refit then
    dispatches fewer, fatter programs instead of paying one launch per
    bucket-tail. The largest bucket never merges (nothing above holds
    its rows); merging only ever widens padding, never drops rows."""
    caps = np.asarray(sorted(bucket_caps), dtype=np.int64)
    active_ids = np.nonzero(active)[0]
    r = counts[active_ids]
    pos = np.searchsorted(caps, r)
    # Entities above the largest cap round up to the next power of two so
    # heavy-tailed size distributions share padded shapes (and jit compiles
    # of the solver) instead of one shape per distinct size.
    pow2 = np.left_shift(
        np.int64(1),
        np.ceil(np.log2(np.maximum(r, 1).astype(np.float64))).astype(
            np.int64
        ),
    )
    cap_of = np.where(pos < caps.size, caps[np.minimum(pos, caps.size - 1)],
                      pow2)
    members = {
        int(c): active_ids[cap_of == c] for c in np.unique(cap_of)
    }
    floor = int(min_bucket_entities or 0)
    if floor > 0 and len(members) > 1:
        occupied = sorted(members)
        merged: dict[int, np.ndarray] = {}
        pending: np.ndarray | None = None
        for i, cap in enumerate(occupied):
            ids = members[cap]
            if pending is not None:
                ids = np.union1d(pending, ids)
                pending = None
            if ids.size < floor and i < len(occupied) - 1:
                pending = ids  # tail rides up into the next bucket
            else:
                # The largest bucket always lands here (its cap holds
                # every smaller entity's rows), so no tail is dropped.
                merged[cap] = ids
        members = merged
    return members


def _split_packed_impl(buf, shapes):
    out = []
    o = 0
    for s in shapes:
        n = int(np.prod(s)) if s else 1
        out.append(jax.lax.slice_in_dim(buf, o, o + n).reshape(s))
        o += n
    return tuple(out)


_split_packed = jax.jit(_split_packed_impl, static_argnames=("shapes",))


# Packed-plan layout contract (build_random_effect_dataset's lazy branch):
# PLAN_ARRAYS_PER_BUCKET arrays per bucket (members, row_ids, counts, proj,
# intercepts), then the [E, S] projector table, then the score gather map.
# Every consumer (device_plans, proj_device, score_inv_device, the fused
# materialization program) indexes through these helpers.
PLAN_ARRAYS_PER_BUCKET = 5


def packed_proj_index(n_blocks: int) -> int:
    return PLAN_ARRAYS_PER_BUCKET * n_blocks


def packed_score_inv_index(n_blocks: int) -> int:
    return PLAN_ARRAYS_PER_BUCKET * n_blocks + 1


def packed_len_with_score_inv(n_blocks: int) -> int:
    return PLAN_ARRAYS_PER_BUCKET * n_blocks + 2


class PackedPlanArrays:
    """Every plan array of a build in ONE granule-padded int32 device
    buffer.

    Remote device links pay a per-transfer-shape setup cost (~65ms each on
    the dev-tunnel TPU backend); ~30 distinct plan-array shapes made that
    the dominant ingest cost (~2s). One packed buffer pays ONE setup, and
    nothing else happens at ingest time:

    - the fused fit slices the buffer INSIDE its own traced programs
      (``slice_in_trace`` — zero additional XLA programs, zero transfers);
    - eager consumers (the unfused loop, tests, mesh sharding) split it
      once through ``device_arrays()``, paying the splitter program's
      compile only when that fallback path actually runs.
    """

    def __init__(self, buf: Array, shapes: tuple):
        self.buf = buf
        self.shapes = tuple(tuple(s) for s in shapes)
        sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        offs = np.cumsum([0] + sizes)
        self.offsets = tuple(int(o) for o in offs[:-1])
        self._split: tuple | None = None

    def __len__(self) -> int:
        return len(self.shapes)

    def view(self, lo: int, hi: int) -> "_PackedPlanView":
        return _PackedPlanView(self, lo, hi)

    @property
    def buffer(self) -> Array:
        return self.buf

    def static_slices(self) -> tuple:
        """((element offset, shape), ...) — THE layout contract for
        traced consumers: slice ``buffer`` at these static offsets inside
        a jit (the fused fit's materialization program does)."""
        return tuple(zip(self.offsets, self.shapes))

    def device_arrays(self) -> tuple:
        if self._split is None:
            self._split = _split_packed(self.buf, shapes=self.shapes)
        return self._split


class _PackedPlanView:
    """Subrange of a PackedPlanArrays (one dataset's arrays of a multi-
    coordinate batch transfer)."""

    def __init__(self, packed: PackedPlanArrays, lo: int, hi: int):
        self.packed = packed
        self.lo = lo
        self.hi = hi

    def __len__(self) -> int:
        return self.hi - self.lo

    @property
    def buffer(self) -> Array:
        return self.packed.buf

    def static_slices(self) -> tuple:
        return self.packed.static_slices()[self.lo:self.hi]

    def device_arrays(self) -> tuple:
        return self.packed.device_arrays()[self.lo:self.hi]


class _ListPlanArrays:
    """Plain per-array placement fallback for non-int32 plan arrays.

    ``static_slices`` is None: traced consumers fall back to taking the
    per-array device handles as operands."""

    static_slices = staticmethod(lambda: None)

    def __init__(self, arrays):
        self._arrays = None
        self._host = list(arrays)

    def __len__(self) -> int:
        return len(self._host)

    def view(self, lo: int, hi: int):
        out = _ListPlanArrays(self._host[lo:hi])
        return out

    def device_arrays(self) -> tuple:
        if self._arrays is None:
            self._arrays = tuple(jax.device_put(self._host))
        return self._arrays


def _plan_arrays_to_device(arrays: list[np.ndarray]):
    """Stage host plan arrays for device use: ONE packed buffer.

    Returns a PackedPlanArrays (or a _ListPlanArrays fallback when dtypes
    are mixed). Device placement goes through the ingest pipeline's
    chunked double-buffered transfer (``pipeline.packed_device_put``):
    below one chunk it is the legacy single staging fill + one
    ``device_put``; above it, granule-aligned chunks stream out
    asynchronously while the host fills the next chunk, and a donated
    in-trace concatenate restores the one contiguous buffer — the packed
    layout contract (``static_slices``) is byte-identical either way.
    """
    if any(a.dtype != np.int32 for a in arrays):
        return _ListPlanArrays(arrays)
    buf, shapes = packed_device_put(arrays)
    return PackedPlanArrays(buf, shapes)


def _bucket_rows(plan: _Plan, members: np.ndarray, cap: int):
    """Vectorized bucket row layout: (rows_flat, t_of, r_of, counts_b).

    ``rows_flat`` are the kept canonical rows of all member entities,
    grouped by entity (reservoir hash order within); ``t_of``/``r_of`` are
    their (bucket slot, within-entity rank) coordinates.

    Pure span arithmetic over the sorted order: each member entity's kept
    rows are exactly the FIRST ``counts[e]`` positions of its sorted span
    (the reservoir keeps the ``upper`` smallest hash keys, which the
    planner's sort puts first), so the selection is O(member rows). The
    previous form re-gathered ``codes[perm]`` and boolean-scanned the
    FULL row table once PER BUCKET — O(n x buckets) host passes that the
    round-5 ingest-floor bisect identified as the planner's real
    regression (the suspected ``cache_stats()`` dir scan never runs in
    the prepare path). Output is bit-identical (pinned by
    tests/test_ingest_pipeline.py against the full-scan reference).
    """
    m_starts = plan.starts[members]
    m_counts = plan.counts[members]
    total = int(m_counts.sum())
    if total == 0:
        empty = np.empty(0, dtype=np.int64)
        return empty, empty.copy(), empty.copy(), m_counts
    t_of = np.repeat(np.arange(members.size, dtype=np.int64), m_counts)
    span_base = np.cumsum(m_counts) - m_counts
    r_of = np.arange(total, dtype=np.int64) - span_base[t_of]
    rows_flat = plan.perm[m_starts[t_of] + r_of]
    return rows_flat, t_of, r_of, m_counts


def _score_table_arrays(
    codes: np.ndarray,
    ell_idx: np.ndarray,
    ell_val: np.ndarray,
    table: _ProjectorTable,
    width_cap: int | None,
    tail_in=None,  # input COO overflow of a DualEll shard, or None
):
    """Materialized scoring-table remap for ALL rows (vectorized).

    Returns (si, sv, tail) where tail is None when uncapped, else
    (rows, indices, values) sorted by row — entries beyond the slab cap
    stream into a COO tail so one dense row never inflates every row's slab
    (SURVEY §7.3 width hazard). ``tail_in`` overflow entries of a dual-ELL
    input stay in COO form end to end when a cap is set; only an uncapped
    build widens them into the rectangular output.
    """
    if tail_in is not None and width_cap is None:
        # Rectangular output was explicitly requested without a bound:
        # widen (old behavior). Width-hazard data should set the cap.
        ell_idx, ell_val = _subset_rows_widened(
            ell_idx, ell_val, tail_in, np.arange(codes.shape[0])
        )
        tail_in = None
    slot, found = table.lookup(codes[:, None], ell_idx)
    found = found & (ell_val != 0.0)
    k_comp = max(int(found.sum(axis=1).max(initial=0)), 1)
    if width_cap is None:
        si, sv = _compact_left(slot, ell_val, found, k_comp)
        return si, sv, None
    k_slab = max(min(width_cap, k_comp), 1)
    si_f, sv_f = _compact_left(slot, ell_val, found, k_comp)
    si, sv = si_f[:, :k_slab], sv_f[:, :k_slab]
    over_i, over_v = si_f[:, k_slab:], sv_f[:, k_slab:]
    mask = over_v != 0.0
    parts_r, parts_i, parts_v = [], [], []
    if mask.any():
        row_of = np.broadcast_to(
            np.arange(codes.shape[0], dtype=np.int64)[:, None], mask.shape
        )
        parts_r.append(row_of[mask])
        parts_i.append(over_i[mask].astype(np.int64))
        parts_v.append(over_v[mask])
    if tail_in is not None:
        tr_in, ti_in, tv_in = tail_in
        slot_t, found_t = table.lookup(codes[tr_in], ti_in)
        ok = found_t & (tv_in != 0.0)
        if ok.any():
            parts_r.append(tr_in[ok].astype(np.int64))
            parts_i.append(slot_t[ok].astype(np.int64))
            parts_v.append(tv_in[ok])
    if parts_r:
        tr = np.concatenate(parts_r)
        ti = np.concatenate(parts_i)
        tv = np.concatenate(parts_v)
        o = np.argsort(tr, kind="stable")  # segment_sum wants sorted rows
        tail = (tr[o], ti[o], tv[o])
    else:
        tail = (
            np.empty(0, np.int64), np.empty(0, np.int64),
            np.empty(0, ell_val.dtype),
        )
    return si, sv, tail


def remap_for_scoring(
    game_data: GameDataset,
    *,
    re_type: str,
    feature_shard_id: str,
    entity_keys: tuple,
    proj_all: np.ndarray,  # [E, S] original feature ids; -1 pad
    dtype=None,
    width_cap: int | None = None,
) -> tuple[Array, Array, Array, tuple[Array, Array, Array] | None]:
    """Remap an arbitrary GameDataset's rows into trained entity subspaces.

    Returns (codes, indices, values, tail) consumable by
    ``score_entity_table_with_tail`` — the materialized scoring path for
    validation / test data (RandomEffectModel.score :70 joins new data by
    REId; entities unseen at training time contribute score 0, matching the
    reference's left-join semantics where rows without a model get no
    score). ``tail`` is None when ``width_cap`` is unset, else device
    (rows, indices, values) arrays for the capped table's COO overflow.
    """
    if dtype is None:
        dtype = game_data.labels.dtype
    codes = scoring_codes(game_data, re_type, entity_keys)
    ell_idx, ell_val, num_features = game_data.host_shard_coo(
        feature_shard_id
    )
    table = projector_table_from_proj_all(proj_all, num_features)
    si, sv, tail = _score_table_arrays(
        codes, ell_idx, ell_val, table, width_cap,
        tail_in=game_data.host_shard_tail(feature_shard_id),
    )
    # Unseen entities: clamp the code and zero the values -> score 0.
    unseen = codes < 0
    sv[unseen] = 0.0
    codes_safe = np.maximum(codes, 0)
    tail_out = None
    if tail is not None:
        tr, ti, tv = tail
        # Invariant: negative-code rows never produce projector hits, so
        # the tail only holds rows of KNOWN entities.
        assert not unseen[tr].any()
        tail_out = (
            jnp.asarray(tr.astype(np.int32)),
            jnp.asarray(ti.astype(np.int32)),
            jnp.asarray(tv, dtype=dtype),
        )
    return (
        jnp.asarray(codes_safe.astype(np.int32)),
        jnp.asarray(si),
        jnp.asarray(sv, dtype=dtype),
        tail_out,
    )


def scoring_codes(
    game_data: GameDataset, re_type: str, entity_keys: tuple
) -> np.ndarray:
    """[n] trained-entity code per row of ``game_data`` (-1 = unseen)."""
    tag = game_data.id_tags[re_type]
    vocab = {str(k): i for i, k in enumerate(entity_keys)}
    code_map = np.array(
        [vocab.get(str(k), -1) for k in tag.inverse], dtype=np.int64
    )
    if len(tag.inverse) and len(entity_keys) and (code_map < 0).all():
        import warnings

        warnings.warn(
            f"scoring remap({re_type!r}): none of {len(tag.inverse)} "
            f"dataset entities match the {len(entity_keys)} model entities "
            "— every random-effect score will be 0",
            stacklevel=2,
        )
    return code_map[tag.host_codes()]


def projector_table_from_proj_all(
    proj_all: np.ndarray, num_features: int
) -> _ProjectorTable:
    """Rebuild the flat projector table from a [E, S] proj matrix.

    A trained model's projectors may reference feature ids beyond a new
    dataset's shard dimension; the stride covers both so unknown features
    are dropped, not crashed on."""
    e, s = proj_all.shape if proj_all.ndim == 2 else (0, 0)
    stride = num_features
    if proj_all.size:
        stride = max(stride, int(proj_all.max(initial=0)) + 1)
    valid = proj_all >= 0
    sizes = valid.sum(axis=1).astype(np.int64) if e else np.empty(0, np.int64)
    offsets = np.zeros(e + 1, dtype=np.int64)
    np.cumsum(sizes, out=offsets[1:])
    if e and offsets[-1]:
        row_of = np.repeat(np.arange(e, dtype=np.int64), sizes)
        keys = row_of * stride + proj_all[valid].astype(np.int64)
    else:
        keys = np.empty(0, dtype=np.int64)
    return _ProjectorTable(keys, offsets, stride, e)


@dataclasses.dataclass
class PendingRandomEffectDataset:
    """A lazy-layout build whose device placement is deferred.

    ``flat`` lists the int32 plan arrays awaiting transfer; ``finalize``
    consumes their device arrays (same order) and returns the dataset. The
    estimator batches every coordinate's transfer into ONE packed push —
    one transfer-path setup and one cached split program for the whole fit
    instead of one per coordinate (`_plan_arrays_to_device`).
    """

    flat: list
    finalize: object  # Callable[[list], RandomEffectDataset]


def predict_plan_shapes(
    game_data: GameDataset,
    config: RandomEffectDataConfiguration,
) -> dict | None:
    """Predict every padded block shape of a build from configs + entity
    counts alone — the ingest pipeline's shape oracle.

    The full planner needs the expensive sorted passes; the SHAPES need
    only the per-entity row counts (one chunked bincount) plus the dense
    shard width: a fully dense shard's active entities all span the whole
    feature set, so every bucket's projector width is ``d``. That lets the
    estimator kick off the fused-fit AOT compile while planning is still
    running. Returns None when shapes can't be predicted without planning
    (sparse shards, Pearson filtering, width caps, wide subspaces) — and a
    WRONG prediction (a dense shard with exact zeros) only wastes the
    background compile: the real fit falls back to the normal jit path,
    never to wrong results.
    """
    feats = game_data.feature_shards.get(config.feature_shard_id)
    if not isinstance(feats, DenseFeatures):
        return None
    if config.features_to_samples_ratio is not None:
        return None
    if config.score_table_width_cap is not None:
        return None
    d = int(feats.x.shape[1])
    if d > DENSE_SUB_DIM_MAX:
        return None  # auto-lazy would refuse; the fused path needs lazy
    tag = game_data.id_tags[config.random_effect_type]
    codes = tag.host_codes()
    num_entities = tag.num_groups
    n = int(codes.shape[0])
    counts_full = bincount_chunked(codes, num_entities).astype(
        np.int64, copy=False
    )
    upper = config.active_data_upper_bound
    lower = config.active_data_lower_bound
    counts = (
        counts_full if upper is None else np.minimum(counts_full, upper)
    )
    active = counts >= (lower or 1)
    bucket_members = _assign_buckets(
        counts, active, config.bucket_caps, config.min_bucket_entities
    )
    any_active = bool(active.any())
    max_sub_dim = d if any_active else 1
    buckets = [
        (cap, int(bucket_members[cap].size), d)
        for cap in sorted(bucket_members)
    ]
    shapes: list[tuple] = []
    for cap, b, s in buckets:
        shapes += [(b,), (b, cap), (b,), (b, s), (b,)]
    shapes.append((num_entities, max_sub_dim))  # projector table
    shapes.append((n,))  # inverse score map
    kept_total = int(counts[active].sum())
    return dict(
        num_entities=num_entities,
        num_rows=n,
        num_features=d,
        max_sub_dim=max_sub_dim,
        buckets=buckets,
        packed_shapes=tuple(shapes),
        kept_total=kept_total,
    )


def skeleton_random_effect_dataset(
    game_data: GameDataset,
    config: RandomEffectDataConfiguration,
) -> RandomEffectDataset | None:
    """A shape-faithful stand-in for one coordinate's lazy dataset.

    Plan leaves are zero host arrays at the PREDICTED shapes; the raw
    feature / label / offset / weight leaves are the REAL device arrays
    (already resident from ``make_game_dataset``), and the packed view
    carries a ``ShapeDtypeStruct`` buffer — enough for ``FusedFit`` to
    trace, lower, and AOT-compile the exact production programs while the
    real planner is still running. Never used to train: only the compiled
    executables (keyed by the fused static key + operand avals) survive.
    """
    import jax as _jax

    from photon_tpu.data.pipeline import padded_len

    pred = predict_plan_shapes(game_data, config)
    if pred is None:
        return None
    tag = game_data.id_tags[config.random_effect_type]
    feats = game_data.feature_shards[config.feature_shard_id]
    e = pred["num_entities"]
    n = pred["num_rows"]
    s_all = pred["max_sub_dim"]
    blocks = []
    for cap, b, s in pred["buckets"]:
        blocks.append(BlockPlan(
            entity_codes=np.zeros(b, np.int32),
            row_ids=np.zeros((b, cap), np.int32),
            row_counts=np.zeros(b, np.int32),
            proj=np.zeros((b, s), np.int32),
            intercept_slots=np.zeros(b, np.int32),
            raw=feats,
            raw_labels=game_data.labels,
            raw_offsets=game_data.offsets,
            raw_weights=game_data.weights,
        ))
    total = sum(
        int(np.prod(sh)) if sh else 1 for sh in pred["packed_shapes"]
    )
    n_pad = padded_len(total)
    packed = PackedPlanArrays(
        _jax.ShapeDtypeStruct((n_pad,), np.int32), pred["packed_shapes"]
    )
    covered = np.zeros(n, dtype=bool)
    covered[:pred["kept_total"]] = True
    sub_dims = np.zeros(e, dtype=np.int64)
    sub_dims[:] = pred["num_features"]
    return RandomEffectDataset(
        config=config,
        num_entities=e,
        entity_keys=tag.inverse,
        blocks=tuple(blocks),
        max_sub_dim=s_all,
        sub_dims=sub_dims,
        proj_all=np.full((e, s_all), -1, dtype=np.int64),
        num_features=pred["num_features"],
        dtype=game_data.labels.dtype,
        score_codes=tag.codes,
        raw=feats,
        proj_dev=None,
        block_codes_np=tuple(
            np.zeros(b, np.int32) for _, b, _ in pred["buckets"]
        ),
        block_intercepts_np=tuple(
            np.zeros(b, np.int32) for _, b, _ in pred["buckets"]
        ),
        covered_np=covered,
        packed_view=packed,
    )


def _gram_window_bounds(
    bi: np.ndarray, bv: np.ndarray, sub_dim: int
) -> tuple | None:
    """HOST (grad_mult, hess_mult) window bounds for one bucket's ELL
    slabs — the static coverage key of the direct gram route
    (algorithm/random_effect._solve_direct_gram) — or None when that
    route can never engage for this bucket.

    Counts only NONZERO entries (the device side remaps zero products to
    the drop segment, so device counts are always <= these), binned into
    the kernel's output windows via ``segment_reduce.window_counts_np``.
    A uniform per-segment bound would be useless: the intercept slot
    co-occurs with every row of its entity, putting the per-SEGMENT
    multiplicity at the row count while whole windows stay cheap.
    Entity-axis PADDING (parallel/mesh) appends inert zero-weight
    entities after these ids, so the bounds survive mesh sharding.
    """
    b, cap, k = bi.shape
    s = int(sub_dim)
    if (
        s <= DENSE_SUB_DIM_MAX
        and b * cap * k * s <= ONE_HOT_ELEMENT_BUDGET
    ):
        return None  # bucket densifies up front; the gram route is moot
    if b * cap * k * k > segment_reduce.GRAM_ELEMENT_BUDGET:
        return None  # pair pass over budget on device and host alike
    nz = bv != 0.0
    grad_counts = hess_counts = None
    # Chunk over the entity axis: the pair-id tensor is
    # [chunk, cap, k, k] int64, a bounded transient for any bucket size.
    step = max(1, (1 << 22) // max(cap * k * k, 1))
    for lo in range(0, b, step):
        hi = min(lo + step, b)
        ent = np.arange(lo, hi, dtype=np.int64)[:, None, None]
        nzc = nz[lo:hi]
        bic = bi[lo:hi].astype(np.int64)
        gids = (ent * s + bic)[nzc]
        gc = segment_reduce.window_counts_np(gids, b * s)
        grad_counts = gc if grad_counts is None else grad_counts + gc
        pair_nz = nzc[:, :, :, None] & nzc[:, :, None, :]
        pids = (
            ent[..., None] * (s * s)
            + bic[:, :, :, None] * s
            + bic[:, :, None, :]
        )[pair_nz]
        hc = segment_reduce.window_counts_np(pids, b * s * s)
        hess_counts = hc if hess_counts is None else hess_counts + hc
    return (
        segment_reduce.window_bound_from_counts(grad_counts.max()),
        segment_reduce.window_bound_from_counts(hess_counts.max()),
    )


def build_random_effect_dataset(
    game_data: GameDataset,
    config: RandomEffectDataConfiguration,
    *,
    intercept_index: int | None = None,
    extra_features: dict[int, np.ndarray] | None = None,
    dtype=None,
    lazy: bool | None = None,
    defer_transfer: bool = False,
) -> RandomEffectDataset:
    """One-shot host-side ingest of a random-effect coordinate's data.

    ``extra_features`` maps entity code -> original feature ids that must be
    in the entity's subspace even if inactive in the data — the prior-model
    support used for warm-start/incremental training
    (RandomEffectDataset.scala:390-426 unions the existing model's features).

    ``lazy`` (default: auto) selects the device layout: lazy BlockPlans that
    materialize inside the jitted solver (Dense/Sparse shards), or fully
    materialized EntityBlocks + scoring table (always used for
    ``DualEllFeatures`` shards, whose COO tail is not row-gatherable).
    """
    requested_dtype = dtype
    if dtype is None:
        dtype = game_data.labels.dtype
    feats = game_data.feature_shards[config.feature_shard_id]
    lazy_capable = isinstance(feats, (DenseFeatures, SparseFeatures))
    # The lazy layout trains straight off the raw device arrays, so it
    # cannot honor a dtype different from the data's.
    dtype_matches = (
        requested_dtype is None
        or jnp.dtype(requested_dtype) == jnp.dtype(game_data.labels.dtype)
    )
    with PIPELINE_STATS.stage("plan"):
        plan = _plan_random_effect(
            game_data, config,
            intercept_index=intercept_index, extra_features=extra_features,
        )
    if lazy is None:
        # An explicit score-table width cap is a signal that max_sub_dim is
        # dominated by heavy entities (SURVEY §7.3): the lazy scorer's
        # [n, S] gather intermediates would recreate exactly the hazard the
        # cap bounds, so honor it with the materialized dual-ELL table.
        # Very wide subspaces likewise stay materialized: the lazy path's
        # one-hot densification is sized for small sub_dims.
        lazy = (
            lazy_capable
            and dtype_matches
            and config.score_table_width_cap is None
            and plan.max_sub_dim <= DENSE_SUB_DIM_MAX
        )
    if lazy and not lazy_capable:
        raise TypeError(
            "lazy random-effect layout requires Dense or Sparse (ELL) "
            f"features, got {type(feats).__name__}"
        )
    if lazy and not dtype_matches:
        raise ValueError(
            f"lazy random-effect layout cannot retype the raw data "
            f"({game_data.labels.dtype} -> {requested_dtype}); pass "
            "lazy=False or build the GameDataset in the target dtype"
        )
    tag = game_data.id_tags[config.random_effect_type]
    num_entities = tag.num_groups

    # Per-bucket plan arrays (all vectorized scatters). Buckets are
    # independent, so they build concurrently on the chunk pool; the
    # ordered wait keeps bucket_host in ascending-cap order, identical to
    # the serial loop.
    def _build_bucket(cap: int) -> dict:
        members = plan.bucket_members[cap]
        rows_flat, t_of, r_of, counts_b = _bucket_rows(plan, members, cap)
        b = members.size
        brow = np.zeros((b, cap), dtype=np.int32)
        brow[t_of, r_of] = rows_flat
        sub = plan.sub_dims[members]
        s = max(int(sub.max(initial=0)), 1)
        bproj = plan.proj_all[members][:, :s].astype(np.int32)
        return dict(
            cap=cap,
            members=members.astype(np.int32),
            brow=brow,
            counts=counts_b.astype(np.int32),
            proj=bproj,
            intercepts=plan.intercept_slots_all[members],
            rows_flat=rows_flat,
            t_of=t_of,
            r_of=r_of,
        )

    with PIPELINE_STATS.stage("pack"):
        # consume_futures: every bucket thunk's exception is observed
        # even when an earlier bucket already failed.
        bucket_host = consume_futures(
            [
                chunk_executor.submit(_build_bucket, cap)
                for cap in sorted(plan.bucket_members)
            ]
        )

    covered_np = np.zeros(plan.codes.shape[0], dtype=bool)
    for bh in bucket_host:
        covered_np[bh["rows_flat"]] = True

    ell_idx = ell_val = ell_tail = None
    if not lazy:
        ell_idx, ell_val, _ = game_data.host_shard_coo(
            config.feature_shard_id
        )
        ell_tail = game_data.host_shard_tail(config.feature_shard_id)
    labels_np = game_data.host_column("labels")
    offsets_np = game_data.host_column("offsets")
    weights_np = game_data.host_column("weights")

    if lazy:
        # Inverse score map: canonical row -> flat position in the
        # concatenation of all buckets' [B, cap] score blocks followed by
        # the passive-row score vector. Scoring then becomes ONE gather —
        # scatter-adds of bucket scores into [n] cost ~4x more on TPU
        # (measured 51ms vs 13ms per pass at bench shapes). Lazy-path
        # only: the materialized layout scores through its remapped table.
        score_inv_np = np.empty(plan.codes.shape[0], dtype=np.int32)
        base = 0
        for bh in bucket_host:
            cap = bh["brow"].shape[1]
            score_inv_np[bh["rows_flat"]] = (
                base + bh["t_of"] * cap + bh["r_of"]
            ).astype(np.int32)
            base += bh["brow"].size
        passive_rows = np.nonzero(~covered_np)[0]
        # base counts PADDED bucket blocks (B*cap per bucket, larger than
        # the row count), so it can cross 2^31 well before n does; past
        # that the int32 map silently wraps and corrupts scoring.
        if base + passive_rows.size >= 2**31:
            raise OverflowError(
                "flat score layout has "
                f"{base + passive_rows.size} elements, which overflows the "
                "int32 inverse score map; shard the random effect wider "
                "(smaller buckets) or reduce score_table_width_cap"
            )
        score_inv_np[passive_rows] = base + np.arange(
            passive_rows.size, dtype=np.int32)

        # ONE batched device_put for every plan array of every bucket.
        # Layout contract (device_plans / proj_device / the fused mat
        # program all index it): 5 arrays per bucket, then the [E, S]
        # projector table at 5*n_buckets, then the score gather map.
        flat: list[np.ndarray] = []
        for bh in bucket_host:
            flat += [bh["members"], bh["brow"], bh["counts"], bh["proj"],
                     bh["intercepts"]]
        proj_dev_np = plan.proj_all.astype(np.int32)
        flat.append(proj_dev_np)
        flat.append(score_inv_np)

        def finalize(devs):
            return _finalize_lazy(
                devs, bucket_host, feats, game_data, config, num_entities,
                tag, plan, dtype, covered_np,
            )

        if defer_transfer:
            return PendingRandomEffectDataset(flat=flat, finalize=finalize)
        return finalize(_plan_arrays_to_device(flat))

    # ---- materialized layout (DualEll shards, introspection) -------------
    blocks = []
    gram_mults_list = []
    for bh in bucket_host:
        members = bh["members"]
        b, cap = bh["brow"].shape
        rows_flat, t_of, r_of = bh["rows_flat"], bh["t_of"], bh["r_of"]
        s = bh["proj"].shape[1]
        # Remap every member row's ELL entries in one vectorized pass
        # (dual-ELL tails widen only to this bucket's own widest row).
        wi, wv = _subset_rows_widened(ell_idx, ell_val, ell_tail, rows_flat)
        slot, found = plan.table.lookup(plan.codes[rows_flat][:, None], wi)
        found = found & (wv != 0.0)
        k = max(int(found.sum(axis=1).max(initial=0)), 1)
        ri, rv = _compact_left(slot, wv, found, k)
        bi = np.zeros((b, cap, k), dtype=np.int32)
        bv = np.zeros((b, cap, k), dtype=ell_val.dtype)
        bi[t_of, r_of] = ri
        bv[t_of, r_of] = rv
        # Static coverage bounds for the direct ELL gram route (priced
        # here, at plan time, like score_tail_mult below): None when
        # this bucket can never take it.
        gram_mults_list.append(_gram_window_bounds(bi, bv, s))
        bl = np.zeros((b, cap), dtype=labels_np.dtype)
        bo = np.zeros((b, cap), dtype=offsets_np.dtype)
        bw = np.zeros((b, cap), dtype=weights_np.dtype)
        brow_arr = bh["brow"]
        bl[t_of, r_of] = labels_np[rows_flat]
        bo[t_of, r_of] = offsets_np[rows_flat]
        bw[t_of, r_of] = weights_np[rows_flat]
        bint = bh["intercepts"]
        slot_iota = np.arange(s)[None, :]
        valid = (slot_iota < plan.sub_dims[members][:, None]).astype(
            np.float32
        )
        penalty = valid.copy()
        has_int = bint >= 0
        penalty[has_int, bint[has_int]] = 0.0
        blocks.append(EntityBlocks(
            entity_codes=jnp.asarray(members),
            x_indices=jnp.asarray(bi),
            x_values=jnp.asarray(bv, dtype=dtype),
            labels=jnp.asarray(bl, dtype=dtype),
            offsets=jnp.asarray(bo, dtype=dtype),
            weights=jnp.asarray(bw, dtype=dtype),
            row_ids=jnp.asarray(brow_arr),
            proj=jnp.asarray(bh["proj"]),
            penalty_mask=jnp.asarray(penalty, dtype=dtype),
            valid_mask=jnp.asarray(valid, dtype=dtype),
            intercept_slots=jnp.asarray(bint),
        ))

    si, sv, tail = _score_table_arrays(
        plan.codes, ell_idx, ell_val, plan.table,
        config.score_table_width_cap, tail_in=ell_tail,
    )
    tail_r = tail_i = tail_v = None
    tail_mult = None
    if tail is not None:
        tail_r = jnp.asarray(tail[0].astype(np.int32))
        tail_i = jnp.asarray(tail[1].astype(np.int32))
        tail_v = jnp.asarray(tail[2], dtype=dtype)
        # Static per-row multiplicity bound for the tiled segment-reduce
        # (tail rows are sorted, so one bincount prices the worst row).
        tail_mult = (
            int(np.bincount(tail[0]).max()) if tail[0].size else 1
        )

    return RandomEffectDataset(
        config=config,
        num_entities=num_entities,
        entity_keys=tag.inverse,
        blocks=tuple(blocks),
        max_sub_dim=plan.max_sub_dim,
        sub_dims=plan.sub_dims,
        proj_all=plan.proj_all,
        num_features=plan.num_features,
        dtype=dtype,
        score_codes=jnp.asarray(plan.codes.astype(np.int32)),
        score_indices=jnp.asarray(si),
        score_values=jnp.asarray(sv, dtype=dtype),
        score_tail_rows=tail_r,
        score_tail_indices=tail_i,
        score_tail_values=tail_v,
        score_tail_mult=tail_mult,
        block_codes_np=tuple(bh["members"] for bh in bucket_host),
        block_intercepts_np=tuple(bh["intercepts"] for bh in bucket_host),
        block_gram_mults=tuple(gram_mults_list),
        covered_np=covered_np,
    )


def _finalize_lazy(
    devs, bucket_host, feats, game_data, config, num_entities, tag, plan,
    dtype, covered_np=None,
):
    """Assemble the lazy RandomEffectDataset around the packed plan view.

    ``devs`` is a PackedPlanArrays/_PackedPlanView: the plan arrays stay
    HOST numpy on the BlockPlan leaves (free), and device placement
    resolves lazily — in-trace slices for the fused fit, one split
    program via ``device_plans()`` for eager consumers."""
    blocks = []
    for bh in bucket_host:
        blocks.append(BlockPlan(
            entity_codes=bh["members"],
            row_ids=bh["brow"],
            row_counts=bh["counts"],
            proj=bh["proj"],
            intercept_slots=bh["intercepts"],
            raw=feats,
            raw_labels=game_data.labels,
            raw_offsets=game_data.offsets,
            raw_weights=game_data.weights,
        ))
    return RandomEffectDataset(
        config=config,
        num_entities=num_entities,
        entity_keys=tag.inverse,
        blocks=tuple(blocks),
        max_sub_dim=plan.max_sub_dim,
        sub_dims=plan.sub_dims,
        proj_all=plan.proj_all,
        num_features=plan.num_features,
        dtype=dtype,
        score_codes=tag.codes,
        raw=feats,
        proj_dev=None,
        block_codes_np=tuple(bh["members"] for bh in bucket_host),
        block_intercepts_np=tuple(
            bh["intercepts"] for bh in bucket_host
        ),
        covered_np=covered_np,
        packed_view=devs,
    )
