"""RandomEffectDataset: per-entity data as size-bucketed padded device blocks.

TPU-native counterpart of the heart of GLMix scaling (photon-api
data/RandomEffectDataset.scala:54, apply :264-354). The reference's build
pipeline — key by REId, per-entity ``LinearSubspaceProjector`` from the union
of active feature indices (:390-426), deterministic reservoir-sampling cap
(groupDataByKeyAndSample :468-527 with byteswap64 hash keys :510), feature
projection to the subspace (:538-550), optional Pearson-correlation feature
selection (:562-576), active-data lower-bound filter (:586-606) and passive
data as the leftovers (:631-640) — happens ONCE, host-side at ingest, and
produces static device arrays:

- **EntityBlocks** (training): entities grouped into size buckets; each bucket
  is a ``[B, R, k]`` ELL slab plus per-entity projector index arrays, so one
  vmapped solver call fits all B entities simultaneously. This replaces the
  reference's per-partition ``mapValues`` local solves
  (RandomEffectCoordinate.scala:243-292) and its partitioner bin-packing
  (RandomEffectDatasetPartitioner.scala:44): padding buckets instead of
  packing bins.
- **Scoring table** (active + passive rows): the full canonical table with
  feature indices remapped into each row's owning entity's subspace, so
  coordinate scoring is one gather-multiply-reduce against the
  ``[num_entities, max_sub_dim]`` coefficient matrix — no join by REId.
  Features outside an entity's subspace have their values zeroed (the
  projector drop semantics of LinearSubspaceProjector.projectForward).

Residual routing (addScoresToOffsets :83-110) reduces to gathering the
canonical offsets vector through each block's ``row_ids``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import DenseFeatures, Features, SparseFeatures
from photon_tpu.data.game_data import GameDataset

Array = jax.Array

# Row-count caps for entity size buckets: entities are padded up to the next
# cap, so worst-case padding waste is 2x within a bucket (SURVEY §7.3).
DEFAULT_BUCKET_CAPS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Per-coordinate random-effect data config.

    Reference: RandomEffectDataConfiguration in
    data/CoordinateDataConfiguration.scala:77 — REType, feature shard, active
    data bounds, features-to-samples ratio (Pearson filter).
    """

    random_effect_type: str
    feature_shard_id: str
    active_data_upper_bound: int | None = None
    active_data_lower_bound: int | None = None
    features_to_samples_ratio: float | None = None
    bucket_caps: tuple[int, ...] = DEFAULT_BUCKET_CAPS
    # Scoring-table ELL width bound (SURVEY §7.3 width hazard): rows with
    # more nnz spill into a COO tail instead of inflating every row's slab.
    score_table_width_cap: int | None = None


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBlocks:
    """One size bucket of entities, padded to common shapes.

    Training slab for a vmapped per-entity solver: leading axis B is the
    entity axis. Padding rows carry weight 0; padded subspace slots have
    ``proj == -1`` and never receive data gradient.
    """

    entity_codes: Array  # [B] int32 — global entity code per slot
    x_indices: Array  # [B, R, k] int32, subspace-remapped
    x_values: Array  # [B, R, k]
    labels: Array  # [B, R]
    offsets: Array  # [B, R] base offsets (residuals added per train call)
    weights: Array  # [B, R]; 0 for padding rows
    row_ids: Array  # [B, R] int32 canonical row ids; 0 for padding (weight 0)
    proj: Array  # [B, S] int32 original feature id per subspace slot; -1 pad
    penalty_mask: Array  # [B, S] 1.0 for penalized slots (valid, non-intercept)
    valid_mask: Array  # [B, S] 1.0 for valid subspace slots
    intercept_slots: Array  # [B] int32 subspace slot of intercept; -1 if none

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def sub_dim(self) -> int:
        return self.proj.shape[-1]


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """All device-resident state for one random-effect coordinate."""

    config: RandomEffectDataConfiguration
    num_entities: int
    entity_keys: tuple  # code -> raw entity key
    blocks: tuple[EntityBlocks, ...]  # active data, size-bucketed
    # Full-table scoring arrays (every canonical row, active AND passive):
    score_codes: Array  # [n] int32 owning-entity code per row
    score_indices: Array  # [n, k] int32 subspace-remapped; 0 where dropped
    score_values: Array  # [n, k]; 0 where the feature is outside the subspace
    max_sub_dim: int
    sub_dims: np.ndarray  # [E] host-side subspace dims
    proj_all: np.ndarray  # [E, max_sub_dim] original feature ids; -1 pad
    num_features: int  # original feature-space dim of the shard
    # COO overflow tail for rows wider than the configured score-table cap
    # (empty arrays when uncapped); tail rows are sorted ascending.
    score_tail_rows: Array | None = None  # [t] int32
    score_tail_indices: Array | None = None  # [t] int32 subspace slots
    score_tail_values: Array | None = None  # [t]

    def real_entity_mask(self, block: EntityBlocks) -> np.ndarray:
        """[B] bool — True for real entities. Mesh-sharded blocks pad the
        entity axis with inert entities whose code is ``num_entities``
        (parallel/mesh.py shard_random_effect_dataset); this helper owns
        that sentinel convention."""
        return np.asarray(block.entity_codes) < self.num_entities

    @property
    def num_active_entities(self) -> int:
        return sum(
            int(self.real_entity_mask(b).sum()) for b in self.blocks
        )


def _stable_type_seed(re_type: str) -> np.uint64:
    """Deterministic 64-bit seed from the REType name (the reference XORs
    ``REType.hashCode`` into the sample key, RandomEffectDataset.scala:510)."""
    import zlib

    return np.uint64(zlib.crc32(re_type.encode()) | (0x9E3779B9 << 32))


def _byteswap64_mix(uids: np.ndarray, seed: np.uint64) -> np.ndarray:
    """splitmix64-style deterministic hash of sample ids — the moral
    equivalent of the reference's ``byteswap64(hash ^ uid)`` reservoir keys:
    a fixed pseudo-random total order over samples, reproducible across
    re-ingests (SURVEY §5.2 determinism requirement)."""
    z = uids.astype(np.uint64) ^ seed
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _rows_to_coo(features: Features) -> tuple[np.ndarray, np.ndarray, int]:
    """Host-side (indices[n, k], values[n, k]) view of a feature shard."""
    if isinstance(features, SparseFeatures):
        return (
            np.asarray(features.indices),
            np.asarray(features.values),
            features.d,
        )
    assert isinstance(features, DenseFeatures)
    x = np.asarray(features.x)
    n, d = x.shape
    idx = np.broadcast_to(np.arange(d, dtype=np.int32), (n, d))
    return idx.copy(), x.copy(), d


def _remap_ell_rows(
    idx_rows: np.ndarray,  # [r, k_in] original feature ids
    val_rows: np.ndarray,  # [r, k_in]
    lut: np.ndarray,  # [num_features] original -> sub slot, -1 dropped
    k_out: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized subspace remap: gather slots, compact valid entries left."""
    sub = lut[idx_rows]  # [r, k_in]
    valid = (val_rows != 0.0) & (sub >= 0)
    order = np.argsort(~valid, axis=1, kind="stable")  # valid entries first
    sub_c = np.take_along_axis(np.where(valid, sub, 0), order, axis=1)
    val_c = np.take_along_axis(np.where(valid, val_rows, 0.0), order, axis=1)
    return sub_c[:, :k_out].astype(np.int32), val_c[:, :k_out]


def _pearson_select(
    values: np.ndarray,  # [r, k] ELL values for one entity's active rows
    indices: np.ndarray,  # [r, k]
    labels: np.ndarray,  # [r]
    active_features: np.ndarray,  # sorted original ids
    keep: int,
    intercept_index: int | None,
    num_features: int,
) -> np.ndarray:
    """Rank an entity's active features by |Pearson corr with the label| and
    keep the top ``keep`` (intercept always kept).

    Reference: LocalDataset.filterFeaturesByPearsonCorrelationScore
    (data/LocalDataset.scala:103, stableComputePearsonCorrelationScore :132):
    features with near-constant columns get score ~0 except the intercept,
    which is always retained.
    """
    if keep >= active_features.size:
        return active_features
    r = labels.shape[0]
    pos = np.full(num_features, -1, dtype=np.int64)
    pos[active_features] = np.arange(active_features.size)
    sub = pos[indices]
    valid = (values != 0.0) & (sub >= 0)
    rows = np.broadcast_to(np.arange(r)[:, None], indices.shape)
    cols = np.zeros((r, active_features.size), dtype=np.float64)
    cols[rows[valid], sub[valid]] = values[valid]
    y = labels.astype(np.float64)
    yc = y - y.mean()
    xc = cols - cols.mean(axis=0, keepdims=True)
    num = xc.T @ yc
    den = np.sqrt((xc * xc).sum(axis=0) * (yc * yc).sum()) + 1e-12
    score = np.abs(num / den)
    if intercept_index is not None and pos[intercept_index] >= 0:
        score[pos[intercept_index]] = np.inf  # always keep the intercept
    order = np.argsort(-score, kind="stable")[:keep]
    return np.sort(active_features[order])


def _build_score_table(
    codes: np.ndarray,  # [n] entity codes into projs; -1 = no entity
    ell_idx: np.ndarray,  # [n, k_in]
    ell_val: np.ndarray,  # [n, k_in]
    projs_of,  # callable e -> [s_e] sorted original feature ids
    num_entities: int,
    num_features: int,
    sort: tuple[np.ndarray, np.ndarray, np.ndarray] | None = None,
    width_cap: int | None = None,
):
    """Shared scoring-table remap: every row's ELL entries mapped into its
    owning entity's subspace (dropped features zeroed). Used by the dataset
    build (active+passive rows) and by ``remap_for_scoring`` (new data).
    ``sort`` optionally supplies a precomputed (order, starts, ends)
    entity grouping to skip the argsort.

    ``width_cap`` bounds the slab width (SURVEY §7.3 width hazard): the
    [n, cap] slab is the ONLY O(n)-wide allocation — entries beyond the cap
    stream into a COO tail per entity, so one dense row never inflates host
    (or device) memory for every row. Returns (si, sv, tail) where tail is
    None when uncapped, else (rows, indices, values) sorted by row."""
    n = codes.shape[0]
    k_all = max(int((ell_val != 0.0).sum(axis=1).max(initial=0)), 1)
    k_slab = k_all if width_cap is None else max(min(width_cap, k_all), 1)
    si = np.zeros((n, k_slab), dtype=np.int32)
    sv = np.zeros((n, k_slab), dtype=ell_val.dtype)
    tail_rows: list[np.ndarray] = []
    tail_idx: list[np.ndarray] = []
    tail_val: list[np.ndarray] = []
    if sort is not None:
        order, starts, ends = sort
    else:
        order = np.argsort(codes, kind="stable")
        sorted_codes = codes[order]
        starts = np.searchsorted(sorted_codes, np.arange(num_entities))
        ends = np.searchsorted(
            sorted_codes, np.arange(num_entities), side="right"
        )
    # A trained model's projectors may reference feature ids beyond this
    # dataset's shard dimension; size the LUT to cover both so unknown
    # features are dropped, not crashed on.
    lut_size = num_features
    for e in range(num_entities):
        p = projs_of(e)
        if p.size:
            lut_size = max(lut_size, int(p.max()) + 1)
    lut = np.full(lut_size, -1, dtype=np.int64)
    for e in range(num_entities):
        rows = order[starts[e] : ends[e]]
        if rows.size == 0:
            continue
        p = projs_of(e)
        lut[p] = np.arange(p.size)
        # Remap at this entity's own width; only the transient per-entity
        # buffer sees the full width.
        k_e = max(int((ell_val[rows] != 0.0).sum(axis=1).max(initial=0)), 1)
        ri, rv = _remap_ell_rows(ell_idx[rows], ell_val[rows], lut, k_e)
        if k_e <= k_slab:
            si[rows, :k_e] = ri
            sv[rows, :k_e] = rv
        else:
            si[rows] = ri[:, :k_slab]
            sv[rows] = rv[:, :k_slab]
            over_i, over_v = ri[:, k_slab:], rv[:, k_slab:]
            mask = over_v != 0.0
            if mask.any():
                row_of = np.broadcast_to(
                    rows[:, None].astype(np.int64), mask.shape)
                tail_rows.append(row_of[mask])
                tail_idx.append(over_i[mask].astype(np.int64))
                tail_val.append(over_v[mask])
        lut[p] = -1
    if width_cap is None:
        return si, sv, None
    if tail_rows:
        tr = np.concatenate(tail_rows)
        ti = np.concatenate(tail_idx)
        tv = np.concatenate(tail_val)
        o = np.argsort(tr, kind="stable")  # segment_sum wants sorted rows
        tail = (tr[o], ti[o], tv[o])
    else:
        tail = (
            np.empty(0, np.int64), np.empty(0, np.int64),
            np.empty(0, ell_val.dtype),
        )
    return si, sv, tail


def remap_for_scoring(
    game_data: GameDataset,
    *,
    re_type: str,
    feature_shard_id: str,
    entity_keys: tuple,
    proj_all: np.ndarray,  # [E, S] original feature ids; -1 pad
    dtype=None,
    width_cap: int | None = None,
) -> tuple[Array, Array, Array, tuple[Array, Array, Array] | None]:
    """Remap an arbitrary GameDataset's rows into trained entity subspaces.

    Returns (codes, indices, values, tail) consumable by
    ``score_entity_table_with_tail`` — the scoring path for validation /
    test data (RandomEffectModel.score :70 joins new data by REId; entities
    unseen at training time contribute score 0, matching the reference's
    left-join semantics where rows without a model get no score). ``tail``
    is None when ``width_cap`` is unset, else device (rows, indices, values)
    arrays for the capped table's COO overflow (the SURVEY §7.3 width
    bound, same convention as the training-side score table).
    """
    if dtype is None:
        dtype = game_data.labels.dtype
    tag = game_data.id_tags[re_type]
    vocab = {str(k): i for i, k in enumerate(entity_keys)}
    # this-dataset code -> trained code (-1 unseen)
    code_map = np.array(
        [vocab.get(str(k), -1) for k in tag.inverse], dtype=np.int64
    )
    if len(tag.inverse) and len(entity_keys) and (code_map < 0).all():
        import warnings

        warnings.warn(
            f"remap_for_scoring({re_type!r}): none of {len(tag.inverse)} "
            f"dataset entities match the {len(entity_keys)} model entities "
            "— every random-effect score will be 0",
            stacklevel=2,
        )
    codes = code_map[np.asarray(tag.codes)]

    ell_idx, ell_val, num_features = _rows_to_coo(
        game_data.feature_shards[feature_shard_id]
    )
    si, sv, tail = _build_score_table(
        codes,
        ell_idx,
        ell_val,
        lambda e: proj_all[e][proj_all[e] >= 0],
        len(entity_keys),
        num_features,
        width_cap=width_cap,
    )
    # Unseen entities: clamp the code and zero the values -> score 0.
    unseen = codes < 0
    sv[unseen] = 0.0
    codes_safe = np.maximum(codes, 0)
    tail_out = None
    if tail is not None:
        tr, ti, tv = tail
        # Invariant: the tail only holds rows of KNOWN entities — the
        # build's searchsorted grouping spans codes 0..E-1, so code -1
        # (unseen) rows never reach the per-entity remap loop.
        assert not unseen[tr].any()
        tail_out = (
            jnp.asarray(tr.astype(np.int32)),
            jnp.asarray(ti.astype(np.int32)),
            jnp.asarray(tv, dtype=dtype),
        )
    return (
        jnp.asarray(codes_safe.astype(np.int32)),
        jnp.asarray(si),
        jnp.asarray(sv, dtype=dtype),
        tail_out,
    )


def build_random_effect_dataset(
    game_data: GameDataset,
    config: RandomEffectDataConfiguration,
    *,
    intercept_index: int | None = None,
    extra_features: dict[int, np.ndarray] | None = None,
    dtype=None,
) -> RandomEffectDataset:
    """One-shot host-side ingest of a random-effect coordinate's data.

    ``extra_features`` maps entity code -> original feature ids that must be
    in the entity's subspace even if inactive in the data — the prior-model
    support used for warm-start/incremental training
    (RandomEffectDataset.scala:390-426 unions the existing model's features).
    """
    if dtype is None:
        dtype = game_data.labels.dtype
    tag = game_data.id_tags[config.random_effect_type]
    codes = np.asarray(tag.codes).astype(np.int64, copy=False)
    num_entities = tag.num_groups
    n = codes.shape[0]

    feats = game_data.feature_shards[config.feature_shard_id]
    ell_idx, ell_val, num_features = _rows_to_coo(feats)
    labels_np = np.asarray(game_data.labels)
    offsets_np = np.asarray(game_data.offsets)
    weights_np = np.asarray(game_data.weights)
    uids = (
        game_data.uids.astype(np.int64)
        if game_data.uids is not None
        else np.arange(n, dtype=np.int64)
    )

    # --- 1. deterministic reservoir cap: per entity keep the
    # active_data_upper_bound rows with smallest hash keys -----------------
    seed = _stable_type_seed(config.random_effect_type)
    order_keys = _byteswap64_mix(uids, seed)
    # Sort rows by (entity, hash key): each entity's rows become contiguous in
    # a deterministic pseudo-random order.
    perm = np.lexsort((order_keys, codes))
    sorted_codes = codes[perm]
    starts = np.searchsorted(sorted_codes, np.arange(num_entities))
    ends = np.searchsorted(sorted_codes, np.arange(num_entities), side="right")

    upper = config.active_data_upper_bound
    lower = config.active_data_lower_bound

    entity_rows: list[np.ndarray] = []
    active = np.zeros(num_entities, dtype=bool)
    for e in range(num_entities):
        rows = perm[starts[e] : ends[e]]
        if upper is not None and rows.size > upper:
            rows = rows[:upper]
        entity_rows.append(rows)
        # Lower-bound filter: too-small entities train no model (their rows
        # still score via the zero row of the coefficient matrix).
        active[e] = rows.size >= (lower or 1)

    # --- 2. per-entity subspace projectors --------------------------------
    # Vectorized: one global unique over (entity, feature) pairs replaces
    # the per-entity np.unique loop (generateLinearSubspaceProjectors'
    # foldByKey becomes a single sort).
    projs: list[np.ndarray] = [np.empty(0, dtype=np.int64)] * num_entities
    sub_dims = np.zeros(num_entities, dtype=np.int64)
    active_ids = np.nonzero(active)[0]
    if active_ids.size:
        kept_rows = np.concatenate([entity_rows[e] for e in active_ids])
        kept_codes = np.repeat(
            active_ids, [entity_rows[e].size for e in active_ids]
        )
        iv = ell_idx[kept_rows]
        present = ell_val[kept_rows] != 0.0
        pair_codes = np.broadcast_to(kept_codes[:, None], iv.shape)[present]
        pair_keys = (
            pair_codes.astype(np.int64) * num_features
            + iv[present].astype(np.int64)
        )
        uniq = np.unique(pair_keys)
        e_of = uniq // num_features
        f_of = uniq % num_features
        e_starts = np.searchsorted(e_of, np.arange(num_entities))
        e_ends = np.searchsorted(e_of, np.arange(num_entities), side="right")
        for e in active_ids:
            projs[e] = f_of[e_starts[e]:e_ends[e]]  # sorted by feature id

    ratio = config.features_to_samples_ratio
    for e in active_ids:
        act = projs[e]
        if ratio is not None:
            rows = entity_rows[e]
            keep = max(int(ratio * rows.size), 1)
            act = _pearson_select(
                ell_val[rows], ell_idx[rows], labels_np[rows], act, keep,
                intercept_index, num_features,
            )
        # Prior-model support is unioned AFTER the Pearson filter: features a
        # warm-start model depends on must stay in the subspace even when
        # inactive/filtered in the current data (RandomEffectDataset.scala:
        # 390-426 unions the existing model's features unconditionally).
        if extra_features and e in extra_features:
            act = np.union1d(act, np.asarray(extra_features[e], dtype=act.dtype))
        projs[e] = act
        sub_dims[e] = act.size

    max_sub_dim = int(sub_dims.max()) if num_entities else 1
    max_sub_dim = max(max_sub_dim, 1)
    proj_all = np.full((num_entities, max_sub_dim), -1, dtype=np.int64)
    for e in range(num_entities):
        proj_all[e, : sub_dims[e]] = projs[e]

    # --- 3. size-bucketed training blocks ---------------------------------
    caps = sorted(config.bucket_caps)
    active_ids = np.nonzero(active)[0]
    bucket_of: dict[int, list[int]] = {}
    for e in active_ids:
        r = entity_rows[e].size
        # Entities above the largest cap round up to the next power of two so
        # heavy-tailed size distributions share padded shapes (and jit
        # compiles of the solver) instead of one shape per distinct size.
        cap = next((c for c in caps if r <= c), 1 << (r - 1).bit_length())
        bucket_of.setdefault(cap, []).append(int(e))

    blocks = []
    for cap in sorted(bucket_of):
        members = bucket_of[cap]
        b = len(members)
        s = max(int(sub_dims[members].max()), 1)
        # Per-bucket ELL capacity: the widest row among members.
        k = 1
        for e in members:
            rows = entity_rows[e]
            k = max(k, int((ell_val[rows] != 0.0).sum(axis=1).max(initial=0)))
        bi = np.zeros((b, cap, k), dtype=np.int32)
        bv = np.zeros((b, cap, k), dtype=ell_val.dtype)
        bl = np.zeros((b, cap), dtype=labels_np.dtype)
        bo = np.zeros((b, cap), dtype=offsets_np.dtype)
        bw = np.zeros((b, cap), dtype=weights_np.dtype)
        brow = np.zeros((b, cap), dtype=np.int32)
        bproj = np.full((b, s), -1, dtype=np.int32)
        bint = np.full(b, -1, dtype=np.int32)
        remap = np.full(num_features, -1, dtype=np.int64)  # reused buffer
        for t, e in enumerate(members):
            rows = entity_rows[e]
            act = projs[e]
            remap[act] = np.arange(act.size)
            bproj[t, : act.size] = act
            if intercept_index is not None and remap[intercept_index] >= 0:
                bint[t] = remap[intercept_index]
            r = rows.size
            bi[t, :r], bv[t, :r] = _remap_ell_rows(
                ell_idx[rows], ell_val[rows], remap, k
            )
            bl[t, :r] = labels_np[rows]
            bo[t, :r] = offsets_np[rows]
            bw[t, :r] = weights_np[rows]
            brow[t, :r] = rows
            remap[act] = -1
        slot = np.arange(s)[None, :]
        valid = (slot < sub_dims[members][:, None]).astype(np.float32)
        penalty = valid.copy()
        has_int = bint >= 0
        penalty[has_int, bint[has_int]] = 0.0
        blocks.append(
            EntityBlocks(
                entity_codes=jnp.asarray(np.asarray(members, dtype=np.int32)),
                x_indices=jnp.asarray(bi),
                x_values=jnp.asarray(bv, dtype=dtype),
                labels=jnp.asarray(bl, dtype=dtype),
                offsets=jnp.asarray(bo, dtype=dtype),
                weights=jnp.asarray(bw, dtype=dtype),
                row_ids=jnp.asarray(brow),
                proj=jnp.asarray(bproj),
                penalty_mask=jnp.asarray(penalty, dtype=dtype),
                valid_mask=jnp.asarray(valid, dtype=dtype),
                intercept_slots=jnp.asarray(bint),
            )
        )

    # --- 4. full-table scoring arrays (active + passive rows) -------------
    si, sv, tail = _build_score_table(
        codes.astype(np.int64),
        ell_idx,
        ell_val,
        lambda e: projs[e],
        num_entities,
        num_features,
        sort=(perm, starts, ends),  # reuse the (entity, hash) lexsort
        width_cap=config.score_table_width_cap,
    )
    tail_r = tail_i = tail_v = None
    if tail is not None:
        tail_r = jnp.asarray(tail[0].astype(np.int32))
        tail_i = jnp.asarray(tail[1].astype(np.int32))
        tail_v = jnp.asarray(tail[2], dtype=dtype)

    return RandomEffectDataset(
        config=config,
        num_entities=num_entities,
        entity_keys=tag.inverse,
        blocks=tuple(blocks),
        score_codes=jnp.asarray(codes.astype(np.int32)),
        score_indices=jnp.asarray(si),
        score_values=jnp.asarray(sv, dtype=dtype),
        max_sub_dim=max_sub_dim,
        sub_dims=sub_dims,
        proj_all=proj_all,
        num_features=num_features,
        score_tail_rows=tail_r,
        score_tail_indices=tail_i,
        score_tail_values=tail_v,
    )
