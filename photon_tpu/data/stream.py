"""Fault-tolerant out-of-core streaming ingest (ROADMAP item 4).

The PR-3 ingest pipeline is fast but materializes the whole dataset in
host memory before planning; production datasets don't fit one host
(PAPER.md §0 — "hundreds of billions of coefficients" sharded per
entity). ``StreamingIngest`` iterates a directory of Avro shards in
bounded-memory WINDOWS: the record dicts of at most two windows exist
at any moment (the block-streaming decoder already bounds the
per-block peak), decode of window k+1 runs on the ingest chunk pool
while window k's device transfer drains asynchronously, and the final
``GameDataset`` assembles from per-window arrays — peak host memory is
the output columns plus O(window), never a whole-dataset record list.

A multi-hour streaming ingest is where production robustness is
decided, so the robustness layers are the headline:

- **Integrity manifest** (``ingest-manifest.json``, committed through
  ``io/model_io.atomic_write_bytes``): per-shard size + sha256 +
  record count. A truncated or bit-rotted shard raises
  ``CorruptShardError`` NAMING THE FILE — at read (size/checksum
  mismatch) or at decode (codec failure, record-count mismatch).
- **Bounded-loss quarantine** (``max_bad_shards`` /
  ``max_bad_fraction``, default 0 = abort): above zero, a corrupt
  shard is skipped, counted, and surfaced — ``ingested_fraction`` and
  the quarantined paths ride the stats dict, the
  ``stream_ingested_fraction`` / ``stream_quarantined_shards``
  registry gauges (→ ``/metrics`` health), and the bench JSON.
  Degraded-continue, never silent.
- **Transient-I/O retry**: shard read and decode are wrapped in
  ``resilience.retry`` behind the seeded ``io.shard_read`` /
  ``io.shard_decode`` fault points; ``errors.is_transient`` classifies
  EIO-style OSErrors, so a network-filesystem blip costs one backoff,
  not the run. A checksum mismatch after a CLEAN read is corruption,
  never retried.
- **Resumable cursor** (``ingest-cursor.json``): each window's arrays
  spill to an atomic npz and the cursor (manifest hash + config key +
  next shard + quarantine set) commits at the shard boundary. A killed
  ingest resumes where it stopped, reloading committed windows from
  their spills — a kill-and-resume ingest produces BYTE-IDENTICAL
  packed buffers to the uninterrupted run (pinned by
  tests/test_ingest_pipeline.py's diff harness).

Warm-start day-over-day retrain rides on top: ``GameEstimator.fit(
init_model=...)`` loads yesterday's GameModel via ``io/model_io``, and
the ``TrainingCheckpointer`` manifest records the ingest cursor + the
init-model digest (``set_run_meta``) so crash recovery resumes
ingest-then-descent end to end. Formats, knobs, and semantics: DATA.md.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import os
import threading
import time

import numpy as np

from photon_tpu.data.dataset import SparseFeatures
from photon_tpu.data.game_data import GameDataset, IdTag
from photon_tpu.data.index_map import IndexMap
from photon_tpu.io import avro
from photon_tpu.io.avro_data import (
    _DECODE_ERRORS,
    _uid_to_int,
    data_shard_files,
    resolve_input_columns,
)
from photon_tpu.resilience.errors import (
    CorruptShardError,
    ResumeMismatchError,
)
from photon_tpu.types import make_feature_key

logger = logging.getLogger(__name__)

MANIFEST_FILE = "ingest-manifest.json"
CURSOR_FILE = "ingest-cursor.json"
VOCAB_FILE = "ingest-vocab.json"
SKETCH_FILE = "ingest-sketch.json"
SCHEMA_VERSION = 1

# Program contract (audited by `python -m photon_tpu.analysis
# --semantic`; builder build_streaming_ingest in analysis/program.py):
# a GameDataset assembled from streamed windows must dispatch EXACTLY
# the fused materialize/fit programs the in-memory ingest path
# dispatches — zero added programs, byte-identical recompile keys
# (stable_under=streamed_ingest) and a callback-free hot loop. The
# streaming layer is host/IO machinery; it must never perturb what XLA
# compiles.
PROGRAM_AUDIT = dict(
    name="streaming-ingest",
    entry="data.stream.StreamingIngest.run -> fused materialize/fit "
    "(streamed windows vs in-memory ingest)",
    builder="build_streaming_ingest",
    max_programs=2,
    stable_under=("streamed_ingest",),
    hot_loop=True,
)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The window double-buffer: `_decode_window` runs on
# the ingest chunk pool (pure file-read + numpy decode — NO JAX: the
# per-window `jax.device_put` stays on the training thread, which is
# what makes the overlap a transfer/decode overlap rather than an
# off-thread dispatch hazard). `StreamStats._lock` guards the counters
# both the worker (decode seconds, rows) and the training thread
# (transfer seconds, quarantine set) write; everything else the worker
# touches is window-local. Exactly one decode future is in flight and
# it is ALWAYS consumed (including on the error drain).
CONCURRENCY_AUDIT = dict(
    name="streaming-ingest",
    locks={
        "StreamStats._lock": (
            "StreamStats._seconds",
            "StreamStats._counts",
            "StreamStats._quarantined",
        ),
    },
    thread_entries=("StreamingIngest._decode_window",),
    jax_dispatch_ok={},
)


# --------------------------------------------------------------------------
# integrity manifest
# --------------------------------------------------------------------------


def _hash_file(path: str) -> tuple[str, int]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
            size += len(block)
    return h.hexdigest(), size


def _count_records(path: str) -> int | None:
    """Record count from the container's block headers (no record
    decode). None when the file cannot even be block-scanned — such a
    shard is already corrupt and will quarantine at decode time."""
    try:
        return sum(
            count for _, count, _ in avro.iter_container_block_bytes(path)
        )
    except (OSError, *_DECODE_ERRORS):
        return None


def build_shard_manifest(
    stream_dir: str, shard_names: list[str] | None = None
) -> dict:
    """Scan ``stream_dir``'s Avro shards into the integrity manifest.

    Per shard: file name (relative), byte size, sha256, record count
    (from block headers — cheap), and the cumulative record offset
    (the stable global row position ``_uid_to_int`` falls back to for
    uid-less records, independent of quarantine decisions so resume
    and quarantine never shift downstream sampling keys).

    ``shard_names`` (base names) restricts the manifest to an explicit
    snapshot — how the pilot freezes a cycle's input set so shards
    landing MID-CYCLE wait for the next cycle instead of changing the
    manifest under a committed cursor.
    """
    wanted = None if shard_names is None else set(shard_names)
    shards = []
    offset = 0
    for path in data_shard_files(stream_dir):
        if wanted is not None and os.path.basename(path) not in wanted:
            continue
        digest, size = _hash_file(path)
        records = _count_records(path)
        shards.append({
            "name": os.path.basename(path),
            "size": size,
            "sha256": digest,
            "records": records,
            "row_offset": offset,
        })
        offset += records or 0
    if not shards:
        raise ValueError(f"no .avro shards under {stream_dir}")
    return {"schema_version": SCHEMA_VERSION, "shards": shards}


def _manifest_bytes(manifest: dict) -> bytes:
    return json.dumps(manifest, indent=2, sort_keys=True).encode("utf-8")


def _atomic_json(path: str, payload: dict) -> None:
    from photon_tpu.io.model_io import atomic_write_bytes

    atomic_write_bytes(path, _manifest_bytes(payload))


# --------------------------------------------------------------------------
# quarantine policy + stats
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """Bounded-loss corrupt-shard policy.

    The budget is ``max(max_bad_shards, floor(max_bad_fraction *
    total_shards))``; the default (both 0) aborts on the FIRST corrupt
    shard — losing data silently is worse than failing loudly, so
    degraded-continue is an explicit opt-in with a bound.
    """

    max_bad_shards: int = 0
    max_bad_fraction: float = 0.0

    def __post_init__(self):
        if self.max_bad_shards < 0:
            raise ValueError("max_bad_shards must be >= 0")
        if not (0.0 <= self.max_bad_fraction <= 1.0):
            raise ValueError("max_bad_fraction must be in [0, 1]")

    def budget(self, total_shards: int) -> int:
        return max(
            int(self.max_bad_shards),
            int(self.max_bad_fraction * total_shards),
        )


class StreamStats:
    """Thread-safe ingest accounting (decode worker + training thread)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seconds: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._quarantined: dict[str, str] = {}  # path -> reason

    def add_seconds(self, name: str, seconds: float) -> None:
        with self._lock:
            self._seconds[name] = self._seconds.get(name, 0.0) + seconds

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def quarantine(self, path: str, reason: str) -> None:
        with self._lock:
            self._quarantined[path] = reason

    def quarantined(self) -> dict[str, str]:
        with self._lock:
            return dict(self._quarantined)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seconds": dict(self._seconds),
                "counts": dict(self._counts),
                "quarantined": dict(self._quarantined),
            }


# --------------------------------------------------------------------------
# decoded window
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _Window:
    """One decoded window's arrays (host numpy, window-local widths)."""

    index: int
    rows: int
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    uids: np.ndarray
    tags: dict[str, np.ndarray]
    shards: dict[str, tuple[np.ndarray, np.ndarray]]  # (idx, val)
    quarantined: list[tuple[str, CorruptShardError]]
    # Device handles of the (async) window transfer, set by
    # _transfer_window on the training thread; None until then (or for
    # an all-quarantined empty window).
    devs: object = None


def _pack_rows(
    rows: list, num_features: int, dtype
) -> tuple[np.ndarray, np.ndarray]:
    """ELL-pack one window's rows at the WINDOW width (the final pad to
    the global width happens at assembly, exactly like the in-memory
    ``_EllBuilder``), with the same out-of-range guard."""
    k = max(max((len(r) for r in rows), default=0), 1)
    idx = np.zeros((len(rows), k), dtype=np.int32)
    val = np.zeros((len(rows), k), dtype=dtype)
    for i, row in enumerate(rows):
        for j, (fi, fv) in enumerate(row):
            idx[i, j] = fi
            val[i, j] = fv
    if idx.size and (
        int(idx.max()) >= num_features or int(idx.min()) < 0
    ):
        raise ValueError(
            f"feature index out of range [0, {num_features}): "
            f"min {int(idx.min())}, max {int(idx.max())}")
    return idx, val


# --------------------------------------------------------------------------
# the streaming ingest
# --------------------------------------------------------------------------


class StreamingIngest:
    """Stream a directory of TrainingExampleAvro shards into a
    ``GameDataset`` with bounded memory, integrity checking, bounded-
    loss quarantine, transient-I/O retry, and a resumable cursor.

    ``work_dir`` holds the run's durable state: the integrity manifest,
    the vocabulary artifact (when maps are data-derived), per-window
    spill files, and the cursor. ``resume=True`` continues a killed
    ingest from its committed cursor (manifest hash + ingest config
    must match — ``ResumeMismatchError`` otherwise) and reloads
    completed windows from their spills, so the resumed dataset is
    byte-identical to the uninterrupted one.
    """

    def __init__(
        self,
        stream_dir: str,
        *,
        work_dir: str,
        feature_shards: dict[str, list[str]] | None = None,
        index_maps: dict[str, IndexMap] | None = None,
        id_tag_names=None,  # list[str] | None ("auto") | "auto"
        id_columns: list[str] | None = None,
        response_field: str | None = None,
        input_columns: dict[str, str] | None = None,
        add_intercept: bool | dict[str, bool] = True,
        dtype="float32",
        window_shards: int = 1,
        quarantine: QuarantinePolicy | None = None,
        resume: bool = False,
        shard_names: list[str] | None = None,
    ):
        if window_shards < 1:
            raise ValueError("window_shards must be >= 1")
        self.stream_dir = stream_dir
        self.work_dir = work_dir
        # Explicit shard snapshot (base names): the manifest — and
        # therefore the cursor and every downstream row offset — covers
        # exactly these files, whatever lands in stream_dir later. A
        # resumed run keeps the COMMITTED manifest's snapshot.
        self.shard_names = (
            None if shard_names is None else [str(s) for s in shard_names]
        )
        self.feature_shards = dict(
            feature_shards or {"features": ["features"]}
        )
        self.index_maps = dict(index_maps) if index_maps else None
        self.id_tag_names = (
            "auto" if id_tag_names is None else id_tag_names
        )
        self.id_columns = list(id_columns or ())
        self.response_field = response_field
        self.cols = resolve_input_columns(input_columns)
        if self.response_field is None:
            self.response_field = self.cols["response"]
        self.add_intercept = add_intercept
        self.np_dtype = np.dtype(dtype)
        self.window_shards = int(window_shards)
        self.quarantine = quarantine or QuarantinePolicy()
        self.resume = bool(resume)
        self.stats = StreamStats()
        overlap = set(self.id_columns) & set(
            self.id_tag_names if self.id_tag_names != "auto" else ()
        )
        if overlap:
            raise ValueError(
                f"id name(s) {sorted(overlap)} listed in both id_columns "
                "and id_tag_names; each id tag must come from exactly "
                "one source")
        # Frozen at construction, BEFORE the vocab scan resolves
        # "auto"/probed fields in place — the cursor and vocab artifact
        # are pinned to the configuration as the CALLER stated it, so a
        # resumed run (which re-resolves from the committed artifact)
        # computes the same key.
        self._frozen_config_key = self._config_key()

    # -- config identity ---------------------------------------------------

    def _shard_intercept(self, shard: str) -> bool:
        if isinstance(self.add_intercept, dict):
            return self.add_intercept.get(shard, True)
        return bool(self.add_intercept)

    @staticmethod
    def _map_digest(m) -> str:
        """Content identity of a prebuilt index map: every (index, key)
        pair, in index order. A regenerated vocabulary of the SAME size
        but different key->index assignment must fail the resume config
        check — size alone would silently mix feature mappings across
        the resume boundary."""
        h = hashlib.sha1()
        for i in range(len(m)):
            h.update(f"{i}\t{m.get_feature_name(i)}\n".encode())
        return h.hexdigest()

    def _config_key(self) -> str:
        """Identity of everything a resumed ingest must share with the
        run that wrote the cursor — a changed window size, shard
        layout, or vocabulary would silently produce different packed
        buffers than the run being resumed."""
        maps = self.index_maps or {}
        parts = [
            repr(sorted(
                (s, tuple(bags)) for s, bags in self.feature_shards.items()
            )),
            repr(self.id_tag_names),
            repr(sorted(self.id_columns)),
            repr(self.response_field),
            repr(sorted(self.cols.items())),
            repr(sorted(
                (s, self._shard_intercept(s)) for s in self.feature_shards
            )),
            repr(str(self.np_dtype)),
            repr(self.window_shards),
            repr(sorted(
                (s, self._map_digest(m)) for s, m in maps.items()
            )),
        ]
        return hashlib.sha1("\n".join(parts).encode()).hexdigest()

    # -- manifest ----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.work_dir, MANIFEST_FILE)

    def _ensure_manifest(self) -> tuple[dict, str]:
        """Load (resume) or build+commit the integrity manifest; returns
        (manifest, sha256-of-committed-bytes) — the hash every cursor
        and vocab artifact is pinned to."""
        os.makedirs(self.work_dir, exist_ok=True)
        path = self._manifest_path()
        producer = os.path.join(self.stream_dir, MANIFEST_FILE)
        if self.resume:
            if not os.path.exists(path):
                raise ResumeMismatchError(
                    f"--resume-ingest: no committed manifest at {path}; "
                    "nothing to resume — run a fresh ingest")
            with open(path, "rb") as f:
                raw = f.read()
            return json.loads(raw.decode()), hashlib.sha256(raw).hexdigest()
        if os.path.exists(producer):
            # A producer-committed manifest travels WITH the data: trust
            # it (the point is detecting rot after it was written).
            with open(producer, "rb") as f:
                raw = f.read()
            manifest = json.loads(raw.decode())
            if self.shard_names is not None:
                wanted = set(self.shard_names)
                manifest = dict(
                    manifest,
                    shards=[
                        s for s in manifest["shards"]
                        if s["name"] in wanted
                    ],
                )
                raw = _manifest_bytes(manifest)
        else:
            manifest = build_shard_manifest(
                self.stream_dir, self.shard_names
            )
            raw = _manifest_bytes(manifest)
        from photon_tpu.io.model_io import atomic_write_bytes

        atomic_write_bytes(path, raw)
        return manifest, hashlib.sha256(raw).hexdigest()

    # -- shard read / decode (the retried, fault-injected boundary) --------

    def _shard_path(self, info: dict) -> str:
        return os.path.join(self.stream_dir, info["name"])

    def _read_verify(self, info: dict) -> bytes:
        """Read the shard's bytes ONCE and verify size+sha256 against
        the manifest; returns the verified buffer so the decode pass
        never re-reads the disk (and there is no TOCTOU window between
        checksum and decode). Transient read faults (EIO-style, or the
        injected ``io.shard_read`` kind) are retried by the wrapper; an
        intact read with the wrong bytes is corruption — typed, never
        retried.
        """
        from photon_tpu.resilience import retry

        path = self._shard_path(info)

        def once() -> bytes:
            with open(path, "rb") as f:
                data = f.read()
            digest = hashlib.sha256(data).hexdigest()
            if len(data) != info["size"] or digest != info["sha256"]:
                raise CorruptShardError(
                    f"shard {path}: size/checksum mismatch vs ingest "
                    f"manifest (size {len(data)} vs {info['size']}, "
                    f"sha256 {digest[:12]}... vs "
                    f"{info['sha256'][:12]}...) — the shard was "
                    "truncated or modified after the manifest was "
                    "committed")
            return data

        return retry.retrying_check(
            "io.shard_read", once, site="stream.shard_read"
        )

    def _iter_shard(self, info: dict, data: bytes):
        """Typed-error record stream over one shard's verified bytes."""
        path = self._shard_path(info)
        try:
            yield from avro.iter_container_bytes(data, name=path)
        except _DECODE_ERRORS as exc:
            raise CorruptShardError(
                f"shard {path}: Avro decode failed "
                f"({type(exc).__name__}: {exc}) — the shard is "
                "truncated or not a valid container") from exc

    def _decode_shard(
        self, info: dict, maps: dict[str, IndexMap], data: bytes
    ):
        """Decode one verified shard into column lists + ELL rows.

        Runs INSIDE the retry wrapper: a transient decode fault redoes
        the whole shard into fresh lists (no partial double-append). A
        record count disagreeing with the manifest is corruption.
        """
        from photon_tpu.resilience import retry

        path = self._shard_path(info)
        tag_names = self._tag_names()

        def once():
            labels: list = []
            offsets: list = []
            weights: list = []
            uids: list = []
            tags: dict[str, list] = {t: [] for t in tag_names}
            rows: dict[str, list] = {s: [] for s in self.feature_shards}
            base = int(info.get("row_offset") or 0)
            n = 0
            for i, rec in enumerate(self._iter_shard(info, data)):
                n += 1
                if self.response_field not in rec:
                    # Typed like the id-tag cases below: schema drift in
                    # ONE shard must name the file and stay eligible for
                    # the quarantine policy, not abort the run with a
                    # bare KeyError from a pool thread.
                    raise CorruptShardError(
                        f"shard {path}: record {i} is missing response "
                        f"field {self.response_field!r}")
                labels.append(rec[self.response_field])
                off = rec.get(self.cols["offset"])
                offsets.append(off if off is not None else 0.0)
                wt = rec.get(self.cols["weight"])
                weights.append(wt if wt is not None else 1.0)
                uids.append(_uid_to_int(rec.get(self.cols["uid"]), base + i))
                for shard, bags in self.feature_shards.items():
                    imap = maps[shard]
                    row = []
                    for bag in bags:
                        for f in rec.get(bag) or ():
                            idx = imap.get_index(
                                make_feature_key(f["name"], f["term"]))
                            if idx is not None and f["value"] != 0.0:
                                row.append((idx, float(f["value"])))
                    if imap.intercept_index is not None:
                        row.append((imap.intercept_index, 1.0))
                    rows[shard].append(row)
                meta = rec.get(self.cols["metadataMap"]) or {}
                for col in self.id_columns:
                    if col not in rec or rec[col] is None:
                        raise CorruptShardError(
                            f"shard {path}: record {i} is missing id "
                            f"column {col!r}")
                    tags[col].append(rec[col])
                for t in tag_names:
                    if t in self.id_columns:
                        continue
                    if t not in meta:
                        raise CorruptShardError(
                            f"shard {path}: record {i} is missing id "
                            f"tag {t!r} in metadataMap")
                    tags[t].append(meta[t])
            if info.get("records") is not None and n != info["records"]:
                raise CorruptShardError(
                    f"shard {path}: decoded {n} record(s) but the "
                    f"ingest manifest records {info['records']} — the "
                    "container lost blocks after the manifest was "
                    "committed")
            return labels, offsets, weights, uids, tags, rows

        return retry.retrying_check(
            "io.shard_decode", once, site="stream.shard_decode"
        )

    # -- the window decode thunk (chunk-pool thread entry) -----------------

    def _decode_window(
        self,
        widx: int,
        infos: list[dict],
        maps: dict[str, IndexMap],
        known_bad: frozenset,
    ) -> _Window:
        """Decode one window of shards into numpy arrays. Pure
        file-read + numpy — NO JAX (the device transfer stays on the
        training thread). Corrupt shards are recorded, not raised: the
        training thread applies the quarantine budget so the decision
        is made in deterministic window order."""
        t0 = time.perf_counter()
        labels: list = []
        offsets: list = []
        weights: list = []
        uids: list = []
        tag_names = self._tag_names()
        tags: dict[str, list] = {t: [] for t in tag_names}
        rows: dict[str, list] = {s: [] for s in self.feature_shards}
        quarantined: list[tuple[str, CorruptShardError]] = []
        for info in infos:
            path = self._shard_path(info)
            if path in known_bad:
                continue
            try:
                data = self._read_verify(info)
                ls, os_, ws, us, tg, rw = self._decode_shard(
                    info, maps, data
                )
            except CorruptShardError as exc:
                quarantined.append((path, exc))
                continue
            labels.extend(ls)
            offsets.extend(os_)
            weights.extend(ws)
            uids.extend(us)
            for t in tag_names:
                tags[t].extend(tg[t])
            for s in self.feature_shards:
                rows[s].extend(rw[s])
            self.stats.count("shards_decoded")
        n = len(labels)
        window = _Window(
            index=widx,
            rows=n,
            # float64 accumulation then one cast — the same chunk
            # semantics as the in-memory reader, so streamed values are
            # bit-identical to read_merged's.
            labels=np.asarray(labels, np.float64).astype(self.np_dtype),
            offsets=np.asarray(offsets, np.float64).astype(self.np_dtype),
            weights=np.asarray(weights, np.float64).astype(self.np_dtype),
            uids=np.asarray(uids, dtype=np.int64),
            tags={t: np.asarray(v) for t, v in tags.items()},
            shards={
                s: _pack_rows(rows[s], len(maps[s]), self.np_dtype)
                for s in self.feature_shards
            },
            quarantined=quarantined,
        )
        self.stats.add_seconds("decode", time.perf_counter() - t0)
        self.stats.count("rows_decoded", n)
        return window

    def _tag_names(self) -> list[str]:
        names = list(self.id_columns)
        tag_src = self.id_tag_names if self.id_tag_names != "auto" else ()
        for t in tag_src:
            if t not in names:
                names.append(t)
        return names

    # -- vocabulary scan ---------------------------------------------------

    def _vocab_path(self) -> str:
        return os.path.join(self.work_dir, VOCAB_FILE)

    def _resolve_vocab(
        self, manifest: dict, manifest_sha: str, budget: int
    ) -> dict[str, IndexMap]:
        """Prebuilt maps pass through; otherwise one streamed scan pass
        builds the missing vocabularies / discovers metadata tag names
        / probes the response field, with the same retry + quarantine
        semantics as the build pass, and commits the result so a
        resumed ingest reuses the identical vocabulary."""
        missing = [
            s for s in self.feature_shards
            if self.index_maps is None or s not in self.index_maps
        ]
        need_scan = bool(missing) or self.id_tag_names == "auto"
        out: dict[str, IndexMap] = dict(self.index_maps or {})

        vocab_path = self._vocab_path()
        # The committed vocabulary is reused ONLY on resume: a fresh run
        # must re-scan (and re-verify) every shard — an operator who
        # repaired a previously quarantined shard gets its rows back
        # instead of the artifact's stale quarantine set silently
        # excluding a now-healthy file.
        if need_scan and self.resume and os.path.exists(vocab_path):
            with open(vocab_path) as f:
                art = json.load(f)
            if (
                art.get("manifest_sha256") == manifest_sha
                and art.get("config_key") == self._frozen_config_key
            ):
                for s, fwd in art["maps"].items():
                    out[s] = IndexMap({k: int(v) for k, v in fwd.items()})
                self.id_tag_names = list(art["id_tag_names"])
                self.response_field = art["response_field"]
                for path, reason in art.get("quarantined", {}).items():
                    self.stats.quarantine(path, reason)
                restored = self.stats.quarantined()
                if len(restored) > budget:
                    # The artifact was committed under a LOOSER policy;
                    # this run's budget refuses the recorded loss.
                    raise CorruptShardError(
                        f"{len(restored)} shard(s) were quarantined by "
                        "the run that committed this vocabulary "
                        f"({sorted(restored)}) but the current policy "
                        f"allows {budget}; raise max_bad_shards/"
                        "max_bad_fraction or repair the shards")
                return out
            raise ResumeMismatchError(
                f"--resume-ingest: the committed vocabulary at "
                f"{vocab_path} was built from a different manifest "
                "or ingest configuration; run a fresh ingest")

        if need_scan:
            keysets: dict[str, set] = {s: set() for s in missing}
            meta_keys: set[str] = set()
            first = None
            t0 = time.perf_counter()
            for info in manifest["shards"]:
                path = self._shard_path(info)
                try:
                    data = self._read_verify(info)
                    got_first = self._scan_shard(
                        info, data, keysets, meta_keys, first is None
                    )
                except CorruptShardError as exc:
                    self.stats.quarantine(path, str(exc))
                    if len(self.stats.quarantined()) > budget:
                        raise
                    logger.warning(
                        "streaming ingest: quarantined %s at scan (%s)",
                        path, exc)
                    continue
                if first is None:
                    first = got_first
            self.stats.add_seconds("scan", time.perf_counter() - t0)
            if first is None:
                raise ValueError(
                    f"no decodable records under {self.stream_dir}")
            if self.response_field is None:
                for candidate in ("response", "label"):
                    if candidate in first:
                        self.response_field = candidate
                        break
                else:
                    raise ValueError(
                        "records carry neither 'response' nor 'label'; "
                        "pass response_field explicitly")
            if self.id_tag_names == "auto":
                self.id_tag_names = sorted(meta_keys)
            for s in missing:
                out[s] = IndexMap.from_feature_names(
                    keysets.pop(s),
                    add_intercept=self._shard_intercept(s),
                )
            _atomic_json(vocab_path, {
                "schema_version": SCHEMA_VERSION,
                "manifest_sha256": manifest_sha,
                "config_key": self._frozen_config_key,
                "maps": {
                    s: dict(out[s].items())
                    for s in sorted(self.feature_shards)
                },
                "id_tag_names": list(self.id_tag_names),
                "response_field": self.response_field,
                "quarantined": self.stats.quarantined(),
            })
        elif self.response_field is None:
            self.response_field = self._probe_response(manifest)
        return out

    def _scan_shard(
        self, info: dict, data: bytes, keysets: dict, meta_keys: set,
        want_first: bool,
    ):
        """One shard's scan pass (inside the retry wrapper)."""
        from photon_tpu.resilience import retry

        def once():
            first = None
            for rec in self._iter_shard(info, data):
                if want_first and first is None:
                    first = rec
                for s, ks in keysets.items():
                    for bag in self.feature_shards[s]:
                        for f in rec.get(bag) or ():
                            ks.add(make_feature_key(f["name"], f["term"]))
                if self.id_tag_names == "auto":
                    meta_keys.update(
                        (rec.get(self.cols["metadataMap"]) or {}).keys()
                    )
            return first

        return retry.retrying_check(
            "io.shard_decode", once, site="stream.shard_scan"
        )

    def _probe_response(self, manifest: dict) -> str:
        for info in manifest["shards"]:
            try:
                first = next(
                    iter(avro.iter_container(self._shard_path(info)))
                )
            except (*_DECODE_ERRORS, OSError, StopIteration):
                continue
            for candidate in ("response", "label"):
                if candidate in first:
                    return candidate
            break
        raise ValueError(
            "records carry neither 'response' nor 'label'; pass "
            "response_field explicitly")

    # -- cursor + spills ---------------------------------------------------

    def _cursor_path(self) -> str:
        return os.path.join(self.work_dir, CURSOR_FILE)

    def _sketch_path(self) -> str:
        return os.path.join(self.work_dir, SKETCH_FILE)

    def _spill_path(self, widx: int) -> str:
        return os.path.join(self.work_dir, f"window-{widx:05d}.npz")

    def _commit_cursor(
        self, manifest_sha: str, next_shard: int, windows: int, rows: int
    ) -> None:
        _atomic_json(self._cursor_path(), {
            "schema_version": SCHEMA_VERSION,
            "manifest_sha256": manifest_sha,
            "config_key": self._frozen_config_key,
            "next_shard": int(next_shard),
            "windows_committed": int(windows),
            "rows_ingested": int(rows),
            "window_shards": self.window_shards,
            "quarantined": self.stats.quarantined(),
        })

    def _load_cursor(self, manifest_sha: str) -> dict | None:
        path = self._cursor_path()
        if not os.path.exists(path):
            return None
        with open(path) as f:
            cursor = json.load(f)
        if cursor.get("schema_version") != SCHEMA_VERSION:
            raise ResumeMismatchError(
                f"ingest cursor {path}: schema_version "
                f"{cursor.get('schema_version')!r} is not the supported "
                f"{SCHEMA_VERSION}")
        if cursor.get("manifest_sha256") != manifest_sha:
            raise ResumeMismatchError(
                f"ingest cursor {path} was committed against a different "
                "shard manifest — the stream directory changed since the "
                "interrupted run; run a fresh ingest")
        if cursor.get("config_key") != self._frozen_config_key:
            raise ResumeMismatchError(
                f"ingest cursor {path} was committed under a different "
                "ingest configuration (shards/tags/window/vocabulary "
                "changed); run a fresh ingest")
        return cursor

    def _spill_window(self, window: _Window) -> None:
        """Atomically spill one window's arrays so a resumed ingest
        reloads them instead of re-reading + re-decoding the shards."""
        from photon_tpu.io.model_io import atomic_write_bytes

        arrays: dict[str, np.ndarray] = {
            "labels": window.labels,
            "offsets": window.offsets,
            "weights": window.weights,
            "uids": window.uids,
        }
        for t, v in window.tags.items():
            arrays[f"tag/{t}"] = v
        for s, (idx, val) in window.shards.items():
            arrays[f"shard/{s}/idx"] = idx
            arrays[f"shard/{s}/val"] = val
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        atomic_write_bytes(self._spill_path(window.index), buf.getbuffer())

    def _load_spill(self, widx: int) -> _Window:
        path = self._spill_path(widx)
        try:
            with np.load(path) as z:
                tags = {}
                shards = {}
                for key in z.files:
                    if key.startswith("tag/"):
                        tags[key[4:]] = z[key]
                    elif key.startswith("shard/") and key.endswith("/idx"):
                        s = key[len("shard/"):-len("/idx")]
                        shards[s] = (z[key], z[f"shard/{s}/val"])
                return _Window(
                    index=widx,
                    rows=int(z["labels"].shape[0]),
                    labels=z["labels"],
                    offsets=z["offsets"],
                    weights=z["weights"],
                    uids=z["uids"],
                    tags=tags,
                    shards=shards,
                    quarantined=[],
                )
        except (OSError, ValueError, KeyError, EOFError) as exc:
            raise ResumeMismatchError(
                f"ingest spill {path} is missing or unreadable ({exc}); "
                "the work dir was pruned mid-chain — run a fresh ingest"
            ) from exc

    # -- the run -----------------------------------------------------------

    def run(self) -> tuple[GameDataset, dict]:
        """Stream-ingest the directory; returns (dataset, stats)."""
        from photon_tpu.data.pipeline import PIPELINE_STATS, chunk_executor

        t_run = time.perf_counter()
        manifest, manifest_sha = self._ensure_manifest()
        shards = manifest["shards"]
        budget = self.quarantine.budget(len(shards))
        maps = self._resolve_vocab(manifest, manifest_sha, budget)
        # The resolved (possibly data-scanned) vocabularies — the CLI
        # reads these after run() for validation ingest + model saving.
        self.resolved_maps = dict(maps)
        self.manifest_sha256 = manifest_sha

        # Model/data-health sketching (obs/health.py; OFF by default):
        # when the health layer is armed, every ingested window folds
        # into one bounded-memory DataSketch — per-column
        # moment/quantile/missing sketches plus per-shard value/nnz
        # histograms and per-feature moments — persisted beside the
        # cursor (SKETCH_FILE) at every cursor commit. Pure host numpy
        # on the training thread: the audited `streaming-ingest` and
        # `health` contracts both pin zero traced-program impact.
        # Resumed windows re-fold from their spills in window order, so
        # a kill-and-resume ingest reproduces the byte-identical sketch
        # (pinned by tests/test_health.py).
        from photon_tpu.obs import health as _health

        sketch = _health.DataSketch() if _health.enabled() else None
        widths = {s: len(maps[s]) for s in self.feature_shards}
        self.health_sketch = sketch

        cursor = self._load_cursor(manifest_sha) if self.resume else None
        start_window = 0
        rows_ingested = 0
        resumed_from = None
        windows: list[_Window] = []
        if cursor is not None:
            start_window = int(cursor["windows_committed"])
            rows_ingested = int(cursor["rows_ingested"])
            resumed_from = int(cursor["next_shard"])
            for path, reason in cursor.get("quarantined", {}).items():
                self.stats.quarantine(path, reason)
            restored = self.stats.quarantined()
            if len(restored) > budget:
                # The cursor was committed under a LOOSER policy; this
                # run's budget refuses the recorded loss — including
                # the already-complete case where no window would ever
                # re-check it.
                raise CorruptShardError(
                    f"{len(restored)} shard(s) were quarantined by the "
                    f"run that committed this cursor "
                    f"({sorted(restored)}) but the current policy "
                    f"allows {budget}; raise max_bad_shards/"
                    "max_bad_fraction or repair the shards and run a "
                    "fresh ingest")
            for w in range(start_window):
                window = self._load_spill(w)
                self._transfer_window(window, PIPELINE_STATS)
                if sketch is not None:
                    sketch.update_window(
                        window.labels, window.offsets, window.weights,
                        window.shards, widths,
                    )
                windows.append(window)
            logger.info(
                "streaming ingest: resumed at shard %d/%d (%d window "
                "spill(s) reloaded, %d rows)", resumed_from, len(shards),
                start_window, rows_ingested)

        # Window plan: consecutive groups over the FULL manifest order
        # (already-quarantined shards are skipped inside the decode, so
        # the window -> shard mapping is identical across resumes).
        specs = [
            (w, shards[lo:lo + self.window_shards])
            for w, lo in enumerate(
                range(0, len(shards), self.window_shards)
            )
        ]
        known_bad = frozenset(self.stats.quarantined())
        pending: tuple[int, object] | None = None
        todo = specs[start_window:]
        if todo:
            widx, infos = todo[0]
            pending = (0, chunk_executor.submit(
                self._decode_window, widx, infos, maps, known_bad
            ))
        while pending is not None:
            i, fut = pending
            # Double buffer: window i+1 starts decoding on the chunk
            # pool BEFORE window i's result is consumed, so its decode
            # overlaps window i's (async) device transfer + spill.
            pending = None
            if i + 1 < len(todo):
                widx, infos = todo[i + 1]
                pending = (i + 1, chunk_executor.submit(
                    self._decode_window, widx, infos, maps, known_bad
                ))
            try:
                window = fut.result()
            except BaseException:
                self._drain(pending)
                raise
            for path, exc in window.quarantined:
                self.stats.quarantine(path, str(exc))
                logger.warning(
                    "streaming ingest: quarantined %s (%s)", path, exc)
            if len(self.stats.quarantined()) > budget:
                self._drain(pending)
                if window.quarantined:
                    raise window.quarantined[-1][1]
                raise CorruptShardError(  # pragma: no cover — the
                    # cursor-restore check above already refuses an
                    # inherited over-budget set; kept so a future
                    # accounting change can never turn this into an
                    # IndexError.
                    f"quarantined shards exceed the policy budget "
                    f"({budget}): {sorted(self.stats.quarantined())}")
            self._transfer_window(window, PIPELINE_STATS)
            self._spill_window(window)
            if sketch is not None:
                sketch.update_window(
                    window.labels, window.offsets, window.weights,
                    window.shards, widths,
                )
            windows.append(window)
            rows_ingested += window.rows
            next_shard = min(
                (todo[i][0] + 1) * self.window_shards, len(shards)
            )
            self._commit_cursor(
                manifest_sha, next_shard, todo[i][0] + 1, rows_ingested
            )
            if sketch is not None:
                # Beside the cursor, committed at the same shard
                # boundary — a resumed run that reloads k windows and
                # re-folds them lands on this exact file again.
                sketch.save(self._sketch_path())

        data = self._assemble(windows, maps, PIPELINE_STATS)
        stats = self._final_stats(
            manifest, rows_ingested, resumed_from,
            time.perf_counter() - t_run,
        )
        if sketch is not None:
            sketch.save(self._sketch_path())
            _health.set_train_sketch(sketch)
            stats["health_sketch_path"] = self._sketch_path()
        return data, stats

    def _drain(self, pending) -> None:
        """Consume an in-flight decode future on the error path (its
        outcome is discarded by design; a dropped future would hide a
        second failure)."""
        if pending is None:
            return
        try:
            pending[1].result()
        except Exception as exc:  # noqa: BLE001 — the primary error wins
            logger.warning(
                "streaming ingest: in-flight window decode also failed "
                "while aborting: %r", exc)

    # -- device transfer + assembly ----------------------------------------

    def _transfer_window(self, window: _Window, pstats) -> None:
        """Enqueue the window's arrays to the device ASYNCHRONOUSLY —
        ``jax.device_put`` returns at enqueue, so the transfer drains
        while the next window decodes on the chunk pool (the
        double-buffer contract). The handles ride on the window for
        final assembly."""
        import jax

        if window.rows == 0:
            window.devs = None
            return
        arrays = [window.labels, window.offsets, window.weights]
        for s in sorted(window.shards):
            idx, val = window.shards[s]
            arrays.extend((idx, val))
        t0 = time.perf_counter()
        with pstats.stage("stream_transfer"):
            window.devs = jax.device_put(arrays)
        self.stats.add_seconds("transfer", time.perf_counter() - t0)

    def _assemble(
        self, windows: list[_Window], maps: dict[str, IndexMap], pstats
    ) -> GameDataset:
        """Concatenate per-window arrays into the final GameDataset:
        host mirrors from the numpy chunks (byte-identical to the
        in-memory ``_EllBuilder`` layout), device columns from the
        already-transferred window buffers (pad to the global ELL
        width, one concatenate per column)."""
        import jax.numpy as jnp

        live = [w for w in windows if w.rows > 0]
        if not live:
            raise ValueError(
                f"no records ingested from {self.stream_dir} "
                f"(quarantined: {sorted(self.stats.quarantined())})")
        host: dict = {
            "labels": np.concatenate([w.labels for w in live]),
            "offsets": np.concatenate([w.offsets for w in live]),
            "weights": np.concatenate([w.weights for w in live]),
        }
        uids = np.concatenate([w.uids for w in live])
        tag_names = self._tag_names()
        id_tags = {
            t: IdTag.from_raw(np.concatenate([w.tags[t] for w in live]))
            for t in tag_names
        }

        shard_names = sorted(self.feature_shards)
        widths = {
            s: max(w.shards[s][0].shape[1] for w in live)
            for s in shard_names
        }
        for s in shard_names:
            k = widths[s]
            host[("shard", s)] = (
                np.concatenate([
                    np.pad(w.shards[s][0],
                           ((0, 0), (0, k - w.shards[s][0].shape[1])))
                    for w in live
                ]),
                np.concatenate([
                    np.pad(w.shards[s][1],
                           ((0, 0), (0, k - w.shards[s][1].shape[1])))
                    for w in live
                ]),
                len(maps[s]),
            )

        with pstats.stage("stream_assemble"):
            def col(j):
                return jnp.concatenate([w.devs[j] for w in live])

            labels_dev, offsets_dev, weights_dev = col(0), col(1), col(2)
            feature_shards = {}
            for si, s in enumerate(shard_names):
                k = widths[s]
                parts_idx = []
                parts_val = []
                for w in live:
                    di = w.devs[3 + 2 * si]
                    dv = w.devs[3 + 2 * si + 1]
                    pad = ((0, 0), (0, k - di.shape[1]))
                    if pad[1][1]:
                        di = jnp.pad(di, pad)
                        dv = jnp.pad(dv, pad)
                    parts_idx.append(di)
                    parts_val.append(dv)
                feature_shards[s] = SparseFeatures(
                    jnp.concatenate(parts_idx),
                    jnp.concatenate(parts_val),
                    len(maps[s]),
                )
        return GameDataset(
            labels=labels_dev,
            offsets=offsets_dev,
            weights=weights_dev,
            feature_shards=feature_shards,
            id_tags=id_tags,
            uids=uids,
            host=host,
        )

    def _final_stats(
        self, manifest: dict, rows: int, resumed_from, wall: float
    ) -> dict:
        snap = self.stats.snapshot()
        quarantined = snap["quarantined"]
        known = [
            s["records"] for s in manifest["shards"]
            if s["records"] is not None
        ]
        expected = sum(known)
        if len(known) < len(manifest["shards"]) and known:
            # Unscannable shards (records=None) are already corrupt;
            # estimate their rows at the known-shard mean so the
            # fraction still reflects the loss (documented in DATA.md).
            expected += int(
                (len(manifest["shards"]) - len(known))
                * (sum(known) / len(known))
            )
        fraction = (rows / expected) if expected else 0.0
        stats = {
            "manifest_sha256": getattr(self, "manifest_sha256", None),
            "work_dir": self.work_dir,
            "shards_total": len(manifest["shards"]),
            "shards_ingested": len(manifest["shards"]) - len(quarantined),
            "shards_quarantined": len(quarantined),
            "quarantined_paths": sorted(quarantined),
            "rows_ingested": int(rows),
            "expected_rows": int(expected),
            "ingested_fraction": round(min(fraction, 1.0), 6),
            "window_shards": self.window_shards,
            "resumed_from_shard": resumed_from,
            "scan_seconds": round(snap["seconds"].get("scan", 0.0), 4),
            "decode_seconds": round(snap["seconds"].get("decode", 0.0), 4),
            "transfer_seconds": round(
                snap["seconds"].get("transfer", 0.0), 4),
            "wall_seconds": round(wall, 4),
            "rows_per_sec": round(rows / wall, 1) if wall > 0 else None,
        }
        # Process-global retry counters snapshot: zero on a clean run
        # (bench-gated); after injected/real transients the exact
        # recovery count is visible in the summary artifact.
        from photon_tpu.resilience import retry_stats

        stats["retry"] = retry_stats()
        # Health surface: the registry gauges feed /metrics (a
        # --monitor-port scrape sees a degraded ingest live) and the
        # training-summary snapshot. Registry mutations are not gated
        # on the telemetry flag, so the probe works with telemetry off.
        try:
            from photon_tpu import obs

            obs.REGISTRY.gauge("stream_ingested_fraction").set(
                stats["ingested_fraction"])
            obs.REGISTRY.gauge("stream_quarantined_shards").set(
                len(quarantined))
            obs.REGISTRY.gauge("stream_rows_ingested").set(rows)
        except Exception:  # pragma: no cover — telemetry must never
            # alter ingest semantics.
            logger.debug("stream gauges unavailable", exc_info=True)
        return stats
