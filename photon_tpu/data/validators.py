"""Row-level input data sanity checks.

TPU-native counterpart of photon-client data/DataValidators.scala:405 —
per-task validator stacks over (label, features, offset, weight) gated by
VALIDATE_FULL / VALIDATE_SAMPLE / VALIDATE_DISABLED
(DataValidationType; driver default DISABLED, GameDriver.scala:223). The
reference aggregates a boolean per validator over the RDD and throws one
IllegalArgumentException listing every failed check
(sanityCheckData :230-253); here each validator is a vectorized numpy
reduction over the columnar GameDataset, and the error additionally reports
how many rows failed which check.
"""

from __future__ import annotations

import enum

import numpy as np

from photon_tpu.data.dataset import (
    DenseFeatures,
    DualEllFeatures,
    SparseFeatures,
)
from photon_tpu.data.game_data import GameDataset
from photon_tpu.types import TaskType

# MathConst.EPSILON: weights must be significantly above zero
# (DataValidators.validWeight).
_EPSILON = 1e-12

# BinaryClassifier.{positive,negative}ClassLabel (BinaryClassifier.scala:75).
POSITIVE_CLASS_LABEL = 1.0
NEGATIVE_CLASS_LABEL = 0.0


class DataValidationType(enum.Enum):
    """Reference: DataValidationType enum (VALIDATE_FULL/SAMPLE/DISABLED)."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"

    @staticmethod
    def parse(value: "DataValidationType | str") -> "DataValidationType":
        if isinstance(value, DataValidationType):
            return value
        v = value.upper()
        if not v.startswith("VALIDATE_"):
            v = "VALIDATE_" + v
        return DataValidationType(v)


def _finite_mask(x: np.ndarray) -> np.ndarray:
    return np.isfinite(x)


def _feature_finite_rows(features, rows) -> np.ndarray:
    """Per-row all-finite mask for the selected rows of a feature shard
    (finiteFeatures); ``rows`` subsets BEFORE the scan so VALIDATE_SAMPLE
    only reads its 10%."""
    if isinstance(features, DualEllFeatures):
        ok = np.isfinite(np.asarray(features.values)[rows]).all(axis=1)
        tv = np.asarray(features.tail_values)
        bad_tail_rows = np.asarray(features.tail_rows)[~np.isfinite(tv)]
        if bad_tail_rows.size:
            n = features.num_rows
            bad = np.zeros(n, dtype=bool)
            bad[bad_tail_rows] = True
            ok = ok & ~bad[np.arange(n)[rows]]
        return ok
    if isinstance(features, SparseFeatures):
        return np.isfinite(np.asarray(features.values)[rows]).all(axis=1)
    assert isinstance(features, DenseFeatures)
    return np.isfinite(np.asarray(features.x)[rows]).all(axis=1)


def _label_validators(task: TaskType):
    """(mask_fn, message) for the task's label check
    (linear/logistic/poisson RegressionValidators; smoothed hinge uses the
    logistic stack)."""
    if task in (TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        return (
            lambda y: (y == POSITIVE_CLASS_LABEL)
            | (y == NEGATIVE_CLASS_LABEL),
            "Data contains row(s) with non-binary label(s)",
        )
    if task == TaskType.POISSON_REGRESSION:
        return (
            lambda y: np.isfinite(y) & (y >= 0),
            "Data contains row(s) with invalid (-, Inf, or NaN) label(s)",
        )
    return (
        _finite_mask,
        "Data contains row(s) with invalid (+/- Inf or NaN) label(s)",
    )


def sanity_check_data(
    data: GameDataset,
    task: TaskType,
    validation_type: DataValidationType | str = (
        DataValidationType.VALIDATE_FULL),
    *,
    check_labels: bool = True,
    seed: int = 0,
) -> None:
    """Raise ValueError listing every failed check (sanityCheckData).

    ``check_labels=False`` is the scoring-driver variant: scoring inputs may
    carry absent/dummy responses, but features/offsets/weights must still be
    sound. VALIDATE_SAMPLE checks a deterministic 10% row subsample
    (the reference's RDD.sample(fraction = 0.10)).
    """
    validation_type = DataValidationType.parse(validation_type)
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return

    n = data.num_samples
    if validation_type == DataValidationType.VALIDATE_SAMPLE:
        keep = max(n // 10, min(n, 1))
        rows = np.random.default_rng(seed).choice(n, size=keep, replace=False)
    else:
        rows = slice(None)

    labels = np.asarray(data.labels)[rows]
    offsets = np.asarray(data.offsets)[rows]
    weights = np.asarray(data.weights)[rows]

    errors: list[str] = []

    def check(mask: np.ndarray, message: str, slug: str) -> None:
        bad = int((~mask).sum())
        if bad:
            errors.append(f"{message} [{bad} row(s)]")
            _record_failure(slug, bad)

    seen_tables: set[int] = set()
    for shard_id in sorted(data.feature_shards):
        feats = data.feature_shards[shard_id]
        # Aliased shard names can share one feature table; scan it once.
        if id(feats) in seen_tables:
            continue
        seen_tables.add(id(feats))
        check(
            _feature_finite_rows(feats, rows),
            "Data contains row(s) with invalid (+/- Inf or NaN) "
            f"feature(s): {shard_id}",
            f"features:{shard_id}",
        )
    check(
        _finite_mask(offsets),
        "Data contains row(s) with invalid (+/- Inf or NaN) offset(s)",
        "offsets",
    )
    check(
        np.isfinite(weights) & (weights > _EPSILON),
        "Data contains row(s) with invalid (-, 0, Inf, or NaN) weight(s)",
        "weights",
    )
    if check_labels:
        label_mask, message = _label_validators(task)
        check(label_mask(labels), message, "labels")

    if errors:
        raise ValueError("Data Validation failed:\n" + "\n".join(errors))


def _record_failure(slug: str, bad_rows: int) -> None:
    """``health_validation_failures_total{check=...}`` registry counter:
    rejected rows are visible on /metrics (and in the telemetry
    snapshot) BEFORE the raised ValueError kills an ingest cycle.
    Registry mutations are not gated on the telemetry flag — the same
    policy as the streaming-ingest gauges — and a broken telemetry
    import must never alter validation semantics."""
    try:
        from photon_tpu import obs

        obs.REGISTRY.counter(
            "health_validation_failures_total", check=slug
        ).inc(bad_rows)
    except Exception:  # pragma: no cover — validation must still raise
        pass
