"""Feature index maps: feature name/term key <-> dense column index.

TPU-native counterpart of the reference's IndexMap hierarchy
(photon-api index/IndexMap.scala:54, DefaultIndexMap.scala:27,
IdentityIndexMapLoader.scala:24) and the off-heap PalDBIndexMap
(index/PalDBIndexMap.scala:43). The PalDB machinery exists because Spark
executors must each hold the map off-heap; on a TPU host a plain dict (plus
an Arrow-style persisted vocab file) covers the same >200k-feature regime,
so there is one in-memory implementation with save/load.
"""

from __future__ import annotations

import json
from pathlib import Path

from photon_tpu.types import INTERCEPT_KEY, FeatureKey


class IndexMap:
    """Bidirectional feature key <-> index map for one feature shard."""

    def __init__(self, name_to_index: dict[FeatureKey, int]):
        self._forward = dict(name_to_index)
        self._backward = {i: n for n, i in self._forward.items()}
        if len(self._backward) != len(self._forward):
            raise ValueError("index map has duplicate indices")

    # -- reference IndexMap trait surface -----------------------------------

    def get_index(self, name: FeatureKey) -> int | None:
        return self._forward.get(name)

    def get_feature_name(self, index: int) -> FeatureKey | None:
        return self._backward.get(index)

    def __len__(self) -> int:
        return len(self._forward)

    def __contains__(self, name: FeatureKey) -> bool:
        return name in self._forward

    def items(self):
        return self._forward.items()

    @property
    def has_intercept(self) -> bool:
        return INTERCEPT_KEY in self._forward

    @property
    def intercept_index(self) -> int | None:
        return self._forward.get(INTERCEPT_KEY)

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_feature_names(
        names, *, add_intercept: bool = True
    ) -> "IndexMap":
        """Build deterministically from a collection of feature keys.

        Reference: DefaultIndexMapLoader scans the data for distinct keys and
        zips them with indices; we sort for run-to-run determinism, then
        append the intercept last (the reference also treats the intercept as
        just another feature key added during ingest).
        """
        uniq = sorted(set(names) - {INTERCEPT_KEY})
        mapping = {n: i for i, n in enumerate(uniq)}
        if add_intercept:
            mapping[INTERCEPT_KEY] = len(mapping)
        return IndexMap(mapping)

    @staticmethod
    def identity(num_features: int, *, add_intercept: bool = False) -> "IndexMap":
        """Pre-indexed data (libsvm-style): name == str(index).

        Reference: IdentityIndexMapLoader.scala:24.
        """
        mapping: dict[FeatureKey, int] = {str(i): i for i in range(num_features)}
        if add_intercept:
            mapping[INTERCEPT_KEY] = num_features
        return IndexMap(mapping)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self._forward))

    @staticmethod
    def load(path: str | Path) -> "IndexMap":
        return IndexMap(json.loads(Path(path).read_text()))
