"""Feature index maps: feature name/term key <-> dense column index.

TPU-native counterpart of the reference's IndexMap hierarchy
(photon-api index/IndexMap.scala:54, DefaultIndexMap.scala:27,
IdentityIndexMapLoader.scala:24) and the off-heap PalDBIndexMap
(index/PalDBIndexMap.scala:43). The PalDB machinery exists because Spark
executors must each hold the map off-heap; on a TPU host a plain dict (plus
an Arrow-style persisted vocab file) covers the same >200k-feature regime,
so there is one in-memory implementation with save/load.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

import numpy as np

from photon_tpu.types import INTERCEPT_KEY, FeatureKey


class IndexMap:
    """Bidirectional feature key <-> index map for one feature shard."""

    def __init__(self, name_to_index: dict[FeatureKey, int]):
        self._forward = dict(name_to_index)
        self._backward = {i: n for n, i in self._forward.items()}
        if len(self._backward) != len(self._forward):
            raise ValueError("index map has duplicate indices")

    # -- reference IndexMap trait surface -----------------------------------

    def get_index(self, name: FeatureKey) -> int | None:
        return self._forward.get(name)

    def get_feature_name(self, index: int) -> FeatureKey | None:
        return self._backward.get(index)

    def __len__(self) -> int:
        return len(self._forward)

    def __contains__(self, name: FeatureKey) -> bool:
        return name in self._forward

    def items(self):
        return self._forward.items()

    @property
    def has_intercept(self) -> bool:
        return INTERCEPT_KEY in self._forward

    @property
    def intercept_index(self) -> int | None:
        return self._forward.get(INTERCEPT_KEY)

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_feature_names(
        names, *, add_intercept: bool = True
    ) -> "IndexMap":
        """Build deterministically from a collection of feature keys.

        Reference: DefaultIndexMapLoader scans the data for distinct keys and
        zips them with indices; we sort for run-to-run determinism, then
        append the intercept last (the reference also treats the intercept as
        just another feature key added during ingest).
        """
        uniq = sorted(set(names) - {INTERCEPT_KEY})
        mapping = {n: i for i, n in enumerate(uniq)}
        if add_intercept:
            mapping[INTERCEPT_KEY] = len(mapping)
        return IndexMap(mapping)

    @staticmethod
    def identity(num_features: int, *, add_intercept: bool = False) -> "IndexMap":
        """Pre-indexed data (libsvm-style): name == str(index).

        Reference: IdentityIndexMapLoader.scala:24.
        """
        mapping: dict[FeatureKey, int] = {str(i): i for i in range(num_features)}
        if add_intercept:
            mapping[INTERCEPT_KEY] = num_features
        return IndexMap(mapping)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self._forward))

    @staticmethod
    def load(path: str | Path) -> "IndexMap":
        return IndexMap(json.loads(Path(path).read_text()))


class HashedIndexMap:
    """Array-backed feature map for multi-million-feature vocabularies.

    TPU-native counterpart of PalDBIndexMap (photon-client
    index/PalDBIndexMap.scala:43): where the reference sidesteps JVM heap
    limits with partitioned off-heap PalDB stores, this sidesteps Python
    dict overhead (~100+ bytes per entry plus per-string objects) with four
    numpy arrays — sorted 64-bit key hashes, their indices, and an
    offset-indexed UTF-8 name blob (~25 bytes/feature total at typical key
    lengths, a ~10x reduction). Lookup is a binary search plus an exact
    name check against the blob, so hash collisions between a probe and a
    stored key cannot mis-resolve. Persisted as one ``.npz``.

    Same surface as ``IndexMap`` (get_index / get_feature_name / len /
    contains / items / intercept) and the same deterministic index
    assignment (sorted keys, intercept last), so the two are
    interchangeable everywhere a shard map flows.
    """

    def __init__(self, hashes, indices, pos_by_index, offsets, blob):
        self._hashes = hashes  # [n] uint64, sorted
        self._indices = indices  # [n] int64 — index at hash position
        self._pos_by_index = pos_by_index  # [n] int64 — hash position by idx
        self._offsets = offsets  # [n + 1] int64 into blob, hash order
        self._blob = blob  # uint8 utf-8 concatenation, hash order

    @staticmethod
    def _hash(key: str):
        return np.uint64(int.from_bytes(
            hashlib.blake2b(key.encode(), digest_size=8).digest(), "little"
        ))

    @staticmethod
    def from_feature_names(names, *, add_intercept: bool = True):
        uniq = sorted(set(str(n) for n in names) - {INTERCEPT_KEY})
        if add_intercept:
            uniq.append(INTERCEPT_KEY)
        n = len(uniq)
        hashes = np.empty(n, dtype=np.uint64)
        for i, k in enumerate(uniq):
            hashes[i] = HashedIndexMap._hash(k)
        order = np.argsort(hashes, kind="stable")
        hashes = hashes[order]
        if n and (hashes[1:] == hashes[:-1]).any():
            raise ValueError(
                "64-bit hash collision between distinct feature keys; "
                "use the dict-backed IndexMap for this vocabulary"
            )
        indices = order.astype(np.int64)  # uniq position == index
        pos_by_index = np.empty(n, dtype=np.int64)
        pos_by_index[indices] = np.arange(n, dtype=np.int64)
        encoded = [uniq[i].encode() for i in order]
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = np.frombuffer(b"".join(encoded), dtype=np.uint8)
        return HashedIndexMap(hashes, indices, pos_by_index, offsets, blob)

    def _name_at_pos(self, pos: int) -> str:
        lo, hi = int(self._offsets[pos]), int(self._offsets[pos + 1])
        return bytes(self._blob[lo:hi]).decode()

    def get_index(self, name: FeatureKey) -> int | None:
        if self._hashes.size == 0:
            return None
        key = str(name)
        h = self._hash(key)
        pos = int(np.searchsorted(self._hashes, h))
        if pos >= self._hashes.size or self._hashes[pos] != h:
            return None
        # Exact verification against the blob: a probe key that collides
        # with a stored hash must not resolve to the stored key's index.
        if self._name_at_pos(pos) != key:
            return None
        return int(self._indices[pos])

    def get_feature_name(self, index: int) -> FeatureKey | None:
        if not 0 <= index < len(self):
            return None
        return self._name_at_pos(int(self._pos_by_index[index]))

    def __len__(self) -> int:
        return int(self._hashes.size)

    def __contains__(self, name: FeatureKey) -> bool:
        return self.get_index(name) is not None

    def items(self):
        for idx in range(len(self)):
            yield self.get_feature_name(idx), idx

    @property
    def has_intercept(self) -> bool:
        return self.get_index(INTERCEPT_KEY) is not None

    @property
    def intercept_index(self) -> int | None:
        return self.get_index(INTERCEPT_KEY)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str | Path) -> None:
        # Write through a file object so the archive lands at EXACTLY the
        # given path (np.savez_compressed on a string appends ".npz",
        # silently breaking the save/load round trip for other suffixes).
        with open(path, "wb") as f:
            np.savez_compressed(
                f,
                hashes=self._hashes,
                indices=self._indices,
                pos_by_index=self._pos_by_index,
                offsets=self._offsets,
                blob=self._blob,
            )

    @staticmethod
    def load(path: str | Path) -> "HashedIndexMap":
        with np.load(str(path)) as z:
            return HashedIndexMap(
                z["hashes"], z["indices"], z["pos_by_index"],
                z["offsets"], z["blob"],
            )
