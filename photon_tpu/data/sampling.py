"""Down-sampling as deterministic weight masking.

TPU-native counterpart of photon-lib sampling/DownSampler.scala:68,
BinaryClassificationDownSampler.scala:32 and DefaultDownSampler.scala:41.

The reference filters RDD rows; filtering changes shapes, so here dropped
rows get weight 0 instead — aggregations treat them exactly like filtered
rows and every shape stays static (no recompilation per sample draw).

Semantics preserved:
- binary tasks: keep all positives, keep negatives with probability ``rate``
  and rescale surviving negative weights by 1/rate (unbiased gradient);
- other tasks: keep rows uniformly with probability ``rate`` with NO weight
  rescale (DefaultDownSampler uses a plain RDD sample);
- seeded and deterministic (the reference seeds its samplers so lineage
  recomputation reproduces draws; here determinism comes from the explicit
  PRNG key).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import GLMBatch

Array = jax.Array

_POS = 0.5


def downsample_binary_negatives(
    batch: GLMBatch, rate: float, key: Array
) -> GLMBatch:
    """Negative down-sampling with weight rescale
    (BinaryClassificationDownSampler.scala:50-54)."""
    if not 0.0 < rate < 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1): {rate}")
    keep = jax.random.uniform(key, batch.labels.shape) < rate
    is_pos = batch.labels > _POS
    new_w = jnp.where(
        is_pos,
        batch.weights,
        jnp.where(keep, batch.weights / rate, 0.0),
    )
    return batch.with_weights(new_w)


def downsample_uniform(batch: GLMBatch, rate: float, key: Array) -> GLMBatch:
    """Uniform down-sampling, no weight rescale (DefaultDownSampler.scala:
    plain ``RDD.sample``)."""
    if not 0.0 < rate < 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1): {rate}")
    keep = jax.random.uniform(key, batch.labels.shape) < rate
    return batch.with_weights(jnp.where(keep, batch.weights, 0.0))


def downsample(
    batch: GLMBatch, rate: float, key: Array, *, binary: bool
) -> GLMBatch:
    if binary:
        return downsample_binary_negatives(batch, rate, key)
    return downsample_uniform(batch, rate, key)
