"""libsvm text ingest -> GLMBatch.

Counterpart of the reference's deprecated libsvm input path
(photon-client io/deprecated, used by the legacy Driver for the a9a fixture)
— kept first-class here because it is the fastest route to standard GLM
benchmark datasets.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from photon_tpu.data.dataset import GLMBatch, make_sparse_batch


def read_libsvm(
    path: str | Path,
    *,
    num_features: int | None = None,
    add_intercept: bool = True,
    binary_labels_to01: bool = True,
    dtype=np.float32,
) -> GLMBatch:
    """Read a libsvm file into a padded-sparse batch.

    libsvm indices are 1-based; they land at column (idx-1). With
    ``add_intercept`` an all-ones column is appended at index d-1.
    Labels -1/+1 are mapped to 0/1 when ``binary_labels_to01``.
    """
    labels: list[float] = []
    rows: list[list[tuple[int, float]]] = []
    max_idx = -1
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        row = []
        for tok in parts[1:]:
            if tok.startswith("#"):
                break
            k, v = tok.split(":")
            idx = int(k) - 1
            if idx < 0:
                raise ValueError(f"libsvm index must be >= 1, got {k}")
            max_idx = max(max_idx, idx)
            row.append((idx, float(v)))
        rows.append(row)

    base = num_features if num_features is not None else max_idx + 1
    if base <= max_idx:
        raise ValueError(f"num_features={base} but saw index {max_idx}")
    d = base + (1 if add_intercept else 0)
    if add_intercept:
        for row in rows:
            row.append((d - 1, 1.0))

    y = np.asarray(labels, dtype=dtype)
    if binary_labels_to01 and y.min() < 0:
        y = (y > 0).astype(dtype)
    return make_sparse_batch(rows, d, y, dtype=dtype)
