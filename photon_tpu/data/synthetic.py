"""Deterministic synthetic dataset generators for tests and benchmarks.

Counterpart of the reference's SparkTestUtils generators
(photon-test-utils test/SparkTestUtils.scala:85-200: seeded balanced binary /
Poisson / linear datasets) and GameTestUtils (synthetic fixed/random-effect
datasets). All generators are seeded and return host numpy, so tests can
derive oracles before device transfer.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_tpu.data.dataset import GLMBatch, make_dense_batch


def _features(rng: np.random.Generator, n: int, d: int, intercept: bool) -> np.ndarray:
    x = rng.normal(size=(n, d)).astype(np.float64)
    if intercept:
        x[:, -1] = 1.0
    return x


def generate_linear(
    seed: int, n: int, d: int, *, noise: float = 0.1, intercept: bool = True
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (X, y, w_true) for y = Xw + noise."""
    rng = np.random.default_rng(seed)
    x = _features(rng, n, d, intercept)
    w = rng.normal(size=d)
    y = x @ w + noise * rng.normal(size=n)
    return x, y, w


def generate_binary(
    seed: int, n: int, d: int, *, intercept: bool = True
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (X, y01, w_true) with y ~ Bernoulli(sigmoid(Xw))."""
    rng = np.random.default_rng(seed)
    x = _features(rng, n, d, intercept)
    w = rng.normal(size=d)
    p = 1.0 / (1.0 + np.exp(-(x @ w)))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    return x, y, w


def generate_poisson(
    seed: int, n: int, d: int, *, intercept: bool = True, scale: float = 0.5
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (X, counts, w_true) with y ~ Poisson(exp(Xw)); w scaled to
    keep rates benign (the reference's 'numerically benign' variant)."""
    rng = np.random.default_rng(seed)
    x = _features(rng, n, d, intercept)
    w = scale * rng.normal(size=d) / np.sqrt(d)
    y = rng.poisson(np.exp(x @ w)).astype(np.float64)
    return x, y, w


def linear_batch(seed: int, n: int, d: int, **kw) -> GLMBatch:
    x, y, _ = generate_linear(seed, n, d, **kw)
    return make_dense_batch(x, y)


def binary_batch(seed: int, n: int, d: int, **kw) -> GLMBatch:
    x, y, _ = generate_binary(seed, n, d, **kw)
    return make_dense_batch(x, y)


@dataclasses.dataclass(frozen=True)
class SyntheticGameData:
    """A GLMix-style problem: global features + per-entity memberships.

    ``entity_ids[re_type]`` gives each row's entity code for that
    random-effect type; ``re_features[re_type]`` the per-type feature matrix
    (the feature shard that type's per-entity models train on).
    """

    x_global: np.ndarray  # [n, d_global]
    labels: np.ndarray  # [n]
    entity_ids: dict[str, np.ndarray]  # re_type -> [n] int codes
    re_features: dict[str, np.ndarray]  # re_type -> [n, d_re]
    w_global: np.ndarray
    re_models: dict[str, np.ndarray]  # re_type -> [num_entities, d_re]


def generate_game_data(
    seed: int,
    n: int,
    d_global: int,
    re_specs: dict[str, tuple[int, int]],
    *,
    task: str = "linear",
    noise: float = 0.1,
    entity_skew: float = 1.2,
) -> SyntheticGameData:
    """GLMix generator: score = x.w_global + sum_t x_t.w_t[entity_t(row)].

    ``re_specs`` maps re_type -> (num_entities, d_re). Entity membership is
    zipf-ish (power-law sized entities, the regime the reference's
    partitioner bin-packs around, RandomEffectDatasetPartitioner.scala:44).
    """
    rng = np.random.default_rng(seed)
    x_global = _features(rng, n, d_global, True)
    w_global = rng.normal(size=d_global)
    score = x_global @ w_global

    entity_ids: dict[str, np.ndarray] = {}
    re_features: dict[str, np.ndarray] = {}
    re_models: dict[str, np.ndarray] = {}
    for re_type, (num_entities, d_re) in re_specs.items():
        probs = (1.0 / np.arange(1, num_entities + 1) ** entity_skew)
        probs /= probs.sum()
        ids = rng.choice(num_entities, size=n, p=probs)
        xt = _features(rng, n, d_re, True)
        wt = 0.5 * rng.normal(size=(num_entities, d_re))
        entity_ids[re_type] = ids
        re_features[re_type] = xt
        re_models[re_type] = wt
        score = score + np.einsum("nd,nd->n", xt, wt[ids])

    if task == "linear":
        labels = score + noise * rng.normal(size=n)
    elif task == "logistic":
        labels = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-score))).astype(np.float64)
    else:
        raise ValueError(f"unknown task {task!r}")

    return SyntheticGameData(
        x_global=x_global,
        labels=labels,
        entity_ids=entity_ids,
        re_features=re_features,
        w_global=w_global,
        re_models=re_models,
    )
