"""GAME model objects: fixed-effect, random-effect, and composite models.

TPU-native counterpart of photon-api model/FixedEffectModel.scala:33 (a
broadcast GLM + feature shard id), model/RandomEffectModel.scala:36 (an
RDD[(REId, GLM)] + REType + shard; ``score`` :70 joins game data by REId) and
photon-lib model/GameModel.scala:32 (ordered map coordinate id -> sub-model;
scores sum across sub-models via ModelDataScores ``+``).

The RDD-of-models becomes ONE padded coefficient matrix ``[num_entities,
max_sub_dim]`` in entity-subspace coordinates: scoring is a two-level gather
(entity row, subspace slot) fused with the multiply-reduce — the join by REId
is index arithmetic. Entities with no trained model (below the active-data
lower bound) occupy all-zero rows, matching the reference's behavior of
contributing no score for unknown entities.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.random_effect import RandomEffectDataset
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops import precision as precision_mod
from photon_tpu.ops import segment_reduce
from photon_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM + the feature shard it scores against.

    Reference: model/FixedEffectModel.scala:33.
    """

    model: GeneralizedLinearModel
    feature_shard_id: str

    @property
    def task(self) -> TaskType:
        return self.model.task


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """All per-entity GLMs of one random-effect type, as a padded matrix.

    ``coefficients[e, s]`` is entity e's coefficient for its subspace slot s;
    ``proj_all[e, s]`` (host-side) names the original feature id of that slot
    (-1 padding). Reference: model/RandomEffectModel.scala:36.
    """

    coefficients: Array  # [E, S]
    random_effect_type: str
    feature_shard_id: str
    task: TaskType
    proj_all: np.ndarray  # [E, S] original feature ids; -1 pad
    variances: Array | None = None  # [E, S]
    entity_keys: tuple = ()

    @property
    def num_entities(self) -> int:
        return self.coefficients.shape[0]

    @property
    def sub_dim(self) -> int:
        return self.coefficients.shape[1]

    def score_table(
        self, codes: Array, indices: Array, values: Array
    ) -> Array:
        """Scores for rows given subspace-remapped ELL arrays.

        z_i = sum_j values[i, j] * W[codes[i], indices[i, j]] — the
        RandomEffectModel.score join (:70) as a fused two-level gather.
        """
        return score_entity_table(self.coefficients, codes, indices, values)

    def score_dataset(self, dataset: RandomEffectDataset) -> Array:
        if dataset.is_lazy:
            z = _score_via_buckets(self.coefficients, dataset)
            if z is not None:
                return z
            return score_raw_features(
                self.coefficients,
                dataset.score_codes,
                dataset.raw,
                dataset.proj_device(),
            )
        tail = None
        if dataset.score_tail_rows is not None:
            tail = (
                dataset.score_tail_rows,
                dataset.score_tail_indices,
                dataset.score_tail_values,
            )
        return score_entity_table_with_tail(
            self.coefficients,
            dataset.score_codes,
            dataset.score_indices,
            dataset.score_values,
            tail,
            tail_multiplicity=getattr(dataset, "score_tail_mult", None),
        )


@jax.jit
def _bucket_score_add(z, x_slab, row_ids, row_counts, codes, w):
    """Add one bucket's kept-row scores into the canonical [n] vector.

    The slab-side formulation replaces the per-row gather scorer for
    covered rows: z = bmm(slab, W[codes]) reads the materialized slab at
    streaming bandwidth instead of 4-byte-granular row gathers (~17x
    faster measured at 4M rows). Mesh sentinel codes have row_counts 0, so
    their lanes are masked before the scatter.
    """
    r = row_ids.shape[1]
    s = x_slab.shape[-1]
    valid = jnp.arange(r, dtype=jnp.int32)[None, :] < row_counts[:, None]
    we = jnp.take(w, codes, axis=0, mode="clip")[:, :s].astype(x_slab.dtype)
    # f32 accumulator whenever the slab is stored bf16 (ops/precision.py
    # mixed-precision invariant); on f32 slabs this is the plain einsum.
    zb = precision_mod.acc_einsum("brs,bs->br", x_slab, we)
    if segment_reduce.kernel_supported(
        int(np.prod(row_ids.shape)), int(z.shape[0]), zb.dtype
    ):
        # Tiled segment-reduce instead of the serialized scatter-add:
        # valid row ids are distinct within one bucket (each kept row
        # belongs to exactly one entity), so multiplicity is 1.
        return segment_reduce.scatter_add_rows(z, row_ids, zb, valid)
    zb = jnp.where(valid, zb, 0.0)
    return z.at[row_ids].add(zb.astype(z.dtype))


def _score_via_buckets(w: Array, ds: RandomEffectDataset) -> Array | None:
    """Bucket-slab scoring for lazy datasets, or None when not applicable.

    Covered (active kept) rows score from the cached materialized slabs;
    the passive remainder (beyond the reservoir cap / inactive entities)
    scores through the raw-gather path on its row SUBSET — the
    active/passive split of RandomEffectDataset.scala:631-640 as device
    index arithmetic. Applicable when every bucket materialized to a
    subspace-dense slab (the common small-sub_dim case).
    """
    from photon_tpu.data.dataset import DenseFeatures, SparseFeatures

    plans = ds.device_plans()
    blocks = ds.device_blocks()
    for plan, eb in zip(plans, blocks):
        if eb is plan or getattr(eb, "x_indices", True) is not None:
            return None
    _, passive = ds.covered_row_partition()
    inv = ds.score_inv_device()
    if inv is not None and (blocks or passive.size):
        # Scatter-free path (same contract as the fused fit's scorer):
        # bucket score blocks + passive scores concatenate into one flat
        # vector that a single gather distributes — TPU scatter-adds of
        # the same pass measured ~4x slower. Empty datasets (no buckets,
        # no passive rows) fall through to the zeros below.
        slabs = tuple(eb.x_values for eb in blocks)
        codes = tuple(p.entity_codes for p in plans)
        pr = jnp.asarray(passive) if passive.size else None
        return _gather_score(
            w, slabs, codes, inv, pr, ds.score_codes, ds.raw,
            ds.proj_device())
    z = jnp.zeros(ds.num_rows, dtype=w.dtype)
    for plan, eb in zip(plans, blocks):
        z = _bucket_score_add(
            z, eb.x_values, plan.row_ids, plan.row_counts,
            plan.entity_codes, w,
        )
    if passive.size:
        pr = jnp.asarray(passive)
        feats = ds.raw
        if isinstance(feats, DenseFeatures):
            z = _passive_score_set_dense(
                z, pr, ds.score_codes, feats.x, w, ds.proj_device()
            )
        else:
            z = _passive_score_set_sparse(
                z, pr, ds.score_codes, feats.indices, feats.values,
                w, ds.proj_device(),
            )
    return z


def bucket_score_parts(w, slabs, codes):
    """Per-bucket flat [B*cap] score vectors (slab GEMM per bucket).

    bf16-stored slabs accumulate their score reduction in f32
    (ops/precision.py); the parts come back f32 either way."""
    parts = []
    for xv, cd in zip(slabs, codes):
        we = jnp.take(w, cd, axis=0, mode="clip")[:, :xv.shape[-1]].astype(
            xv.dtype)
        parts.append(
            precision_mod.acc_einsum("brs,bs->br", xv, we).reshape(-1)
        )
    return parts


def passive_raw_scores(w, pr, score_codes, feats, proj_dev):
    """Raw-feature scores for the passive row subset ``pr`` (traceable).

    Computed in the COEFFICIENT dtype — passive rows must not round
    through a lower slab dtype on their way into the final gather."""
    from photon_tpu.data.dataset import DenseFeatures

    codes_p = jnp.take(score_codes, pr)
    if isinstance(feats, DenseFeatures):
        zp = _score_raw_dense(
            w, codes_p, jnp.take(feats.x, pr, axis=0), proj_dev)
    else:
        zp = _score_raw_sparse(
            w, codes_p, jnp.take(feats.indices, pr, axis=0),
            jnp.take(feats.values, pr, axis=0), proj_dev,
        )
    return zp.astype(w.dtype)


@jax.jit
def _gather_score(w, slabs, codes, inv, pr, score_codes, feats, proj_dev):
    """ONE gather distributes concatenated bucket + passive scores to
    canonical rows (the scatter-free scoring contract; shared shape with
    fused_fit._re_score)."""
    parts = bucket_score_parts(w, slabs, codes)
    if pr is not None:
        parts.append(passive_raw_scores(w, pr, score_codes, feats,
                                        proj_dev))
    return jnp.take(
        jnp.concatenate(parts), inv, mode="clip").astype(w.dtype)


@jax.jit
def _passive_score_set_dense(z, pr, score_codes, x, w, proj_dev):
    """Scatter passive-row scores into z as ONE program: the row-subset
    gathers, the raw-feature score, and the set-scatter each compile as
    separate half-second eager programs on the tunneled TPU backend
    otherwise."""
    codes_p = jnp.take(score_codes, pr)
    zp = _score_raw_dense(w, codes_p, jnp.take(x, pr, axis=0), proj_dev)
    return z.at[pr].set(zp.astype(z.dtype))


@jax.jit
def _passive_score_set_sparse(z, pr, score_codes, indices, values, w,
                              proj_dev):
    codes_p = jnp.take(score_codes, pr)
    zp = _score_raw_sparse(
        w, codes_p, jnp.take(indices, pr, axis=0),
        jnp.take(values, pr, axis=0), proj_dev,
    )
    return z.at[pr].set(zp.astype(z.dtype))


def score_entity_table(
    w: Array, codes: Array, indices: Array, values: Array
) -> Array:
    """z_i = sum_j values[i,j] * w[codes[i], indices[i,j]] (jit-friendly)."""
    if w.shape[0] == 0:
        # Empty model set (e.g. a partial-retrain dir with no coefficients):
        # every row is an unknown entity and scores 0 (the reference's
        # left-join-with-no-match semantics).
        return jnp.zeros(codes.shape[0], dtype=values.dtype)
    s = w.shape[1]
    n, k = indices.shape
    rows = jnp.take(w, codes, axis=0)  # [n, S]
    from photon_tpu.data.random_effect import DENSE_SUB_DIM_MAX

    # One-hot contraction instead of take_along_axis: batched gathers
    # compile ~40x slower on TPU than the equivalent matmul. Bounded by
    # total one-hot elements so a width-capped table (k << S chosen to
    # bound memory) never inflates by a factor of S.
    if s <= DENSE_SUB_DIM_MAX and n * k * s <= (1 << 28):
        onehot = (
            indices[:, :, None]
            == jnp.arange(s, dtype=indices.dtype)[None, None, :]
        ).astype(rows.dtype)  # [n, k, S]
        picked = jnp.einsum("nks,ns->nk", onehot, rows)
    else:
        picked = jnp.take_along_axis(rows, indices, axis=-1)  # [n, k]
    return precision_mod.acc_sum(
        precision_mod.like_storage(values, picked) * picked, axis=-1
    )


@jax.jit
def _score_raw_dense(w: Array, codes: Array, x: Array, proj: Array) -> Array:
    """Fused dense-shard scoring: scatter each entity's subspace
    coefficients into original feature space ([E, d], small), then one
    gather-dot per row against the HBM-resident raw matrix. No [n, k]
    scoring table ever exists."""
    e, s = w.shape
    d = x.shape[1]
    # -1 projector pads scatter into a spill column that is sliced away.
    pr = jnp.where(proj >= 0, proj, d)
    w_orig = jnp.zeros((e, d + 1), w.dtype)
    w_orig = w_orig.at[
        jnp.arange(e, dtype=jnp.int32)[:, None], pr
    ].set(jnp.where(proj >= 0, w, 0.0))[:, :d]
    # Unseen entities (code -1) drop to zero rows. NOTE: jnp.take wraps
    # negative indices numpy-style BEFORE the out-of-bounds fill check, so
    # -1 must be masked explicitly, not left to mode="fill".
    rows = jnp.take(
        w_orig, jnp.maximum(codes, 0), axis=0, mode="fill", fill_value=0
    )
    rows = jnp.where((codes >= 0)[:, None], rows, 0)
    # Row-axis reduction: f32 accumulator when the table is stored bf16
    # (the serving precision path); identical to the plain sum at f32.
    return precision_mod.acc_sum(x.astype(w.dtype) * rows, axis=-1)


@jax.jit
def _score_raw_sparse(
    w: Array, codes: Array, indices: Array, values: Array, proj: Array
) -> Array:
    """Fused ELL-shard scoring against the owning entity's projector.

    Small subspaces use a one-hot contraction (feature-id match feeding a
    matmul); larger ones fall back to binary search + take_along_axis.
    Batched gather ops compile ~40x slower on TPU than the one-hot einsum,
    so the contraction is the default for every realistic sub_dim.
    """
    from photon_tpu.data.random_effect import DENSE_SUB_DIM_MAX

    s = w.shape[1]
    # Unseen entities (code -1): jnp.take wraps negative indices
    # numpy-style before the fill check, so mask them explicitly.
    safe = jnp.maximum(codes, 0)
    known = codes >= 0
    wrows = jnp.take(w, safe, axis=0, mode="fill", fill_value=0)  # [n, S]
    n, k = indices.shape
    if s <= DENSE_SUB_DIM_MAX and n * k * s <= (1 << 28):
        prows = jnp.take(proj, safe, axis=0)  # [n, S]; -1 pads never match
        onehot = (
            indices[:, :, None] == prows[:, None, :]
        ).astype(values.dtype)  # [n, k, S]
        contrib = jnp.einsum("nk,nks->ns", values, onehot)
        return jnp.where(
            known,
            precision_mod.acc_einsum(
                "ns,ns->n", precision_mod.like_storage(contrib, wrows),
                wrows,
            ),
            0.0,
        )
    sentinel = jnp.iinfo(jnp.int32).max
    psort = jnp.where(proj >= 0, proj, sentinel)  # [E, S], stays ascending
    prows = jnp.take(
        psort, safe, axis=0, mode="fill", fill_value=sentinel
    )  # [n, S]
    slot = jax.vmap(jnp.searchsorted)(prows, indices)
    slot = jnp.minimum(slot, s - 1)
    hit = (jnp.take_along_axis(prows, slot, axis=1) == indices) & known[:, None]
    picked = jnp.take_along_axis(wrows, slot, axis=1)
    return precision_mod.acc_sum(
        jnp.where(
            hit, precision_mod.like_storage(values, picked) * picked, 0.0
        ),
        axis=-1,
    )


def score_raw_features(
    w: Array, codes: Array, feats, proj_dev: Array
) -> Array:
    """Lazy-layout scoring straight off the raw feature arrays.

    The materialized equivalent (``score_entity_table``) reads a
    pre-remapped [n, k] table; this fuses the remap into the score so the
    only per-row state in HBM is the raw shard itself (shared with every
    other consumer). ``proj_dev`` is the device [E, S] projector matrix.
    """
    from photon_tpu.data.dataset import DenseFeatures, SparseFeatures

    if w.shape[0] == 0:
        n = (
            feats.x.shape[0]
            if isinstance(feats, DenseFeatures)
            else feats.indices.shape[0]
        )
        return jnp.zeros(n, dtype=w.dtype)
    if isinstance(feats, DenseFeatures):
        return _score_raw_dense(w, codes, feats.x, proj_dev)
    if isinstance(feats, SparseFeatures):
        return _score_raw_sparse(
            w, codes, feats.indices, feats.values, proj_dev
        )
    raise TypeError(
        f"lazy scoring expects Dense or Sparse features, got "
        f"{type(feats).__name__}"
    )


def score_entity_table_with_tail(
    w: Array,
    codes: Array,
    indices: Array,
    values: Array,
    tail: tuple[Array, Array, Array] | None,
    tail_multiplicity: int | None = None,
) -> Array:
    """score_entity_table plus a width-capped table's COO overflow tail
    (rows sorted ascending; see RandomEffectDataConfiguration
    .score_table_width_cap).

    ``tail_multiplicity`` is the host-computed max tail entries per row
    (RandomEffectDataset.score_tail_mult): with it, the sorted tail
    reduction runs through the tiled Pallas segment-reduce where
    supported instead of the XLA scatter lowering of ``segment_sum``.
    """
    base = score_entity_table(w, codes, indices, values)
    if tail is None or w.shape[0] == 0:
        return base
    tr, ti, tv = tail
    # Flattened 1-D take instead of a two-vector gather (compile cost).
    flat = jnp.take(codes, tr) * w.shape[1] + ti
    picked = jnp.take(w.reshape(-1), flat)
    contrib = precision_mod.like_storage(tv, picked) * picked
    n = base.shape[0]
    if tail_multiplicity is not None and segment_reduce.kernel_supported(
        int(tr.shape[0]), int(n), contrib.dtype
    ):
        summed = segment_reduce.sorted_segment_sum(
            contrib, tr.astype(jnp.int32), n,
            multiplicity=int(tail_multiplicity),
            site="segment_reduce/score_tail",
        )
    else:
        if contrib.dtype == jnp.bfloat16:
            contrib = contrib.astype(jnp.float32)  # f32 accumulator
        summed = jax.ops.segment_sum(
            contrib, tr, num_segments=n, indices_are_sorted=True
        )
    return base + summed.astype(base.dtype)


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered composite of coordinate sub-models (model/GameModel.scala:32).

    Iteration order is the coordinate update sequence; total score is the sum
    of per-coordinate scores (DataScores ``+`` algebra).
    """

    models: dict[str, FixedEffectModel | RandomEffectModel]

    def __getitem__(self, coordinate_id: str):
        return self.models[coordinate_id]

    def __contains__(self, coordinate_id: str) -> bool:
        return coordinate_id in self.models

    def items(self):
        return self.models.items()

    def updated(self, coordinate_id: str, model) -> "GameModel":
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(new)

    @property
    def task(self) -> TaskType:
        for m in self.models.values():
            return m.task
        raise ValueError("empty GAME model")


def remap_random_effect_model(
    model: RandomEffectModel,
    *,
    entity_keys: tuple,
    proj_all: np.ndarray,
) -> RandomEffectModel:
    """Re-layout a RandomEffectModel onto a different dataset layout.

    Used when an externally loaded model (warm start / partial retrain,
    GameTrainingDriver.scala:395-404) meets a freshly built
    RandomEffectDataset whose entity vocabulary and per-entity subspace slot
    order differ from the model's. Coefficients are routed by (entity key,
    original feature id); entities/features absent from the new layout are
    dropped, new ones start at zero — the fullOuterJoin warm-start semantics
    of RandomEffectCoordinate.scala:200.
    """
    e_new, s_new = proj_all.shape
    w_old = np.asarray(model.coefficients)
    v_old = None if model.variances is None else np.asarray(model.variances)
    dtype = w_old.dtype
    w = np.zeros((e_new, s_new), dtype=dtype)
    v = None if v_old is None else np.zeros((e_new, s_new), dtype=dtype)
    old_vocab = {str(k): i for i, k in enumerate(model.entity_keys)}
    n_hit = sum(1 for k in entity_keys if str(k) in old_vocab)
    if entity_keys and model.entity_keys and n_hit == 0:
        import warnings

        warnings.warn(
            f"remap_random_effect_model({model.random_effect_type!r}): none "
            f"of {len(entity_keys)} dataset entities match the "
            f"{len(model.entity_keys)} model entities — the warm start is "
            "effectively a zero model",
            stacklevel=2,
        )
    max_feat = 0
    if proj_all.size:
        max_feat = max(max_feat, int(proj_all.max(initial=0)))
    if model.proj_all.size:
        max_feat = max(max_feat, int(model.proj_all.max(initial=0)))
    lut = np.full(max_feat + 1, -1, dtype=np.int64)
    for en, key in enumerate(entity_keys):
        eo = old_vocab.get(str(key))
        if eo is None:
            continue
        old_p = model.proj_all[eo]
        old_valid = old_p >= 0
        lut[old_p[old_valid]] = np.nonzero(old_valid)[0]
        new_p = proj_all[en]
        new_valid = new_p >= 0
        src = lut[new_p[new_valid]]
        dst = np.nonzero(new_valid)[0]
        hit = src >= 0
        w[en, dst[hit]] = w_old[eo, src[hit]]
        if v is not None:
            v[en, dst[hit]] = v_old[eo, src[hit]]
        lut[old_p[old_valid]] = -1
    return dataclasses.replace(
        model,
        coefficients=jnp.asarray(w),
        variances=None if v is None else jnp.asarray(v),
        proj_all=proj_all,
        entity_keys=entity_keys,
    )


@dataclasses.dataclass(frozen=True)
class SparseEntityCoefficients:
    """One entity's model in original-space sparse form: parallel arrays of
    (original feature id, mean[, variance]) — the shape of one per-entity
    BayesianLinearModelAvro record."""

    feature_indices: np.ndarray  # [nnz] original feature ids
    means: np.ndarray  # [nnz]
    variances: np.ndarray | None  # [nnz]


def random_effect_model_to_glms(
    model: RandomEffectModel,
) -> dict[str, SparseEntityCoefficients]:
    """Expand the padded matrix into per-entity original-space sparse
    coefficients (for model export parity with the reference's per-entity
    BayesianLinearModelAvro records). The subspace slot order is compacted
    away; ``feature_indices`` names each mean's original feature id."""
    out: dict[str, SparseEntityCoefficients] = {}
    w = np.asarray(model.coefficients)
    v = None if model.variances is None else np.asarray(model.variances)
    for e in range(model.num_entities):
        valid = model.proj_all[e] >= 0
        if not valid.any():
            continue
        key = model.entity_keys[e] if model.entity_keys else str(e)
        out[str(key)] = SparseEntityCoefficients(
            feature_indices=model.proj_all[e, valid].astype(np.int64),
            means=w[e, valid],
            variances=None if v is None else v[e, valid],
        )
    return out
