"""GLM model objects: coefficients and task-typed generalized linear models.

TPU-native counterpart of the reference's model layer:
``Coefficients`` (photon-lib model/Coefficients.scala:31, computeScore :51),
``GeneralizedLinearModel`` and its task-specific subclasses
(photon-api supervised/model/GeneralizedLinearModel.scala:33,
LogisticRegressionModel.scala:31 — mean = sigmoid,
PoissonRegressionModel — mean = exp, LinearRegressionModel,
SmoothedHingeLossLinearSVMModel; ``BinaryClassifier`` trait :23).

The Scala subclass hierarchy collapses to one pytree dataclass carrying a
``TaskType``: the link function and loss are looked up from the task, and the
model flows through jit as data.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import Features, GLMBatch
from photon_tpu.ops import losses as losses_mod
from photon_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    """Means + optional variances of model coefficients.

    Reference: model/Coefficients.scala:31. Variances appear when variance
    computation is enabled (SIMPLE/FULL) and feed incremental training's
    Gaussian prior.
    """

    means: Array  # [d]
    variances: Array | None = None  # [d]

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, features: Features) -> Array:
        """x . w for a batch of rows (Coefficients.computeScore :51)."""
        return features.matvec(self.means)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros(dim, dtype=dtype))

    def padded_to(self, dim: int) -> "Coefficients":
        """Zero-pad means (and variances, if present) up to ``dim``.

        The bridge from logical-d models into a column-sharded solve's
        device-count-padded coefficient space: zero means are inert as a
        warm start, and zero variances mark "absent from the prior" for
        ``inverse_prior_variances``'s l2 fallback.
        """
        pad = dim - self.means.shape[-1]
        if pad <= 0:
            return self
        return Coefficients(
            means=jnp.pad(self.means, (0, pad)),
            variances=(
                None if self.variances is None
                else jnp.pad(self.variances, (0, pad))
            ),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A task-typed GLM.

    ``score`` is the linear margin; ``mean`` applies the inverse link
    (sigmoid / identity / exp); ``predict_class`` thresholds binary tasks
    (BinaryClassifier.predictClassWithThreshold semantics).
    """

    coefficients: Coefficients
    task: TaskType = dataclasses.field(metadata=dict(static=True))

    @property
    def loss(self) -> losses_mod.PointwiseLoss:
        return losses_mod.get_loss(self.task)

    def compute_score(self, features: Features, offsets: Array | None = None) -> Array:
        z = self.coefficients.compute_score(features)
        return z if offsets is None else z + offsets

    def compute_mean(self, features: Features, offsets: Array | None = None) -> Array:
        """E[y | x] via the inverse link (GeneralizedLinearModel.computeMean)."""
        return self.loss.mean(self.compute_score(features, offsets))

    def predict_class(
        self, features: Features, offsets: Array | None = None, threshold: float = 0.5
    ) -> Array:
        if self.task not in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        ):
            raise ValueError(f"{self.task} is not a binary classification task")
        if self.task == TaskType.LOGISTIC_REGRESSION:
            return (self.compute_mean(features, offsets) > threshold).astype(jnp.int32)
        # SVM: sign of the margin
        return (self.compute_score(features, offsets) > 0.0).astype(jnp.int32)

    def update_coefficients(self, coefficients: Coefficients) -> "GeneralizedLinearModel":
        """Reference: GeneralizedLinearModel.updateCoefficients."""
        return dataclasses.replace(self, coefficients=coefficients)


def logistic_regression(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, TaskType.LOGISTIC_REGRESSION)


def linear_regression(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, TaskType.LINEAR_REGRESSION)


def poisson_regression(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, TaskType.POISSON_REGRESSION)


def smoothed_hinge_svm(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(
        coefficients, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)
