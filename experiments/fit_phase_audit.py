"""Audit 2: break the first fit's wall-clock into phases (post compile-fix).

Blocks after every coordinate update in the first fit so the stamps show
which program's FIRST execution (load) is slow on the tunneled backend.
Run this with the machine otherwise idle — concurrent CPU load (e.g. a
pytest run) inflates the tunnel client's dispatch path badly.
"""

import logging
import sys
import time

logging.basicConfig(level=logging.INFO)

sys.path.insert(0, "/root/repo")
import numpy as np  # noqa: E402

import bench  # noqa: E402

T0 = time.perf_counter()


def stamp(label):
    print(f"[{time.perf_counter() - T0:8.2f}s] {label}", flush=True)


import jax  # noqa: E402

import photon_tpu.estimators.game_estimator as ge  # noqa: E402
from photon_tpu.algorithm import random_effect as re_mod  # noqa: E402
from photon_tpu.algorithm import coordinate as fe_mod  # noqa: E402

orig_prime = ge.GameEstimator._prime_compilations


def prime(self, *a, **k):
    stamp("prime start")
    orig_prime(self, *a, **k)
    stamp("prime done")


ge.GameEstimator._prime_compilations = prime

BLOCKING = [True]

orig_re_train = re_mod.RandomEffectCoordinate.train


def re_train(self, *a, **k):
    t = time.perf_counter()
    out = orig_re_train(self, *a, **k)
    if BLOCKING[0]:
        np.asarray(out[0].coefficients).sum()
        stamp(
            f"re train {self.dataset.config.random_effect_type} "
            f"blocked in {time.perf_counter() - t:.2f}s"
        )
    return out


re_mod.RandomEffectCoordinate.train = re_train

orig_fe_train = fe_mod.FixedEffectCoordinate.train


def fe_train(self, *a, **k):
    t = time.perf_counter()
    out = orig_fe_train(self, *a, **k)
    if BLOCKING[0]:
        np.asarray(out[0].coefficients.means).sum()
        stamp(f"fe train blocked in {time.perf_counter() - t:.2f}s")
    return out


fe_mod.FixedEffectCoordinate.train = fe_train

orig_re_score = re_mod.RandomEffectCoordinate.score


def re_score(self, model):
    t = time.perf_counter()
    out = orig_re_score(self, model)
    if BLOCKING[0]:
        jax.block_until_ready(out)
        np.asarray(out[:1])
        stamp(
            f"re score {self.dataset.config.random_effect_type} "
            f"blocked in {time.perf_counter() - t:.2f}s"
        )
    return out


re_mod.RandomEffectCoordinate.score = re_score

stamp("build_data start")
data = bench.build_data("logistic")
stamp("build_data done")
est = bench.build_estimator("logistic")
datasets, _ = est.prepare(data)
stamp("prepare done")


def fit_blocking():
    r = est.fit(data)[0]
    for m in r.model.models.values():
        c = (m.coefficients if hasattr(m, "coefficients")
             else m.model.coefficients.means)
        float(np.asarray(c).sum())
    return r


fit_blocking()
stamp("first fit done")
BLOCKING[0] = False
for i in range(2):
    t = time.perf_counter()
    fit_blocking()
    stamp(f"steady fit {i} done in {time.perf_counter() - t:.2f}s")
