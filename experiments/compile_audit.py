"""Audit: where do the bench's 191 cold-compile seconds go?

Runs the bench's logistic variant once with jax_log_compiles plus wall-clock
stamps around prepare / first fit, and a per-program compile-time summary
parsed from JAX's logging. Round-5 instrumentation; not part of the package.
"""

import logging
import re
import sys
import time

import jax

jax.config.update("jax_log_compiles", True)

sys.path.insert(0, "/root/repo")
import bench  # noqa: E402


class CompileLog(logging.Handler):
    def __init__(self):
        super().__init__()
        self.events = []  # (t, seconds, name)

    def emit(self, record):
        msg = record.getMessage()
        m = re.search(r"Finished XLA compilation of (.+?) in (\d+\.\d+) sec",
                      msg)
        if m:
            self.events.append(
                (time.perf_counter(), float(m.group(2)), m.group(1)))
            print(f"[{time.perf_counter() - T0:8.2f}s] compiled "
                  f"{m.group(1)[:70]} in {m.group(2)}s", flush=True)


handler = CompileLog()
logging.getLogger("jax._src.interpreters.pxla").addHandler(handler)
logging.getLogger("jax._src.dispatch").addHandler(handler)
logging.getLogger("jax").addHandler(handler)
logging.getLogger("jax").setLevel(logging.DEBUG)

T0 = time.perf_counter()


def stamp(label):
    print(f"[{time.perf_counter() - T0:8.2f}s] {label}", flush=True)


stamp("build_data start")
data = bench.build_data("logistic")
stamp("build_data done")
est = bench.build_estimator("logistic")
datasets, _ = est.prepare(data)
stamp("prepare done")

import numpy as np  # noqa: E402

r = est.fit(data)[0]
for m in r.model.models.values():
    c = (m.coefficients if hasattr(m, "coefficients")
         else m.model.coefficients.means)
    float(np.asarray(c).sum())
stamp("first fit done")

total_compile = sum(s for _, s, _ in handler.events)
print(f"\nprograms compiled: {len(handler.events)}; "
      f"sum of compile seconds: {total_compile:.1f} "
      f"(wall inside first fit differs if concurrent)")
for t, s, name in sorted(handler.events, key=lambda e: -e[1])[:25]:
    print(f"  {s:8.2f}s  {name[:90]}")
