"""Probe: where the tunneled backend's per-program first-execution tax
comes from.

Three program families, each compiled AOT then timed on first and second
execution (first minus second = hidden load/warmup cost):
  trivial  — one fused elementwise program;
  looped   — fori_loop of matmuls (sequential structure, no vmap);
  newtonish — vmap over B entities of while_loop(fori_loop CG) on tiny
              shapes, structurally like the production bucket solver.

Vary B to see whether the tax scales with device work or program
structure. Run idle.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def time_one(name, f, x):
    t0 = time.perf_counter()
    c = jax.jit(f).lower(x).compile()
    t_c = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(c(x)[0] if isinstance(c(x), tuple) else c(x))
    t_1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(c(x)[0] if isinstance(c(x), tuple) else c(x))
    t_2 = time.perf_counter() - t0
    print(f"{name:28s} compile {t_c:7.2f}s  first {t_1:7.2f}s  "
          f"second {t_2:7.3f}s", flush=True)


def trivial(x):
    return jnp.tanh(x * 2.0 + 1.0).sum()


def looped(x):
    def body(_, s):
        return jnp.tanh(s @ s * 1e-3)

    return lax.fori_loop(0, 30, body, x)


def make_newtonish(s=17, r=64):
    def solve_one(xe, ye):
        w0 = jnp.zeros(s, xe.dtype)

        def cg(h, b):
            def step(_, st):
                xx, rr, p, rs = st
                hp = h @ p
                a = rs / jnp.maximum(p @ hp, 1e-30)
                xx = xx + a * p
                rr = rr - a * hp
                rs2 = rr @ rr
                return xx, rr, rs2 / jnp.maximum(rs, 1e-30) * p + rr, rs2

            st = (jnp.zeros_like(b), b, b, b @ b)
            return lax.fori_loop(0, s, step, st)[0]

        def cond(st):
            return st[2] < 8

        def body(st):
            w, f, it = st
            z = xe @ w
            sig = jax.nn.sigmoid(z)
            g = xe.T @ (sig - ye)
            h = xe.T @ (xe * (sig * (1 - sig))[:, None]) + jnp.eye(s)
            d = cg(h, -g)
            ts = 0.5 ** jnp.arange(8.0)
            zt = z[None] + ts[:, None] * (xe @ d)[None]
            ft = jnp.sum(jnp.logaddexp(0.0, zt) - zt * ye[None], axis=1)
            best = jnp.argmax(ft <= f)
            w = w + ts[best] * d
            return w, ft[best], it + 1

        w, f, _ = lax.while_loop(
            cond, body, (w0, jnp.asarray(1e30, xe.dtype),
                         jnp.asarray(0, jnp.int32)))
        return w

    def f(args):
        xs, ys = args
        return jax.vmap(solve_one)(xs, ys)

    return f


def main():
    key = jax.random.PRNGKey(0)
    time_one("trivial [4M]", trivial, jnp.ones((4_000_000,), jnp.float32))
    time_one("looped [512,512]x30", looped,
             jax.random.normal(key, (512, 512), jnp.float32))
    for b in (1_000, 100_000):
        xs = jax.random.normal(key, (b, 64, 17), jnp.float32)
        ys = (jax.random.uniform(key, (b, 64)) > 0.5).astype(jnp.float32)
        time_one(f"newtonish B={b}", make_newtonish(), (xs, ys))


if __name__ == "__main__":
    main()
