"""Probe: split the production bucket-solver's cost into trace (lower) /
XLA compile / first execution, on the real bench shapes.

If lowering dominates, the compile blowup is Python tracing, not XLA.
"""

import sys
import time

sys.path.insert(0, "/root/repo")
import numpy as np  # noqa: E402

import bench  # noqa: E402


def stamp(label, t0):
    print(f"{label}: {time.perf_counter() - t0:.2f}s", flush=True)


data = bench.build_data("logistic")
est = bench.build_estimator("logistic")
t0 = time.perf_counter()
datasets, _ = est.prepare(data)
stamp("prepare", t0)

coords = est._build_coordinates(
    datasets, {}, {}, logical_rows=data.num_samples)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from photon_tpu.algorithm import random_effect as re_mod  # noqa: E402

coord = coords["per-user"].inner if hasattr(coords["per-user"], "inner") \
    else coords["per-user"]
ds = coord.dataset

t0 = time.perf_counter()
blocks = ds.device_blocks()
stamp("device_blocks (materialize compile+run)", t0)

dtype = jnp.dtype(ds.dtype)
residuals = jnp.zeros(ds.num_rows, dtype)
w0_full = jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)

for i, block in enumerate(blocks):
    shape = tuple(np.asarray(block.row_ids).shape) if hasattr(
        block, "row_ids") else "?"
    print(f"-- bucket {i}: rows shape {shape}, sub_dim {block.sub_dim}",
          flush=True)
    # Reproduce _dispatch_block's call but staged: lower, compile, run.
    kwargs = dict(
        sub_dim=block.sub_dim,
        task=coord.task,
        opt_config=coord.config.optimizer,
        use_owlqn=False,
        variance_computation=coord.config.variance_computation,
        direct=False,
        newton=True,
    )
    args = (
        block, residuals, None, None, w0_full,
        np.asarray(0.0, dtype=dtype), np.asarray(1.0, dtype=dtype),
        np.asarray(1.0, dtype=dtype), None, w0_full, None,
    )
    t0 = time.perf_counter()
    lowered = re_mod._solve_block.lower(*args, **kwargs)
    stamp("   lower (trace)", t0)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    stamp("   XLA compile", t0)
    t0 = time.perf_counter()
    out = compiled(*args)
    np.asarray(out[0]).sum()
    stamp("   first exec (AOT-compiled)", t0)
